"""NLP tests (≡ deeplearning4j-nlp test suite: Word2VecTests,
ParagraphVectorsTest, tokenizer tests — scaled to a synthetic corpus
since the environment has no egress for real text datasets)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, FastText, Glove,
                                    LabelledDocument, NGramTokenizerFactory,
                                    ParagraphVectors, Word2Vec, build_vocab,
                                    char_ngrams)


def synthetic_corpus(n=300, seed=0):
    """Two topic clusters: words within a topic co-occur, across don't."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tech = ["cpu", "gpu", "ram", "disk", "cache", "bus"]
    sents = []
    for _ in range(n):
        topic = animals if rng.random() < 0.5 else tech
        sents.append(" ".join(rng.choice(topic, size=6)))
    return sents


class TestTokenization:
    def test_default_tokenizer(self):
        tok = DefaultTokenizerFactory().create("hello world foo")
        assert tok.countTokens() == 3
        assert tok.getTokens() == ["hello", "world", "foo"]
        assert tok.hasMoreTokens()
        assert tok.nextToken() == "hello"

    def test_common_preprocessor(self):
        fac = DefaultTokenizerFactory()
        fac.setTokenPreProcessor(CommonPreprocessor())
        toks = fac.create("Hello, World! 123 test.").getTokens()
        assert toks == ["hello", "world", "test"]

    def test_ngram_tokenizer(self):
        fac = NGramTokenizerFactory(minN=1, maxN=2)
        toks = fac.create("a b c").getTokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestVocab:
    def test_build_and_query(self):
        v = build_vocab([["a", "b", "a"], ["a", "c"]], min_count=1)
        assert v.numWords() == 3
        assert v.wordFrequency("a") == 3
        assert v.containsWord("b") and not v.containsWord("z")
        assert v.wordAtIndex(v.indexOf("c")) == "c"
        assert v.totalWordOccurrences() == 5

    def test_min_count_prunes(self):
        v = build_vocab([["a", "b", "a"]], min_count=2)
        assert v.words() == ["a"]

    def test_negative_table_normalized(self):
        v = build_vocab([["a", "b", "a"]], min_count=1)
        p = v.negative_table()
        assert p.shape == (2,) and abs(p.sum() - 1.0) < 1e-9


class TestWord2Vec:
    @pytest.fixture(scope="class")
    def model(self):
        return (Word2Vec.Builder()
                .minWordFrequency(1).layerSize(32).seed(7).windowSize(3)
                .epochs(3).negativeSample(5).sampling(0)
                .learningRate(0.01).batchSize(512)
                .iterate(CollectionSentenceIterator(synthetic_corpus()))
                .tokenizerFactory(DefaultTokenizerFactory())
                .build().fit())

    def test_vocab(self, model):
        assert model.vocabSize() == 12
        assert model.hasWord("cat") and model.hasWord("gpu")

    def test_vector_shape(self, model):
        assert model.getWordVector("cat").shape == (32,)

    def test_topic_clustering(self, model):
        # within-topic similarity beats cross-topic
        assert model.similarity("cat", "dog") > model.similarity("cat", "gpu")
        assert model.similarity("cpu", "ram") > model.similarity("cpu", "cow")

    def test_words_nearest(self, model):
        near = model.wordsNearest("cat", topN=5)
        assert "cat" not in near
        animals = {"dog", "horse", "cow", "sheep", "goat"}
        assert len(set(near[:3]) & animals) >= 2


class TestParagraphVectors:
    def test_dbow_labels_cluster(self):
        docs = []
        for i, s in enumerate(synthetic_corpus(60, seed=1)):
            topic = "animals" if s.split()[0] in {
                "cat", "dog", "horse", "cow", "sheep", "goat"} else "tech"
            docs.append(LabelledDocument(s, f"{topic}_{i}"))
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(24).seed(3).epochs(3)
              .sampling(0).batchSize(256)
              .iterate(docs).build().fit())
        assert pv.getLabelVector(docs[0].labels[0]).shape == (24,)
        v = pv.inferVector("cat dog horse cow")
        assert v.shape == (24,) and np.isfinite(v).all()

    def test_dm_runs(self):
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(16).seed(3).epochs(2)
              .sampling(0).batchSize(128)
              .sequenceLearningAlgorithm("DM")
              .iterate(synthetic_corpus(30)).build().fit())
        assert pv.params["docs"].shape == (30, 16)

    def test_nearest_labels(self):
        docs = [("animal_doc", "cat dog cow horse sheep goat cat dog"),
                ("tech_doc", "cpu gpu ram disk cache bus cpu gpu")] * 5
        docs = [(f"{lab}_{i}", txt) for i, (lab, txt) in enumerate(docs)]
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(16).seed(5).epochs(10)
              .sampling(0).batchSize(128).iterate(docs).build().fit())
        labs = pv.nearestLabels("cat dog sheep", topN=3)
        assert len(labs) == 3


class TestGlove:
    def test_topic_clustering(self):
        g = (Glove.Builder()
             .minWordFrequency(1).layerSize(24).seed(11).windowSize(4)
             .epochs(40).learningRate(0.05)
             .iterate(synthetic_corpus(200, seed=2)).build().fit())
        assert g.getWordVector("cat").shape == (24,)
        assert g.similarity("cat", "dog") > g.similarity("cat", "gpu")


class TestFastText:
    def test_char_ngrams(self):
        grams = char_ngrams("cat", 3, 4)
        assert "<ca" in grams and "at>" in grams and "<cat" in grams

    def test_train_and_oov(self):
        ft = (FastText.Builder()
              .minWordFrequency(1).layerSize(16).seed(9).windowSize(3)
              .epochs(2).sampling(0).batchSize(256)
              .iterate(synthetic_corpus(80)).build().fit())
        assert ft.getWordVector("cat").shape == (16,)
        # OOV word built purely from shared subword n-grams
        oov = ft.getWordVector("cats")
        assert oov.shape == (16,) and np.isfinite(oov).all()
        assert ft.similarity("cat", "dog") == ft.similarity("dog", "cat")


class TestWordVectorSerializer:
    """Round-3 VERDICT item 8 (≡ deeplearning4j-nlp ::
    loader.WordVectorSerializer): word2vec C text + binary round-trips."""

    def _vectors(self):
        from deeplearning4j_tpu.nlp import StaticWordVectors
        rng = np.random.default_rng(3)
        words = ["the", "quick", "brown", "fox", "naïve"]  # incl. non-ASCII
        table = rng.standard_normal((5, 8)).astype(np.float32)
        return StaticWordVectors(table, words)

    def test_text_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp import WordVectorSerializer
        v = self._vectors()
        p = str(tmp_path / "vecs.txt")
        WordVectorSerializer.writeWord2VecModel(v, p, binary=False)
        back = WordVectorSerializer.readWord2VecModel(p)
        assert back.vocabSize() == 5
        for w in ("quick", "naïve"):
            np.testing.assert_allclose(back.getWordVector(w),
                                       v.getWordVector(w), atol=1e-5)

    def test_binary_roundtrip_exact(self, tmp_path):
        from deeplearning4j_tpu.nlp import WordVectorSerializer
        v = self._vectors()
        p = str(tmp_path / "vecs.bin")
        WordVectorSerializer.writeWord2VecModel(v, p, binary=True)
        back = WordVectorSerializer.readWord2VecModel(p)
        # binary is bit-exact
        np.testing.assert_array_equal(back._table(), v._table())
        assert [back.vocab.wordAtIndex(i) for i in range(5)] == \
            [v.vocab.wordAtIndex(i) for i in range(5)]

    def test_format_autodetect(self, tmp_path):
        from deeplearning4j_tpu.nlp import WordVectorSerializer
        v = self._vectors()
        pt, pb = str(tmp_path / "t.txt"), str(tmp_path / "b.bin")
        WordVectorSerializer.writeWord2VecModel(v, pt, binary=False)
        WordVectorSerializer.writeWord2VecModel(v, pb, binary=True)
        assert not WordVectorSerializer._is_binary(pt)
        assert WordVectorSerializer._is_binary(pb)

    def test_trained_word2vec_exports(self, tmp_path):
        from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                            WordVectorSerializer, Word2Vec)
        sents = ["the cat sat on the mat", "the dog sat on the log"] * 4
        w2v = (Word2Vec.Builder().minWordFrequency(1).layerSize(12)
               .seed(1).epochs(1)
               .iterate(CollectionSentenceIterator(sents)).build())
        w2v.fit()
        p = str(tmp_path / "trained.txt")
        WordVectorSerializer.writeWord2VecModel(w2v, p)
        back = WordVectorSerializer.loadStaticModel(p)
        assert back.hasWord("cat")
        np.testing.assert_allclose(back.getWordVector("cat"),
                                   w2v.getWordVector("cat"), atol=1e-5)

    def test_embedding_layer_bridge(self, tmp_path):
        """Loaded static vectors initialise an EmbeddingLayer whose lookups
        reproduce the stored vectors."""
        from deeplearning4j_tpu.nlp import WordVectorSerializer
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (EmbeddingLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        v = self._vectors()
        p = str(tmp_path / "e.txt")
        WordVectorSerializer.writeWord2VecModel(v, p)
        back = WordVectorSerializer.readWord2VecModel(p)
        w = WordVectorSerializer.embeddingLayerWeights(back, extra_tokens=2)
        assert w.shape == (7, 8)
        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(EmbeddingLayer(nIn=7, nOut=8))
                .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                                   activation="softmax"))
                .setInputType(InputType.feedForward(7)).build())
        net = MultiLayerNetwork(conf).init()
        net._params["0"]["W"] = jnp.asarray(w)
        idx = np.array([back.vocab.indexOf("fox")], np.int32)
        emb = net.feedForward(idx)[0].numpy()[0]
        np.testing.assert_allclose(emb, back.getWordVector("fox"), atol=1e-5)


class TestCnnSentenceIterator:
    def _wv(self):
        from deeplearning4j_tpu.nlp import StaticWordVectors
        words = ["good", "bad", "movie", "great", "awful", "unk"]
        rng = np.random.RandomState(0)
        return StaticWordVectors(rng.randn(6, 8).astype(np.float32), words)

    def _provider(self):
        from deeplearning4j_tpu.nlp import CollectionLabeledSentenceProvider
        return CollectionLabeledSentenceProvider(
            ["good movie", "great movie", "awful movie", "bad bad movie"],
            ["pos", "pos", "neg", "neg"])

    def test_cnn2d_layout_and_mask(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        wv = self._wv()
        it = (CnnSentenceDataSetIterator.Builder("CNN2D")
              .sentenceProvider(self._provider()).wordVectors(wv)
              .minibatchSize(4).maxSentenceLength(16).build())
        ds = it.next()
        assert ds.features.shape == (4, 1, 3, 8)   # longest sentence: 3
        assert ds.labels.shape == (4, 2)
        # mask marks real words; "good movie" has 2
        np.testing.assert_array_equal(ds.featuresMask[0], [1, 1, 0])
        # first word of first sentence is the "good" vector
        np.testing.assert_allclose(ds.features[0, 0, 0],
                                   wv.getWordVector("good"))
        assert it.getLabels() == ["neg", "pos"]
        assert not it.hasNext()
        it.reset()
        assert it.hasNext()

    def test_rnn_layout_channels_first(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        it = (CnnSentenceDataSetIterator.Builder("RNN")
              .sentenceProvider(self._provider()).wordVectors(self._wv())
              .minibatchSize(2).build())
        ds = it.next()
        assert ds.features.shape == (2, 8, 2)      # (B, vecSize, maxLen)

    def test_unknown_word_handling(self):
        from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                            CollectionLabeledSentenceProvider)
        wv = self._wv()
        prov = CollectionLabeledSentenceProvider(["good zzz movie"], ["pos"])
        # RemoveWord (default): zzz skipped -> 2 tokens
        it = (CnnSentenceDataSetIterator.Builder()
              .sentenceProvider(prov).wordVectors(wv).build())
        assert it.next().features.shape[2] == 2
        # UseUnknown: zzz -> the "unk" vector, 3 tokens
        prov.reset()
        it2 = (CnnSentenceDataSetIterator.Builder()
               .sentenceProvider(prov).wordVectors(wv)
               .useUnknown("unk").build())
        ds = it2.next()
        assert ds.features.shape[2] == 3
        np.testing.assert_allclose(ds.features[0, 0, 1],
                                   wv.getWordVector("unk"))

    def test_preprocessor_applied(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        it = (CnnSentenceDataSetIterator.Builder()
              .sentenceProvider(self._provider()).wordVectors(self._wv())
              .minibatchSize(4).build())

        class Doubler:
            def preProcess(self, ds):
                ds.features = ds.features * 2.0

        base = it.next().features
        it.reset()
        it.setPreProcessor(Doubler())
        np.testing.assert_allclose(it.next().features, base * 2.0)

    def test_max_sentence_length_caps(self):
        from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                            CollectionLabeledSentenceProvider)
        prov = CollectionLabeledSentenceProvider(
            ["good " * 10 + "movie"], ["pos"])
        it = (CnnSentenceDataSetIterator.Builder()
              .sentenceProvider(prov).wordVectors(self._wv())
              .maxSentenceLength(4).build())
        assert it.next().features.shape[2] == 4


class TestSequenceVectors:
    def test_embeds_arbitrary_elements(self):
        from deeplearning4j_tpu.nlp import (AbstractSequenceIterator,
                                            SequenceVectors)
        # product-id style elements (spaces + punctuation allowed: no
        # tokenizer is involved); two co-occurrence groups
        rng = np.random.default_rng(0)
        group_a = [f"item A{i}" for i in range(6)]
        group_b = [f"item B{i}" for i in range(6)]
        seqs = []
        for _ in range(300):
            g = group_a if rng.random() < 0.5 else group_b
            seqs.append(list(rng.choice(g, size=6)))
        sv = (SequenceVectors.Builder()
              .layerSize(32).windowSize(3).epochs(10).seed(7)
              .learningRate(0.01).batchSize(512).sampling(0)
              .iterate(AbstractSequenceIterator(seqs))
              .build().fit())
        assert sv.vocabSize() == 12
        assert sv.hasWord("item A1")
        # same criterion as TestWord2Vec: nearest neighbors are dominated
        # by the element's own co-occurrence group
        for probe in ("item A1", "item B1", "item A3", "item B4"):
            near = sv.wordsNearest(probe, topN=3)
            assert probe not in near
            group = probe[:6]
            assert all(w.startswith(group) for w in near), (probe, near)

    def test_plain_list_input(self):
        from deeplearning4j_tpu.nlp import SequenceVectors
        sv = (SequenceVectors.Builder().layerSize(8).epochs(1).seed(0)
              .iterate([["x", "y", "z"], ["x", "z"]]).build().fit())
        assert sv.vocabSize() == 3
        assert sv.getWordVector("x").shape == (8,)

    def test_numerically_identical_to_word2vec(self):
        """Same corpus, same hyperparameters: SequenceVectors must produce
        the EXACT Word2Vec embedding table (it is the same pipeline)."""
        from deeplearning4j_tpu.nlp import (AbstractSequenceIterator,
                                            SequenceVectors)
        rng = np.random.default_rng(4)
        words = [f"w{i}" for i in range(8)]
        seqs = [list(rng.choice(words, size=5)) for _ in range(40)]
        kw = dict(layerSize=12, seed=9, epochs=2)
        w2v = (Word2Vec.Builder().minWordFrequency(1).layerSize(12).seed(9)
               .windowSize(3).epochs(2).sampling(0).learningRate(0.05)
               .batchSize(128)
               .iterate(CollectionSentenceIterator(
                   [" ".join(s) for s in seqs]))
               .tokenizerFactory(DefaultTokenizerFactory()).build().fit())
        sv = (SequenceVectors.Builder().layerSize(12).seed(9).windowSize(3)
              .epochs(2).sampling(0).learningRate(0.05).batchSize(128)
              .iterate(AbstractSequenceIterator(seqs)).build().fit())
        assert sv.vocab.words() == w2v.vocab.words()
        np.testing.assert_array_equal(np.asarray(sv.params["syn0"]),
                                      np.asarray(w2v.params["syn0"]))

    def test_rejects_raw_strings(self):
        from deeplearning4j_tpu.nlp import (AbstractSequenceIterator,
                                            SequenceVectors)
        with pytest.raises(TypeError, match="ELEMENTS"):
            AbstractSequenceIterator(["a b c", "d e"])
        with pytest.raises(TypeError, match="ELEMENTS"):
            (SequenceVectors.Builder().iterate(["a b c"]).build().fit())


class TestVectorizers:
    DOCS = ["cat dog cat", "dog mouse", "cat cat cat", "mouse mouse dog"]

    def test_bag_of_words_counts(self):
        from deeplearning4j_tpu.nlp import BagOfWordsVectorizer
        v = (BagOfWordsVectorizer.Builder().minWordFrequency(1)
             .iterate(self.DOCS).build().fit())
        assert v.vocabSize() == 3
        row = v.transform("cat dog cat")
        assert row[v.vocab.indexOf("cat")] == 2.0
        assert row[v.vocab.indexOf("dog")] == 1.0
        assert row[v.vocab.indexOf("mouse")] == 0.0
        # OOV words are ignored
        assert v.transform("zebra zebra").sum() == 0.0
        assert v.transformAll(self.DOCS).shape == (4, 3)

    def test_tfidf_oracle(self):
        import math
        from deeplearning4j_tpu.nlp import TfidfVectorizer
        v = (TfidfVectorizer.Builder().minWordFrequency(1)
             .iterate(self.DOCS).build().fit())
        # df: cat=2, dog=3, mouse=2 over 4 docs; idf = log(1 + N/df)
        row = v.transform("cat dog cat")
        idf_cat = math.log(1 + 4 / 2)
        idf_dog = math.log(1 + 4 / 3)
        assert row[v.vocab.indexOf("cat")] == pytest.approx(
            (2 / 3) * idf_cat, rel=1e-6)
        assert row[v.vocab.indexOf("dog")] == pytest.approx(
            (1 / 3) * idf_dog, rel=1e-6)
        assert v.tfidfWord("cat", ["cat", "dog", "cat"]) == pytest.approx(
            (2 / 3) * idf_cat, rel=1e-6)

    def test_vectorize_to_dataset_and_training(self):
        from deeplearning4j_tpu.nlp import TfidfVectorizer
        docs = self.DOCS * 8
        labels = ["feline" if "cat" in d else "other" for d in docs]
        v = (TfidfVectorizer.Builder().minWordFrequency(1)
             .iterate(docs).labels(labels).build().fit())
        ds = v.vectorize("cat cat dog", "feline")
        assert ds.features.shape == (1, 3) and ds.labels.shape == (1, 2)
        assert ds.labels[0, 0] == 1.0  # "feline" first in declaration order
        # the (N, V) matrix trains a dense classifier end to end
        from deeplearning4j_tpu.nn import (Adam, InputType,
                                           MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        x = v.transformAll(docs)
        y = np.eye(2, dtype=np.float32)[
            [0 if l == "feline" else 1 for l in labels]]
        net = MultiLayerNetwork(
            NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(nOut=8, activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=2,
                               activation="softmax"))
            .setInputType(InputType.feedForward(3)).build()).init()
        for _ in range(30):
            net.fit(x, y)
        acc = (np.asarray(net.output(x)).argmax(-1) == y.argmax(-1)).mean()
        assert acc == 1.0

    def test_min_word_frequency_prunes(self):
        from deeplearning4j_tpu.nlp import BagOfWordsVectorizer
        v = (BagOfWordsVectorizer.Builder().minWordFrequency(3)
             .iterate(self.DOCS).build().fit())
        # cat appears 5x, dog 3x, mouse 3x -> all kept at min 3
        assert v.vocabSize() == 3
        v2 = (BagOfWordsVectorizer.Builder().minWordFrequency(4)
              .iterate(self.DOCS).build().fit())
        assert v2.vocab.words() == ["cat"]

    def test_guards_and_tokenized_input(self):
        from deeplearning4j_tpu.nlp import TfidfVectorizer
        unfit = TfidfVectorizer.Builder().iterate(self.DOCS).build()
        with pytest.raises(ValueError, match="fit"):
            unfit.transform("cat")
        with pytest.raises(ValueError, match="fit"):
            unfit.tfidfWord("cat", ["cat"])
        v = unfit.fit()
        # tuple/list of tokens both accepted as pre-tokenized input
        np.testing.assert_array_equal(v.transform(("cat", "dog")),
                                      v.transform(["cat", "dog"]))
        with pytest.raises(ValueError, match="unknown label"):
            (TfidfVectorizer.Builder().iterate(self.DOCS)
             .labels(["a", "b"]).build().fit().vectorize("cat", "zzz"))

    def test_label_declaration_order_preserved(self):
        from deeplearning4j_tpu.nlp import BagOfWordsVectorizer
        v = (BagOfWordsVectorizer.Builder().iterate(self.DOCS)
             .labels(["positive", "negative"]).build().fit())
        # NOT alphabetical: column 0 must be "positive" as declared
        assert v.vectorize("cat", "positive").labels[0, 0] == 1.0
        assert v.vectorize("cat", "negative").labels[0, 1] == 1.0

    def test_fit_transform_matches_fit_then_transform(self):
        from deeplearning4j_tpu.nlp import TfidfVectorizer
        a = (TfidfVectorizer.Builder().build()).fitTransform(self.DOCS)
        v = TfidfVectorizer.Builder().iterate(self.DOCS).build().fit()
        np.testing.assert_array_equal(a, v.transformAll(self.DOCS))


class TestHierarchicalSoftmax:
    """Round-5 (≡ Word2Vec.Builder.useHierarchicSoftmax /
    HierarchicSoftmax): Huffman-tree output layer as the batched
    (B, L, D)-gather form."""

    def test_huffman_codes_prefix_free_and_frequency_ordered(self):
        from deeplearning4j_tpu.nlp.word2vec import _build_huffman
        counts = [100, 50, 20, 10, 5, 2, 1]
        points, codes, mask = _build_huffman(counts)
        lens = mask.sum(-1).astype(int)
        # most frequent word gets the (joint-)shortest code
        assert lens[0] == lens.min()
        assert lens[-1] == lens.max()
        # prefix-free: no word's code is a prefix of another's
        sigs = []
        for w in range(len(counts)):
            sigs.append(tuple(codes[w, :lens[w]].astype(int)))
        for a in sigs:
            for b in sigs:
                if a is not b:
                    assert a[:len(b)] != b or a == b
        # inner-node ids within range (V-1 nodes)
        assert points.max() < len(counts) - 1
        # expected total: sum(len*count) is the Huffman-optimal cost
        assert int((lens * np.asarray(counts)).sum()) == \
            sum(c * l for c, l in zip(counts, lens))

    def test_hs_word2vec_learns_topics(self):
        model = (Word2Vec.Builder()
                 .minWordFrequency(1).layerSize(32).seed(7).windowSize(3)
                 .epochs(4).useHierarchicSoftmax(True).sampling(0)
                 .learningRate(0.01).batchSize(512)
                 .iterate(CollectionSentenceIterator(synthetic_corpus()))
                 .tokenizerFactory(DefaultTokenizerFactory())
                 .build().fit())
        assert model.params["syn1"].shape[0] == model.vocabSize() - 1
        assert model.similarity("cat", "dog") > model.similarity("cat",
                                                                 "gpu")
        assert model.similarity("cpu", "ram") > model.similarity("cpu",
                                                                 "cow")

    def test_hs_single_word_vocab_safe(self):
        from deeplearning4j_tpu.nlp.word2vec import _build_huffman
        points, codes, mask = _build_huffman([5])
        assert mask.sum() == 0   # no inner nodes, empty path


def test_hs_rejected_on_ns_only_models():
    from deeplearning4j_tpu.nlp import FastText, ParagraphVectors
    with pytest.raises(ValueError, match="useHierarchicSoftmax"):
        (ParagraphVectors.Builder().useHierarchicSoftmax(True)
         .iterate([("d0", "a b c")]).build())
    with pytest.raises(ValueError, match="useHierarchicSoftmax"):
        (FastText.Builder().useHierarchicSoftmax(True)
         .iterate(CollectionSentenceIterator(["a b c"])).build())
