"""NLP tests (≡ deeplearning4j-nlp test suite: Word2VecTests,
ParagraphVectorsTest, tokenizer tests — scaled to a synthetic corpus
since the environment has no egress for real text datasets)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, FastText, Glove,
                                    LabelledDocument, NGramTokenizerFactory,
                                    ParagraphVectors, Word2Vec, build_vocab,
                                    char_ngrams)


def synthetic_corpus(n=300, seed=0):
    """Two topic clusters: words within a topic co-occur, across don't."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tech = ["cpu", "gpu", "ram", "disk", "cache", "bus"]
    sents = []
    for _ in range(n):
        topic = animals if rng.random() < 0.5 else tech
        sents.append(" ".join(rng.choice(topic, size=6)))
    return sents


class TestTokenization:
    def test_default_tokenizer(self):
        tok = DefaultTokenizerFactory().create("hello world foo")
        assert tok.countTokens() == 3
        assert tok.getTokens() == ["hello", "world", "foo"]
        assert tok.hasMoreTokens()
        assert tok.nextToken() == "hello"

    def test_common_preprocessor(self):
        fac = DefaultTokenizerFactory()
        fac.setTokenPreProcessor(CommonPreprocessor())
        toks = fac.create("Hello, World! 123 test.").getTokens()
        assert toks == ["hello", "world", "test"]

    def test_ngram_tokenizer(self):
        fac = NGramTokenizerFactory(minN=1, maxN=2)
        toks = fac.create("a b c").getTokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestVocab:
    def test_build_and_query(self):
        v = build_vocab([["a", "b", "a"], ["a", "c"]], min_count=1)
        assert v.numWords() == 3
        assert v.wordFrequency("a") == 3
        assert v.containsWord("b") and not v.containsWord("z")
        assert v.wordAtIndex(v.indexOf("c")) == "c"
        assert v.totalWordOccurrences() == 5

    def test_min_count_prunes(self):
        v = build_vocab([["a", "b", "a"]], min_count=2)
        assert v.words() == ["a"]

    def test_negative_table_normalized(self):
        v = build_vocab([["a", "b", "a"]], min_count=1)
        p = v.negative_table()
        assert p.shape == (2,) and abs(p.sum() - 1.0) < 1e-9


class TestWord2Vec:
    @pytest.fixture(scope="class")
    def model(self):
        return (Word2Vec.Builder()
                .minWordFrequency(1).layerSize(32).seed(7).windowSize(3)
                .epochs(3).negativeSample(5).sampling(0)
                .learningRate(0.05).batchSize(512)
                .iterate(CollectionSentenceIterator(synthetic_corpus()))
                .tokenizerFactory(DefaultTokenizerFactory())
                .build().fit())

    def test_vocab(self, model):
        assert model.vocabSize() == 12
        assert model.hasWord("cat") and model.hasWord("gpu")

    def test_vector_shape(self, model):
        assert model.getWordVector("cat").shape == (32,)

    def test_topic_clustering(self, model):
        # within-topic similarity beats cross-topic
        assert model.similarity("cat", "dog") > model.similarity("cat", "gpu")
        assert model.similarity("cpu", "ram") > model.similarity("cpu", "cow")

    def test_words_nearest(self, model):
        near = model.wordsNearest("cat", topN=5)
        assert "cat" not in near
        animals = {"dog", "horse", "cow", "sheep", "goat"}
        assert len(set(near[:3]) & animals) >= 2


class TestParagraphVectors:
    def test_dbow_labels_cluster(self):
        docs = []
        for i, s in enumerate(synthetic_corpus(60, seed=1)):
            topic = "animals" if s.split()[0] in {
                "cat", "dog", "horse", "cow", "sheep", "goat"} else "tech"
            docs.append(LabelledDocument(s, f"{topic}_{i}"))
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(24).seed(3).epochs(3)
              .sampling(0).batchSize(256)
              .iterate(docs).build().fit())
        assert pv.getLabelVector(docs[0].labels[0]).shape == (24,)
        v = pv.inferVector("cat dog horse cow")
        assert v.shape == (24,) and np.isfinite(v).all()

    def test_dm_runs(self):
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(16).seed(3).epochs(2)
              .sampling(0).batchSize(128)
              .sequenceLearningAlgorithm("DM")
              .iterate(synthetic_corpus(30)).build().fit())
        assert pv.params["docs"].shape == (30, 16)

    def test_nearest_labels(self):
        docs = [("animal_doc", "cat dog cow horse sheep goat cat dog"),
                ("tech_doc", "cpu gpu ram disk cache bus cpu gpu")] * 5
        docs = [(f"{lab}_{i}", txt) for i, (lab, txt) in enumerate(docs)]
        pv = (ParagraphVectors.Builder()
              .minWordFrequency(1).layerSize(16).seed(5).epochs(10)
              .sampling(0).batchSize(128).iterate(docs).build().fit())
        labs = pv.nearestLabels("cat dog sheep", topN=3)
        assert len(labs) == 3


class TestGlove:
    def test_topic_clustering(self):
        g = (Glove.Builder()
             .minWordFrequency(1).layerSize(24).seed(11).windowSize(4)
             .epochs(40).learningRate(0.05)
             .iterate(synthetic_corpus(200, seed=2)).build().fit())
        assert g.getWordVector("cat").shape == (24,)
        assert g.similarity("cat", "dog") > g.similarity("cat", "gpu")


class TestFastText:
    def test_char_ngrams(self):
        grams = char_ngrams("cat", 3, 4)
        assert "<ca" in grams and "at>" in grams and "<cat" in grams

    def test_train_and_oov(self):
        ft = (FastText.Builder()
              .minWordFrequency(1).layerSize(16).seed(9).windowSize(3)
              .epochs(2).sampling(0).batchSize(256)
              .iterate(synthetic_corpus(80)).build().fit())
        assert ft.getWordVector("cat").shape == (16,)
        # OOV word built purely from shared subword n-grams
        oov = ft.getWordVector("cats")
        assert oov.shape == (16,) and np.isfinite(oov).all()
        assert ft.similarity("cat", "dog") == ft.similarity("dog", "cat")
