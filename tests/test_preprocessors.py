"""Input-preprocessor tests (≡ deeplearning4j-nn ::
preprocessor.CNNProcessorTest / RnnDataFormatTests) — round-1 VERDICT
flagged RnnToCnnPreProcessor as untested."""
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor, FeedForwardToRnnPreProcessor,
    RnnToCnnPreProcessor, RnnToFeedForwardPreProcessor)


class TestRnnToCnn:
    def test_reshape_semantics(self):
        """(B, T, H*W*C) -> (B*T, H, W, C): time folds into batch, each
        timestep becomes one image (the reference's reshape, NHWC here)."""
        pp = RnnToCnnPreProcessor(height=2, width=3, channels=2)
        b, t = 4, 5
        x = np.arange(b * t * 12, dtype=np.float32).reshape(b, t, 12)
        y = pp.preProcess(x)
        assert y.shape == (b * t, 2, 3, 2)
        # example (bi, ti) must equal row-major reshape of that timestep
        for bi in (0, 3):
            for ti in (0, 4):
                np.testing.assert_array_equal(
                    y[bi * t + ti], x[bi, ti].reshape(2, 3, 2))

    def test_output_type(self):
        pp = RnnToCnnPreProcessor(8, 8, 3)
        ot = pp.getOutputType(InputType.recurrent(8 * 8 * 3))
        assert (ot.height, ot.width, ot.channels) == (8, 8, 3)


class TestRoundTrips:
    def test_ff_cnn_roundtrip(self):
        x = np.random.default_rng(0).normal(size=(3, 24)).astype(np.float32)
        to_cnn = FeedForwardToCnnPreProcessor(2, 4, 3)
        back = CnnToFeedForwardPreProcessor()
        np.testing.assert_array_equal(back.preProcess(to_cnn.preProcess(x)), x)

    def test_rnn_ff_fold(self):
        x = np.random.default_rng(1).normal(size=(2, 5, 7)).astype(np.float32)
        pp = RnnToFeedForwardPreProcessor()
        y = pp.preProcess(x)
        assert y.shape == (10, 7)
        np.testing.assert_array_equal(y[5], x[1, 0])

    def test_ff_rnn_single_step(self):
        x = np.random.default_rng(2).normal(size=(4, 6)).astype(np.float32)
        y = FeedForwardToRnnPreProcessor().preProcess(x)
        assert y.shape == (4, 1, 6)

    def test_cnn_rnn(self):
        x = np.random.default_rng(3).normal(
            size=(2, 2, 2, 3)).astype(np.float32)
        y = CnnToRnnPreProcessor().preProcess(x)
        assert y.shape == (2, 1, 12)
