"""Worker process for the two-process jax.distributed test.

Run as: python multihost_worker.py <process_id> <port> <out_json>
Each process owns 4 virtual CPU devices; the global mesh spans 8 devices
across the 2 processes — the SharedTrainingMaster topology (multi-host dp
over DCN) executed for real, not just gated code (round-1 VERDICT item 7).
"""
import json
import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
out_path = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

import numpy as np
import jax

# distributed init MUST precede anything that can touch the XLA backend —
# including framework imports (deeplearning4j_tpu.ops touches jax at import)
from deeplearning4j_tpu.parallel.mesh import initialize_distributed

assert initialize_distributed(f"localhost:{port}", num_processes=2,
                              process_id=pid)

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.sharded_trainer import ShardedTrainer
from deeplearning4j_tpu.nn.updaters import Sgd
assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, devs  # 4 local + 4 remote

mesh = Mesh(np.array(devs), ("dp",))

rng = np.random.default_rng(0)  # same seed on both processes
W1 = (rng.standard_normal((8, 16)) * 0.3).astype(np.float32)
W2 = (rng.standard_normal((16, 4)) * 0.3).astype(np.float32)
xs = rng.standard_normal((16, 8)).astype(np.float32)
ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]


def loss_fn(params, batch, rng_key):
    h = jnp.tanh(batch["x"] @ params["W1"])
    logits = h @ params["W2"]
    return -jnp.mean(jnp.sum(batch["y"] * jax.nn.log_softmax(logits, -1), -1))


trainer = ShardedTrainer(loss_fn, Sgd(0.2), mesh)
params, opt_state = trainer.init({"W1": W1, "W2": W2})

bsh = NamedSharding(mesh, P("dp"))


def gmake(arr):
    return jax.make_array_from_callback(arr.shape, bsh, lambda idx: arr[idx])


batch = {"x": gmake(xs), "y": gmake(ys)}
losses = []
for i in range(5):
    params, opt_state, loss = trainer.fit_batch(params, opt_state, batch,
                                                jax.random.PRNGKey(i))
    losses.append(float(loss))

flat = np.concatenate([np.asarray(jax.device_get(params[k])).ravel()
                       for k in sorted(params)])
result = {"pid": pid, "losses": losses,
          "checksum": float(np.abs(flat).sum())}

# -- cluster metrics plane over the REAL coordination KV (ISSUE 15) ------
# Each process publishes its registry snapshot at sync cadence; process
# 0 renders the fleet /metrics view and the /health cluster meta. A
# forced SLO breach on process 0 must flip health to degraded with the
# objective named, then recover once the breach clears.
import time

from deeplearning4j_tpu import monitoring as mon
from deeplearning4j_tpu import resilience
from deeplearning4j_tpu.monitoring import cluster as cluster_mod
from deeplearning4j_tpu.monitoring import slo as slo_mod
from deeplearning4j_tpu.parallel.coordination import PeerCoordinator

mon.enable()
reg = mon.get_registry()
reg.counter("dl4j.test.worker_steps").inc(len(losses))
coordinator = PeerCoordinator(sync_every=1, peer_timeout=30).install()
for _ in range(3):
    coordinator.on_step()
coordinator.barrier("metrics-published")

if pid == 0:
    text = cluster_mod.cluster_prometheus_text(coordinator)
    probe = "dl4j_test_worker_steps"
    result["cluster_metrics"] = {
        "host0": f'{probe}{{host="0"}}' in text,
        "host1": f'{probe}{{host="1"}}' in text,
        "cluster_sum": f'{probe}{{host="cluster"}} 10' in text,
        "age_gauge": "dl4j_cluster_snapshot_age_seconds" in text,
    }
    snap = resilience.health_snapshot()
    result["health_cluster"] = snap["distributed"]["cluster"]
    table = coordinator.peer_table()
    result["peer_steps_per_s"] = {
        str(k): v.get("steps_per_s") for k, v in table.items()}

# -- straggler plane over the REAL coordination KV (ISSUE 16) ------------
# Process 1 plays the straggler: its flight recorder reports a 60 ms
# dispatch phase vs process 0's 5 ms. One sync point publishes both
# digests; process 0 must name the host AND the phase on /stragglers,
# carry both timelines on /steps, render one training lane per host on
# /trace, and flip health degraded via the StragglerObjective — then
# auto-recover when the slowdown clears.
from deeplearning4j_tpu.monitoring import steps as steps_mod
from deeplearning4j_tpu.monitoring import stragglers as stragglers_mod

rec = steps_mod.recorder()
rec.clear()
dispatch_ms = 60.0 if pid == 1 else 5.0
for _ in range(4):
    rec.on_span("fit.data_next", 1.0)
    rec.on_span("sharded.dispatch", dispatch_ms)
coordinator.on_step()                      # sync-point publish
coordinator.barrier("slowed-published")

if pid == 0:
    att = stragglers_mod.attribution(coordinator)
    result["straggler"] = att["slowest"]
    result["timeline_hosts"] = sorted(att["hosts"])
    result["timeline_phases"] = {
        h: sorted(d["phases_p50_ms"]) for h, d in att["hosts"].items()}
    result["derived_exchange_ms"] = \
        stragglers_mod.derived_exchange_ms(coordinator)

    import urllib.request
    from deeplearning4j_tpu.ui.server import UIServer
    server = UIServer.getInstance()
    server.start(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        sdoc = json.load(urllib.request.urlopen(base + "/stragglers",
                                                timeout=10))
        result["http_stragglers"] = sdoc["slowest"]
        steps_doc = json.load(urllib.request.urlopen(base + "/steps",
                                                     timeout=10))
        result["http_steps_hosts"] = sorted(steps_doc.get("hosts", {}))
        tdoc = json.load(urllib.request.urlopen(base + "/trace",
                                                timeout=10))
        result["trace_lanes"] = sorted(
            e["args"]["name"] for e in tdoc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
            and str(e["args"].get("name", "")).startswith("train host"))
    finally:
        server.stop()

    sg_tracker = slo_mod.SloTracker(
        [slo_mod.StragglerObjective("straggler_ratio", max_ratio=2.0,
                                    coordinator=coordinator)],
        short_window=0.2, long_window=0.5, min_interval=0.0).install()
    deadline = time.monotonic() + 0.7
    while time.monotonic() < deadline:
        sg_tracker.evaluate(force=True)
        time.sleep(0.05)
    breach = resilience.health_snapshot()
    obj = breach["slo"]["objectives"]["straggler_ratio"]
    result["straggler_breach"] = {"status": breach["status"],
                                  "violated": breach["slo"]["violated"],
                                  "culprit": obj.get("culprit")}

coordinator.barrier("straggler-breach")

# the slowdown clears: both hosts republish healthy digests
rec.clear()
for _ in range(4):
    rec.on_span("fit.data_next", 1.0)
    rec.on_span("sharded.dispatch", 5.0)
coordinator.on_step()
coordinator.barrier("recovered-published")

if pid == 0:
    deadline = time.monotonic() + 0.7
    while time.monotonic() < deadline:
        sg_tracker.evaluate(force=True)
        time.sleep(0.05)
    recovered = resilience.health_snapshot()
    result["straggler_recovered"] = {
        "status": recovered["status"],
        "violated": recovered["slo"]["violated"]}
    sg_tracker.uninstall()

    # forced SLO breach: impossible latency objective over a loaded
    # histogram; tiny burn windows so breach AND recovery both land
    # inside the soak
    h = reg.histogram("dl4j.test.worker_lat", reservoir=256)
    for _ in range(256):
        h.observe(100.0)
    tracker = slo_mod.SloTracker(
        [slo_mod.LatencyObjective("worker_p99",
                                  metric="dl4j.test.worker_lat",
                                  max_value=5.0)],
        short_window=0.2, long_window=0.5, min_interval=0.0).install()
    deadline = time.monotonic() + 0.7
    while time.monotonic() < deadline:
        tracker.evaluate(force=True)
        time.sleep(0.05)
    breach = resilience.health_snapshot()
    result["slo_breach"] = {"status": breach["status"],
                            "violated": breach["slo"]["violated"]}
    for _ in range(512):                     # latency recovers
        h.observe(0.1)
    deadline = time.monotonic() + 0.7
    while time.monotonic() < deadline:
        tracker.evaluate(force=True)
        time.sleep(0.05)
    recovered = resilience.health_snapshot()
    result["slo_recovered"] = {"status": recovered["status"],
                               "violated": recovered["slo"]["violated"]}
    tracker.uninstall()

coordinator.barrier("slo-done")
coordinator.uninstall()
mon.disable()

with open(out_path, "w") as f:
    json.dump(result, f)
print("worker", pid, "done", result["losses"][0], "->", result["losses"][-1])
