"""Native C++ runtime tests (SURVEY.md §4; ≡ libnd4j/DataVec native
pipeline coverage): parity of native vs pure-python paths."""
import os
import struct
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu.runtime import native_lib


pytestmark = pytest.mark.skipif(not native_lib.available(),
                                reason="native toolchain unavailable")


def _write_idx_u8(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def test_idx_read_native_matches_python():
    arr = (np.arange(2 * 5 * 5) % 256).astype(np.uint8).reshape(2, 5, 5)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t-images-idx3-ubyte")
        _write_idx_u8(p, arr)
        got = native_lib.idx_read(p)
        np.testing.assert_array_equal(got, arr)
        from deeplearning4j_tpu.datasets.iterators import _read_idx
        np.testing.assert_array_equal(_read_idx(p), arr)


def test_gather_batch_scales():
    arch = (np.arange(6 * 4) % 256).astype(np.uint8).reshape(6, 4)
    out = native_lib.gather_batch_u8(arch, [5, 1, 3], scale=1 / 255.0)
    np.testing.assert_allclose(out, arch[[5, 1, 3]].astype(np.float32) / 255,
                               rtol=1e-6)
    out2 = native_lib.gather_batch_u8(arch, [0], scale=2.0, bias=-1.0)
    np.testing.assert_allclose(out2, arch[[0]].astype(np.float32) * 2 - 1,
                               rtol=1e-6)


def test_one_hot():
    labels = np.array([3, 1, 0, 2], np.uint8)
    oh = native_lib.one_hot_u8(labels, [0, 3], 4)
    np.testing.assert_allclose(oh, [[0, 0, 0, 1], [0, 0, 1, 0]])


def test_standardize_inplace():
    data = np.random.default_rng(0).standard_normal((8, 3)).astype(np.float32)
    mean = data.mean(0).astype(np.float32)
    std = data.std(0).astype(np.float32)
    want = (data - mean) / std
    native_lib.standardize_inplace(data, mean, std)
    np.testing.assert_allclose(data, want, rtol=1e-5)


def test_arena_alloc_reset():
    a = native_lib.NativeArena(1 << 16)
    b1 = a.alloc_f32((16,))
    b1[:] = 7.0
    used1 = a.used()
    assert used1 >= 64
    a.reset()
    assert a.used() == 0
    b2 = a.alloc_f32((16,))
    # same memory reused after reset
    assert b2.__array_interface__["data"][0] == b1.__array_interface__["data"][0]
    a.close()


def test_arena_overflow_falls_back():
    a = native_lib.NativeArena(256)
    big = a.alloc_f32((1024,))  # larger than arena: heap fallback
    big[:] = 1.0
    assert big.shape == (1024,)
    a.close()


def test_ring_buffer_roundtrip():
    import ctypes
    lib = native_lib.get_lib()
    ring = lib.dl4j_ring_create(4)
    bufs = []
    for i in range(3):
        buf = ctypes.create_string_buffer(8)
        ctypes.memset(buf, 65 + i, 8)
        bufs.append(buf)
        assert lib.dl4j_ring_push(ring, ctypes.cast(buf, ctypes.c_void_p), 8) == 0
    assert lib.dl4j_ring_size(ring) == 3
    out = ctypes.c_void_p()
    n = lib.dl4j_ring_pop(ring, ctypes.byref(out))
    assert n == 8
    got = ctypes.string_at(out, 8)
    assert got == b"A" * 8
    lib.dl4j_ring_close(ring)
    # drain remaining then closed → -1 (after queue empties)
    lib.dl4j_ring_pop(ring, ctypes.byref(out))
    lib.dl4j_ring_pop(ring, ctypes.byref(out))
    assert lib.dl4j_ring_pop(ring, ctypes.byref(out)) == -1
    # NOTE: ring intentionally not destroyed — dl4j_ring_destroy frees
    # queued buffers with free(), and these are python-owned.


def test_workspace_scope():
    from deeplearning4j_tpu.runtime.workspace import Nd4jWorkspace
    with Nd4jWorkspace("TEST") as ws:
        buf = ws.alloc((32, 32))
        buf[:] = 1.0
        assert ws.bytes_used() >= 32 * 32 * 4
    assert ws.bytes_used() == 0
    ws.close()


def test_executioner_profiling():
    import jax.numpy as jnp
    from deeplearning4j_tpu.runtime.executioner import OpExecutioner
    ex = OpExecutioner.getInstance()
    ex.setProfilingMode(True)

    def square_sum(x):
        return jnp.sum(x * x)

    out = ex.exec(square_sum, jnp.ones(8))
    assert float(out) == 8.0
    stats = ex.getProfilingStats()
    assert stats["square_sum"]["count"] >= 1
    ex.setProfilingMode(False)


class TestNativeCsv:
    """dl4j_csv_parse: single-pass numeric CSV -> float32 matrix, exact
    equality with the Python csv module on the same content."""

    def test_numeric_matches_python_csv(self, tmp_path):
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(0)
        arr = rng.standard_normal((37, 5)).astype(np.float32)
        lines = ["h1,h2,h3,h4,h5"] + [
            ",".join(f"{v:.6g}" for v in row) for row in arr]
        path = tmp_path / "t.csv"
        path.write_text("\n".join(lines) + "\n")
        got = native_lib.csv_to_floats(str(path), ",", skip_rows=1)
        assert got is not None and got.shape == (37, 5)
        want = np.array([[float(x) for x in l.split(",")]
                         for l in lines[1:]], np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_non_numeric_fields_become_nan(self):
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        got = native_lib.csv_to_floats(b"1.5,abc,3\n,2,\n")
        assert got.shape == (2, 3)
        assert got[0, 0] == 1.5 and np.isnan(got[0, 1]) and got[0, 2] == 3
        # blank fields are NaN and must NOT swallow the next line's number
        assert np.isnan(got[1, 0]) and got[1, 1] == 2 and np.isnan(got[1, 2])

    def test_csv_reader_bulk_path_equivalence(self, tmp_path):
        from deeplearning4j_tpu.datavec.records import (
            CSVRecordReader, RecordReaderDataSetIterator)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((24, 4)).astype(np.float32)
        y = rng.integers(0, 3, 24)
        rows = [",".join([f"{v:.6g}" for v in x[i]] + [str(y[i])])
                for i in range(24)]
        path = tmp_path / "d.csv"
        path.write_text("\n".join(rows))
        reader = CSVRecordReader().initialize(str(path))
        it = RecordReaderDataSetIterator(reader, 8, labelIndex=4,
                                         numClasses=3)
        np.testing.assert_allclose(it.features,
                                   np.array([[float(v) for v in r.split(",")[:4]]
                                             for r in rows], np.float32),
                                   rtol=1e-6)
        assert it.labels.shape == (24, 3)
        assert (it.labels.argmax(1) == y).all()
        # string-labelled CSVs must keep the record-level slow path working
        srows = [r + ",name" for r in rows]
        sreader = CSVRecordReader().initialize("\n".join(srows))
        assert sreader.numeric_matrix() is None
        rec = sreader.next()
        assert rec[-1] == "name"

    def test_tab_delim_empty_field_stays_aligned(self):
        # whitespace delimiter + empty field: strtof must not swallow the
        # next field (bounded-field parse)
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        got = native_lib.csv_to_floats(b"1\t\t3\n4\t5\t6\n", "\t")
        assert got.shape == (2, 3)
        assert got[0, 0] == 1 and np.isnan(got[0, 1]) and got[0, 2] == 3
        assert list(got[1]) == [4, 5, 6]

    def test_trailing_garbage_is_nan_not_truncated(self):
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        got = native_lib.csv_to_floats(b"1.5abc,2\n3, 4 \n")
        assert np.isnan(got[0, 0]) and got[0, 1] == 2
        assert got[1, 0] == 3 and got[1, 1] == 4  # padded fields still parse

    def test_skip_counts_physical_lines(self):
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        # blank first line consumes the skip, exactly like csv.reader slicing
        got = native_lib.csv_to_floats(b"\n1,2\n3,4\n", skip_rows=1)
        assert got.shape == (2, 2) and got[0, 0] == 1 and got[1, 1] == 4

    def test_bulk_path_gates(self):
        from deeplearning4j_tpu.datavec.records import CSVRecordReader
        # interior blank line -> record/matrix views disagree -> no bulk
        r = CSVRecordReader().initialize("1,2\n\n3,4\n")
        assert r.numeric_matrix() is None
        # partially-consumed reader -> no bulk matrix
        r2 = CSVRecordReader().initialize("1,2\n3,4\n")
        assert r2.numeric_matrix() is not None
        r2.next()
        assert r2.numeric_matrix() is None
        r2.reset()
        assert r2.numeric_matrix() is not None
        # garbage suffix falls back to the Python path (which raises on use)
        r3 = CSVRecordReader().initialize("1.5abc,2\n3,4\n")
        assert r3.numeric_matrix() is None

    def test_hex_floats_and_ragged_rejected(self):
        from deeplearning4j_tpu.runtime import native_lib
        from deeplearning4j_tpu.datavec.records import CSVRecordReader
        if not native_lib.available():
            pytest.skip("native toolchain unavailable")
        # hex parses in strtof but raises in Python float() -> must be NaN
        got = native_lib.csv_to_floats(b"0x10,2\n3,4\n")
        assert np.isnan(got[0, 0]) and got[0, 1] == 2
        # ragged numeric rows: the bulk gate must refuse (Python raises)
        r = CSVRecordReader().initialize("1\n2,3\n")
        assert r.numeric_matrix() is None


class TestNativeImageOps:
    def test_bilinear_matches_oracle_many_shapes(self):
        from deeplearning4j_tpu.runtime import native_lib
        if not native_lib.available():
            import pytest
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(7)
        for (sh, sw, c), (dh, dw) in [((8, 8, 3), (16, 16)),
                                      ((64, 48, 3), (17, 29)),
                                      ((5, 5, 1), (10, 3)),
                                      ((224, 224, 3), (64, 64))]:
            img = rng.integers(0, 256, size=(sh, sw, c), dtype=np.uint8)
            got = native_lib.resize_bilinear_u8(img, dh, dw)
            want = native_lib._resize_bilinear_oracle(img, dh, dw)
            assert got.shape == (dh, dw, c)
            np.testing.assert_allclose(got, want, atol=1e-3)

    def test_identity_resize_is_exact(self):
        from deeplearning4j_tpu.runtime import native_lib
        rng = np.random.default_rng(3)
        img = rng.integers(0, 256, size=(12, 9, 3), dtype=np.uint8)
        out = native_lib.resize_bilinear_u8(img, 12, 9)
        np.testing.assert_allclose(out, img.astype(np.float32), atol=1e-4)

    def test_native_image_loader(self, tmp_path):
        from deeplearning4j_tpu.datavec.image_records import \
            NativeImageLoader
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 256, size=(40, 30, 3), dtype=np.uint8)
        # array source
        m = NativeImageLoader(16, 16, 3).asMatrix(arr)
        assert m.shape == (1, 16, 16, 3) and m.dtype == np.float32
        assert 0 <= m.min() and m.max() <= 255
        # file source via PIL round trip
        from PIL import Image
        p = tmp_path / "img.png"
        Image.fromarray(arr).save(p)
        m2 = NativeImageLoader(16, 16, 3).asMatrix(str(p))
        np.testing.assert_allclose(m2, m, atol=1e-3)
        # grayscale conversion
        g = NativeImageLoader(8, 8, 1).asMatrix(arr)
        assert g.shape == (1, 8, 8, 1)

    def test_reader_native_loader_option(self, tmp_path):
        from PIL import Image

        from deeplearning4j_tpu.datavec.image_records import \
            ImageRecordReader
        rng = np.random.default_rng(1)
        for cls in ("a", "b"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(rng.integers(
                    0, 256, size=(20, 20, 3), dtype=np.uint8)).save(
                        d / f"{i}.png")
        rr = ImageRecordReader(8, 8, 3, nativeLoader=True).initialize(
            str(tmp_path))
        img, lab = rr.next()
        assert img.shape == (8, 8, 3) and img.dtype == np.float32
        assert rr.getLabels() == ["a", "b"]

    def test_loader_float_and_alpha_inputs(self):
        from deeplearning4j_tpu.datavec.image_records import \
            NativeImageLoader
        rng = np.random.default_rng(5)
        u8 = rng.integers(0, 256, size=(10, 10, 3), dtype=np.uint8)
        base = NativeImageLoader(8, 8, 3).asMatrix(u8)
        # normalized floats give the SAME image back (no truncation)
        f01 = NativeImageLoader(8, 8, 3).asMatrix(
            u8.astype(np.float32) / 255.0)
        np.testing.assert_allclose(f01, base, atol=1.0)
        assert f01.max() > 10         # not silently near-black
        # [0,255] floats round
        f255 = NativeImageLoader(8, 8, 3).asMatrix(u8.astype(np.float32))
        np.testing.assert_allclose(f255, base, atol=1e-3)
        # RGBA drops alpha; LA drops alpha for grayscale
        rgba = np.concatenate([u8, np.full((10, 10, 1), 255, np.uint8)],
                              -1)
        np.testing.assert_allclose(
            NativeImageLoader(8, 8, 3).asMatrix(rgba), base, atol=1e-3)
        la = np.concatenate([u8[..., :1],
                             np.full((10, 10, 1), 255, np.uint8)], -1)
        g = NativeImageLoader(8, 8, 1).asMatrix(la)
        assert g.shape == (1, 8, 8, 1)

    def test_loader_float_overshoot_and_ambiguous(self):
        import pytest

        from deeplearning4j_tpu.datavec.image_records import \
            NativeImageLoader
        # bilinear/bicubic overshoot past 1.0 still reads as normalized
        a = np.full((8, 8, 3), 0.5, np.float32)
        a[0, 0, 0] = 1.004
        m = NativeImageLoader(8, 8, 3).asMatrix(a)
        assert m.max() > 100          # scaled by 255, not near-black
        # max in (1.01, 2.0) is ambiguous and must fail loudly
        bad = np.full((8, 8, 3), 1.5, np.float32)
        with pytest.raises(ValueError, match="ambiguous"):
            NativeImageLoader(8, 8, 3).asMatrix(bad)

    def test_loader_rejects_negative_floats(self):
        import pytest

        from deeplearning4j_tpu.datavec.image_records import \
            NativeImageLoader
        arr = np.random.default_rng(0).uniform(
            -1, 1, size=(8, 8, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="negative"):
            NativeImageLoader(4, 4, 3).asMatrix(arr)
