"""TF frozen-graph import tests (≡ nd4j TFGraphTestAllSameDiff-style: run
an imported graph and compare against a reference implementation). Graphs
are authored with the dependency-free tfproto writer — same wire format a
real frozen .pb uses."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import tfproto
from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.tf_import import (TFGraphMapper,
                                                   UnsupportedTFOpError,
                                                   importFrozenTF)


class TestProtoCodec:
    def test_tensor_roundtrip(self):
        for arr in [np.arange(6, dtype=np.float32).reshape(2, 3),
                    np.asarray([[1, 2], [3, 4]], np.int64),
                    np.float32(3.5).reshape(())]:
            out = tfproto.parse_tensor(tfproto.encode_tensor(arr))
            assert out.dtype == arr.dtype and np.array_equal(out, arr)

    def test_graphdef_roundtrip(self):
        w = np.ones((2, 2), np.float32)
        data = tfproto.encode_graphdef([
            ("W", "Const", [], {"value": w, "dtype": ("dtype",
                                                      tfproto.DT_FLOAT)}),
            ("x", "Placeholder", [], {}),
            ("y", "MatMul", ["x", "W"], {"transpose_b": True}),
        ])
        nodes = tfproto.parse_graphdef(data)
        assert [n.op for n in nodes] == ["Const", "Placeholder", "MatMul"]
        assert nodes[2].inputs == ["x", "W"]
        assert nodes[2].attrs["transpose_b"] is True
        assert np.array_equal(nodes[0].attrs["value"], w)

    def test_negative_int_attr(self):
        data = tfproto.encode_graphdef([("n", "Mean", [], {"axis": -1})])
        assert tfproto.parse_graphdef(data)[0].attrs["axis"] == -1


def mlp_graphdef(rng):
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    nodes = [
        ("input", "Placeholder", [], {}),
        ("w1", "Const", [], {"value": w1}),
        ("b1", "Const", [], {"value": b1}),
        ("w2", "Const", [], {"value": w2}),
        ("mm1", "MatMul", ["input", "w1"], {}),
        ("ba1", "BiasAdd", ["mm1", "b1"], {}),
        ("act1", "Relu", ["ba1"], {}),
        ("mm2", "MatMul", ["act1", "w2"], {}),
        ("probs", "Softmax", ["mm2"], {}),
    ]
    return tfproto.encode_graphdef(nodes), (w1, b1, w2)


class TestImport:
    def test_mlp_matches_numpy(self):
        rng = np.random.default_rng(0)
        data, (w1, b1, w2) = mlp_graphdef(rng)
        sd = importFrozenTF(data)
        x = rng.normal(size=(5, 4)).astype(np.float32)
        got = np.asarray(sd.outputSingle({"input": x}, "probs").jax())
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2
        e = np.exp(logits - logits.max(-1, keepdims=True))
        expect = e / e.sum(-1, keepdims=True)
        assert np.allclose(got, expect, atol=1e-5)

    def test_layernorm_gelu_fragment(self):
        """The BERT building block: mean/var layernorm + erf GELU."""
        rng = np.random.default_rng(1)
        gamma = rng.normal(size=(6,)).astype(np.float32)
        beta = rng.normal(size=(6,)).astype(np.float32)
        nodes = [
            ("x", "Placeholder", [], {}),
            ("axes", "Const", [], {"value": np.asarray([-1], np.int32)}),
            ("mu", "Mean", ["x", "axes"], {"keep_dims": True}),
            ("d", "SquaredDifference", ["x", "mu"], {}),
            ("var", "Mean", ["d", "axes"], {"keep_dims": True}),
            ("eps", "Const", [], {"value": np.float32(1e-5).reshape(())}),
            ("vpe", "AddV2", ["var", "eps"], {}),
            ("rstd", "Rsqrt", ["vpe"], {}),
            ("cen", "Sub", ["x", "mu"], {}),
            ("nrm", "Mul", ["cen", "rstd"], {}),
            ("gamma", "Const", [], {"value": gamma}),
            ("beta", "Const", [], {"value": beta}),
            ("scl", "Mul", ["nrm", "gamma"], {}),
            ("ln", "AddV2", ["scl", "beta"], {}),
            # erf-GELU: 0.5 * x * (1 + erf(x / sqrt(2)))
            ("c_half", "Const", [], {"value": np.float32(0.5).reshape(())}),
            ("c_rsq2", "Const", [], {"value": np.float32(
                1 / np.sqrt(2)).reshape(())}),
            ("xs", "Mul", ["ln", "c_rsq2"], {}),
            ("erf", "Erf", ["xs"], {}),
            ("one", "Const", [], {"value": np.float32(1.0).reshape(())}),
            ("erf1", "AddV2", ["erf", "one"], {}),
            ("xh", "Mul", ["ln", "c_half"], {}),
            ("gelu", "Mul", ["xh", "erf1"], {}),
        ]
        sd = importFrozenTF(tfproto.encode_graphdef(nodes))
        x = rng.normal(size=(3, 6)).astype(np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "gelu").jax())
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        ln = (x - mu) / np.sqrt(var + 1e-5) * gamma + beta
        from scipy.special import erf as sp_erf
        expect = 0.5 * ln * (1 + sp_erf(ln / np.sqrt(2)))
        assert np.allclose(got, expect, atol=1e-4)

    def test_embedding_gather(self):
        table = np.arange(20, dtype=np.float32).reshape(5, 4)
        nodes = [
            ("ids", "Placeholder", [], {}),
            ("table", "Const", [], {"value": table}),
            ("emb", "GatherV2", ["table", "ids"], {}),
        ]
        sd = importFrozenTF(tfproto.encode_graphdef(nodes))
        ids = np.asarray([[0, 3], [2, 4]], np.int32)
        got = np.asarray(sd.outputSingle({"ids": ids}, "emb").jax())
        assert np.array_equal(got, table[ids])

    def test_transpose_reshape_concat(self):
        nodes = [
            ("a", "Placeholder", [], {}),
            ("perm", "Const", [], {"value": np.asarray([1, 0], np.int32)}),
            ("at", "Transpose", ["a", "perm"], {}),
            ("shp", "Const", [], {"value": np.asarray([6, 1], np.int32)}),
            ("ar", "Reshape", ["at", "shp"], {}),
            ("axis", "Const", [], {"value": np.asarray(1, np.int32)}),
            ("cat", "ConcatV2", ["ar", "ar", "axis"], {}),
        ]
        sd = importFrozenTF(tfproto.encode_graphdef(nodes))
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        got = np.asarray(sd.outputSingle({"a": a}, "cat").jax())
        r = a.T.reshape(6, 1)
        assert np.array_equal(got, np.concatenate([r, r], 1))

    def test_unsupported_op_raises(self):
        nodes = [("x", "Placeholder", [], {}),
                 ("y", "SomeExoticOp", ["x"], {})]
        with pytest.raises(UnsupportedTFOpError, match="SomeExoticOp"):
            importFrozenTF(tfproto.encode_graphdef(nodes))

    def test_imported_graph_is_trainable(self):
        """Imported constants can be promoted to variables and fine-tuned
        (≡ the reference's imported-BERT fine-tune path)."""
        rng = np.random.default_rng(2)
        data, _ = mlp_graphdef(rng)
        sd = importFrozenTF(data)
        sd.convertConstantsToVariables("w1", "b1", "w2")
        from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam
        labels = sd.placeHolder("labels", None, 3)
        loss = sd.loss.softmaxCrossEntropy("loss", labels,
                                           sd.getVariable("mm2"))
        sd.setLossVariables("loss")
        sd.setTrainingConfig(TrainingConfig.Builder().updater(Adam(1e-2))
                             .dataSetFeatureMapping("input")
                             .dataSetLabelMapping("labels").build())
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(3, size=16)]
        losses = [sd.fit(x, y) for _ in range(10)]
        assert losses[-1] < losses[0]


class TestControlFlow:
    def test_if_cond(self):
        sd = SameDiff.create()
        sd.placeHolder("x", 3)
        p = sd.placeHolder("p", 1)
        sd.ifCond("br", p, [sd.getVariable("x")],
                  lambda a: a * 2.0, lambda a: a - 1.0)
        x = np.ones(3, np.float32)
        hi = np.asarray(sd.outputSingle({"x": x, "p": [1.0]}, "br").jax())
        lo = np.asarray(sd.outputSingle({"x": x, "p": [0.0]}, "br").jax())
        assert np.allclose(hi, 2.0) and np.allclose(lo, 0.0)

    def test_while_loop(self):
        sd = SameDiff.create()
        a = sd.var("a", np.asarray([1.0], np.float32))
        outs = sd.whileLoop("w", [a], lambda v: (v < 100.0).all(),
                            lambda v: (v * 2.0,))
        assert float(sd.outputSingle({}, outs[0].name).jax()[0]) == 128.0

    def test_scan(self):
        sd = SameDiff.create()
        init = sd.constant("c0", np.float32(0.0))
        xs = sd.placeHolder("xs", 5)
        carry, ys = sd.scanLoop("s", init, xs, lambda c, x: (c + x, c + x))
        r = sd.output({"xs": np.arange(5, dtype=np.float32)},
                      [carry.name, ys.name])
        assert float(r[carry.name].jax()) == 10.0
        assert np.allclose(np.asarray(r[ys.name].jax()), [0, 1, 3, 6, 10])

    def test_for_loop(self):
        sd = SameDiff.create()
        a = sd.var("acc", np.zeros((2,), np.float32))
        outs = sd.forLoop("f", 4, [a], lambda i, v: (v + 1.0,))
        assert np.allclose(np.asarray(
            sd.outputSingle({}, outs[0].name).jax()), 4.0)

    def test_while_grad(self):
        """Control flow composes with jax.grad through the jitted graph."""
        sd = SameDiff.create()
        x = sd.placeHolder("x", 1)
        outs = sd.whileLoop("w", [x], lambda v: (v < 10.0).all(),
                            lambda v: (v * 2.0,))
        # d(final)/dx: final = x * 2^k, k data-dependent — check forward
        out = sd.outputSingle({"x": np.asarray([1.5], np.float32)},
                              outs[0].name)
        assert float(out.jax()[0]) == 12.0


class TestFrozenCnnOps:
    """Round-4 session 4: the frozen-CNN op tail — Conv2D, pools,
    FusedBatchNorm, ConcatV2, Pad, DepthwiseConv2dNative."""

    def test_conv_bn_pool_stack(self):
        rng = np.random.default_rng(3)
        w = rng.normal(size=(3, 3, 2, 4)).astype(np.float32)   # HWIO
        gamma = rng.normal(size=(4,)).astype(np.float32)
        beta = rng.normal(size=(4,)).astype(np.float32)
        mean = rng.normal(size=(4,)).astype(np.float32)
        var = np.abs(rng.normal(size=(4,))).astype(np.float32) + 0.5
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("w", "Const", [], {"value": w}),
            ("g", "Const", [], {"value": gamma}),
            ("b", "Const", [], {"value": beta}),
            ("m", "Const", [], {"value": mean}),
            ("v", "Const", [], {"value": var}),
            ("conv", "Conv2D", ["x", "w"],
             {"strides": [1, 1, 1, 1], "padding": "SAME"}),
            ("bn", "FusedBatchNormV3", ["conv", "g", "b", "m", "v"],
             {"epsilon": 1e-3}),
            ("act", "Relu", ["bn"], {}),
            ("pool", "MaxPool", ["act"],
             {"ksize": [1, 2, 2, 1], "strides": [1, 2, 2, 1],
              "padding": "VALID"}),
        ])
        sd = importFrozenTF(data)
        x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "pool").jax())
        assert got.shape == (2, 3, 3, 4)
        # numpy oracle
        import jax
        import jax.numpy as jnp
        conv = np.asarray(jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")))
        bn = (conv - mean) / np.sqrt(var + 1e-3) * gamma + beta
        act = np.maximum(bn, 0)
        want = act.reshape(2, 3, 2, 3, 2, 4).max(axis=(2, 4))
        assert np.allclose(got, want, atol=1e-4)

    def test_depthwise_and_avgpool(self):
        rng = np.random.default_rng(4)
        w = rng.normal(size=(3, 3, 2, 2)).astype(np.float32)  # (H,W,C,M)
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("w", "Const", [], {"value": w}),
            ("dw", "DepthwiseConv2dNative", ["x", "w"],
             {"strides": [1, 1, 1, 1], "padding": "SAME"}),
            ("ap", "AvgPool", ["dw"],
             {"ksize": [1, 4, 4, 1], "strides": [1, 4, 4, 1],
              "padding": "VALID"}),
        ])
        sd = importFrozenTF(data)
        x = rng.normal(size=(1, 4, 4, 2)).astype(np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "ap").jax())
        assert got.shape == (1, 1, 1, 4)   # C*M output channels
        # channel 0 of the depthwise out uses ONLY input channel 0
        x2 = x.copy()
        x2[..., 1] = 0.0
        got2 = np.asarray(sd.outputSingle({"x": x2}, "ap").jax())
        assert np.allclose(got[..., :2], got2[..., :2], atol=1e-5)

    def test_concat_and_pad(self):
        data = tfproto.encode_graphdef([
            ("a", "Placeholder", [], {}),
            ("b", "Placeholder", [], {}),
            ("axis", "Const", [], {"value": np.int32(-1)}),
            ("cat", "ConcatV2", ["a", "b", "axis"], {}),
            ("p", "Const", [],
             {"value": np.array([[0, 0], [1, 2]], np.int32)}),
            ("out", "Pad", ["cat", "p"], {}),
        ])
        sd = importFrozenTF(data)
        a = np.ones((2, 2), np.float32)
        b = 2 * np.ones((2, 3), np.float32)
        got = np.asarray(sd.outputSingle({"a": a, "b": b}, "out").jax())
        want = np.pad(np.concatenate([a, b], -1), [(0, 0), (1, 2)])
        assert np.array_equal(got, want)

    def test_nchw_rejected(self):
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("w", "Const", [], {"value": np.zeros((1, 1, 1, 1),
                                                  np.float32)}),
            ("conv", "Conv2D", ["x", "w"],
             {"strides": [1, 1, 1, 1], "padding": "SAME",
              "data_format": "NCHW"}),
        ])
        with pytest.raises(UnsupportedTFOpError, match="NHWC"):
            importFrozenTF(data)

    def test_concat_v1_axis_first(self):
        # v1 Concat: axis is the FIRST input
        data = tfproto.encode_graphdef([
            ("axis", "Const", [], {"value": np.int32(1)}),
            ("a", "Placeholder", [], {}),
            ("b", "Placeholder", [], {}),
            ("cat", "Concat", ["axis", "a", "b"], {}),
        ])
        sd = importFrozenTF(data)
        a = np.ones((2, 2), np.float32)
        b = 2 * np.ones((2, 3), np.float32)
        got = np.asarray(sd.outputSingle({"a": a, "b": b}, "cat").jax())
        np.testing.assert_array_equal(got, np.concatenate([a, b], 1))

    def test_explicit_padding_conv(self):
        w = np.ones((2, 2, 1, 1), np.float32)
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("w", "Const", [], {"value": w}),
            ("conv", "Conv2D", ["x", "w"],
             {"strides": [1, 1, 1, 1], "padding": "EXPLICIT",
              "explicit_paddings": [0, 0, 1, 0, 2, 0, 0, 0]}),
        ])
        sd = importFrozenTF(data)
        x = np.ones((1, 3, 3, 1), np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "conv").jax())
        # padded input is 4x5 -> VALID 2x2 conv gives 3x4
        assert got.shape == (1, 3, 4, 1)

    def test_bn_epsilon_default_matches_tf_opdef(self):
        # ADVICE r4: the TF OpDef default is 1e-4; a frozen graph with the
        # default-valued attr stripped must not import with a 10x epsilon
        rng = np.random.RandomState(3)
        gamma = rng.rand(2).astype(np.float32) + 0.5
        beta = rng.randn(2).astype(np.float32)
        mean = rng.randn(2).astype(np.float32)
        var = rng.rand(2).astype(np.float32) * 1e-3   # tiny var: eps matters
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("g", "Const", [], {"value": gamma}),
            ("b", "Const", [], {"value": beta}),
            ("m", "Const", [], {"value": mean}),
            ("v", "Const", [], {"value": var}),
            ("bn", "FusedBatchNormV3", ["x", "g", "b", "m", "v"], {}),
        ])
        sd = importFrozenTF(data)
        x = rng.normal(size=(2, 3, 3, 2)).astype(np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "bn").jax())
        want = (x - mean) / np.sqrt(var + 1e-4) * gamma + beta
        assert np.allclose(got, want, atol=1e-4), \
            np.abs(got - want).max()

    def test_training_mode_bn_rejected(self):
        z = np.zeros(1, np.float32)
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("g", "Const", [], {"value": z}), ("b", "Const", [], {"value": z}),
            ("m", "Const", [], {"value": z}), ("v", "Const", [], {"value": z}),
            ("bn", "FusedBatchNormV3", ["x", "g", "b", "m", "v"],
             {"is_training": True}),
        ])
        with pytest.raises(UnsupportedTFOpError, match="is_training"):
            importFrozenTF(data)

    def test_explicit_batch_padding_rejected(self):
        w = np.ones((2, 2, 1, 1), np.float32)
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("w", "Const", [], {"value": w}),
            ("conv", "Conv2D", ["x", "w"],
             {"strides": [1, 1, 1, 1], "padding": "EXPLICIT",
              "explicit_paddings": [1, 0, 1, 0, 2, 0, 0, 0]}),
        ])
        with pytest.raises(UnsupportedTFOpError, match="batch/channel"):
            importFrozenTF(data)


class TestSplitUnpackTail:
    """Round-5 TF importer tail: Split/SplitV/Unpack (multi-output ':N'
    refs), AddN, LeakyRelu, Softplus."""

    def test_split_equal_and_output_refs(self):
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("axis", "Const", [], {"value": np.asarray(1, np.int32)}),
            ("split", "Split", ["axis", "x"], {"num_split": 3}),
            ("y", "Sub", ["split:2", "split"], {}),   # out2 - out0
        ])
        sd = importFrozenTF(data)
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        got = np.asarray(sd.outputSingle({"x": x}, "y").jax())
        np.testing.assert_array_equal(got, x[:, 4:6] - x[:, 0:2])

    def test_splitv_sizes(self):
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("sizes", "Const", [], {"value": np.asarray([1, 3],
                                                        np.int32)}),
            ("axis", "Const", [], {"value": np.asarray(1, np.int32)}),
            ("sv", "SplitV", ["x", "sizes", "axis"], {}),
        ])
        sd = importFrozenTF(data)
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        outs = sd.output({"x": x}, ["sv", "sv:1"])
        np.testing.assert_array_equal(np.asarray(outs["sv"].jax()),
                                      x[:, :1])
        np.testing.assert_array_equal(np.asarray(outs["sv:1"].jax()),
                                      x[:, 1:])

    def test_unpack_addn_leakyrelu_softplus(self):
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("u", "Unpack", ["x"], {"axis": 0, "num": 2}),
            ("s", "AddN", ["u", "u:1"], {}),
            ("l", "LeakyRelu", ["s"], {"alpha": 0.1}),
            ("p", "Softplus", ["l"], {}),
        ])
        sd = importFrozenTF(data)
        x = np.asarray([[[1.0, -2.0], [3.0, -4.0]],
                        [[5.0, -6.0], [7.0, -8.0]]], np.float32)
        got = np.asarray(sd.outputSingle({"x": x}, "p").jax())
        s = x[0] + x[1]
        leaky = np.where(s > 0, s, 0.1 * s)
        np.testing.assert_allclose(got, np.log1p(np.exp(-np.abs(leaky)))
                                   + np.maximum(leaky, 0), rtol=1e-5)

    def test_split_roundtrips_through_serde(self, tmp_path):
        data = tfproto.encode_graphdef([
            ("x", "Placeholder", [], {}),
            ("axis", "Const", [], {"value": np.asarray(0, np.int32)}),
            ("sp", "Split", ["axis", "x"], {"num_split": 2}),
            ("y", "Add", ["sp", "sp:1"], {}),
        ])
        sd = importFrozenTF(data)
        x = np.random.default_rng(6).normal(size=(4, 3)).astype(np.float32)
        want = np.asarray(sd.outputSingle({"x": x}, "y").jax())
        art = tmp_path / "tfsplit.sdz"
        sd.save(art)
        got = np.asarray(SameDiff.load(art).outputSingle({"x": x},
                                                         "y").jax())
        np.testing.assert_array_equal(got, want)


def test_split_indivisible_and_leakyrelu_zero_alpha():
    data = tfproto.encode_graphdef([
        ("x", "Placeholder", [], {}),
        ("axis", "Const", [], {"value": np.asarray(1, np.int32)}),
        ("sp", "Split", ["axis", "x"], {"num_split": 2}),
    ])
    sd = importFrozenTF(data)
    with pytest.raises(ValueError, match="divisible"):
        sd.outputSingle({"x": np.zeros((2, 7), np.float32)}, "sp")
    data2 = tfproto.encode_graphdef([
        ("x", "Placeholder", [], {}),
        ("y", "LeakyRelu", ["x"], {"alpha": 0.0}),   # == plain relu
    ])
    sd2 = importFrozenTF(data2)
    got = np.asarray(sd2.outputSingle(
        {"x": np.asarray([-1.0, 2.0], np.float32)}, "y").jax())
    np.testing.assert_array_equal(got, [0.0, 2.0])
