"""Device-level observability: ProfileSession (on-demand XLA profiling
windows), device-memory telemetry + OOM forensics, and the step-time
attribution flight recorder — end-to-end through the trainers and the
UI server endpoints."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitoring as mon
from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.monitoring import memory as mon_memory
from deeplearning4j_tpu.monitoring import profiler as mon_profiler
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   NeuralNetConfiguration, OutputLayer, Sgd)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import MetricsListener


@pytest.fixture(autouse=True)
def _device_obs_clean():
    """Leave the process-global observability state as we found it:
    monitoring disabled, recorder/tracer empty, no armed session."""
    yield
    active = mon_profiler.active_session()
    if active is not None:
        active.finish()
    mon.disable()
    mon.get_tracer().clear()
    mon.step_recorder().clear()


def _mlp(n_in=4, n_out=2, seed=1, hidden=8):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1)).activation("relu")
            .list()
            .layer(DenseLayer.Builder().nOut(hidden).build())
            .layer(OutputLayer.Builder("mcxent").nOut(n_out)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _iterator(n_batches=5, batch=8, n_in=4, n_out=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_batches * batch, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[
        rng.integers(0, n_out, n_batches * batch)]
    return ArrayDataSetIterator(x, y, batch)


# -- ProfileSession --------------------------------------------------------
class TestProfileSession:
    @pytest.mark.slow   # suite diet (ISSUE 18): ~9 s profiled 6-step
    # fit; capture/report basics stay tier-1 via
    # test_finish_closes_short_window, and the registry/endpoint
    # surface via TestEndpoints::test_profile_and_steps_endpoints
    def test_armed_session_captures_k_steps_and_reports(self):
        net = _mlp()
        session = mon.profile_next_steps(3)
        assert mon_profiler.active_session() is session
        net.fit(_iterator(6), epochs=1, prefetch=0)
        # window closed itself after 3 steps, mid-fit
        assert session.state == "done", session.error
        assert mon_profiler.active_session() is None
        rep = session.report
        assert rep["steps"] == 3
        # the acceptance bar: a per-op table with >= 1 op
        assert rep["op_count"] >= 1 and len(rep["ops"]) >= 1
        top = rep["ops"][0]
        assert top["self_ms"] >= 0 and top["count"] >= 1
        assert top["category"]
        assert rep["device_self_ms"] > 0
        assert rep["categories"]
        assert mon.last_report() is rep
        # report published to the registry (dl4j.profile.*) and rendered
        reg = mon.get_registry()
        assert reg.get(mon.PROFILE_CAPTURED_STEPS).value == 3
        assert reg.get(mon.PROFILE_DEVICE_MS).value > 0
        assert reg.get(mon.PROFILE_SESSIONS).value >= 1
        text = session.render(top=5)
        assert "device self time" in text and "by category:" in text

    def test_finish_closes_short_window(self):
        net = _mlp()
        x = np.zeros((8, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        session = mon.profile_next_steps(50)
        net.fit(DataSet(x, y))
        net.fit(DataSet(x, y))
        assert session.state == "tracing"
        session.finish()
        assert session.state == "done", session.error
        assert session.report["steps"] == 2
        assert session.report["op_count"] >= 1

    def test_rearm_replaces_armed_session(self):
        s1 = mon.profile_next_steps(3)
        s2 = mon.profile_next_steps(4)
        assert mon_profiler.active_session() is s2
        # the replaced session is CLOSED, not left armed: a trainer
        # thread racing through step_start must find it finished, or it
        # would open a trace window nothing ever stops
        assert s1.state == "failed"
        s2.finish()   # never saw a step -> failed, deactivated
        assert s2.state == "failed"
        assert mon_profiler.active_session() is None

    def test_armed_but_no_fit_is_harmless(self):
        session = mon.profile_next_steps(3)
        assert session.state == "armed"
        session.finish()


# -- step-time attribution flight recorder ---------------------------------
class TestFlightRecorder:
    def test_disabled_monitoring_records_nothing(self):
        mon.step_recorder().clear()
        net = _mlp()
        net.fit(_iterator(3), epochs=1, prefetch=0)
        assert mon.step_recorder().records() == []
        assert mon.step_recorder().summary()["count"] == 0

    def test_attribution_sums_to_wall_time(self):
        """The acceptance bar: per-step phase times must sum to within
        20% of step wall time (coverage ~1.0). Uses a non-toy step (the
        microbench shape) so fixed per-step glue — span bookkeeping,
        loop overhead, OS jitter — is proportionally small, as it is in
        any real run."""
        mon.step_recorder().clear()
        net = _mlp(n_in=64, n_out=8, hidden=128)
        net.setListeners(MetricsListener())
        net.fit(_iterator(n_batches=55, batch=64, n_in=64, n_out=8),
                epochs=1, prefetch=0)
        rec = mon.step_recorder()
        recs = rec.records()
        assert len(recs) >= 50
        s = rec.summary()
        for phase in ("data_next", "stage", "dispatch", "listeners"):
            assert phase in s["phases"], s["phases"].keys()
            assert s["phases"][phase]["p50"] >= 0
        assert s["wall_ms"] and s["wall_ms"]["p50"] > 0
        assert s["coverage"] is not None
        assert 0.8 <= s["coverage"] <= 1.2, s["coverage"]

    def test_ring_is_bounded(self):
        rec = mon.step_recorder()
        rec.clear()
        mon.enable()
        for _ in range(rec.capacity + 50):
            rec.on_span("train.dispatch", 1.0)
            rec.on_span("train.listeners", 0.1)
        recs = rec.records()
        assert len(recs) == rec.capacity
        # oldest records dropped, step numbering continuous
        assert recs[-1]["step"] == rec.capacity + 50
        assert recs[0]["step"] == 51

    def test_compile_and_host_blocked_attribution(self):
        rec = mon.step_recorder()
        rec.clear()
        mon.enable()
        rec.on_span("fit.data_next", 2.0)
        rec.on_compile(0.5)
        rec.on_host_blocked(3.0)
        rec.on_span("train.dispatch", 10.0)
        rec.on_span("train.listeners", 1.0)
        (r,) = rec.records()
        assert r["compile_count"] == 1
        assert r["compile_ms"] == pytest.approx(500.0)
        assert r["host_blocked_ms"] == pytest.approx(3.0)
        assert r["phases"] == {"data_next": 2.0, "dispatch": 10.0,
                               "listeners": 1.0}

    def test_metrics_listener_exposes_records_and_feeds_histograms(self):
        mon.step_recorder().clear()
        net = _mlp()
        listener = MetricsListener(registry=MetricsRegistry())
        net.setListeners(listener)
        net.fit(_iterator(5), epochs=1, prefetch=0)
        assert len(listener.stepRecords()) == 5
        assert listener.stepSummary()["count"] == 5
        # per-step histograms land on the GLOBAL registry (the recorder
        # is process-global; per-listener registries only scope the
        # listener's own series)
        h = mon.get_registry().get(mon.STEP_PHASE_MS,
                                   labels={"phase": "dispatch"})
        assert h is not None and h.count >= 5


# -- device memory telemetry ----------------------------------------------
class TestMemoryTelemetry:
    def test_sample_records_footprint_and_last_sample(self):
        reg = MetricsRegistry()
        net = _mlp()
        snap = mon_memory.sample(reg, model=net)
        assert snap["devices"]   # virtual CPU devices enumerate
        assert snap["model"]["params_bytes"] > 0
        assert snap["model"]["opt_state_bytes"] >= 0
        assert mon_memory.last_sample() is snap
        assert reg.get(mon.MODEL_PARAMS_BYTES).value \
            == snap["model"]["params_bytes"]
        # CPU backend: memory_stats unsupported -> the gauge says so
        sup = reg.get(mon.DEVICE_MEMORY_SUPPORTED,
                      labels={"device": next(iter(snap["devices"]))})
        assert sup is not None and sup.value == 0.0

    def test_footprint_of_uninitialized_model(self):
        class Empty:
            pass
        fp = mon_memory.footprint(Empty())
        assert fp == {"params_bytes": 0, "opt_state_bytes": 0,
                      "layer_state_bytes": 0}

    def test_memory_monitor_thread_samples(self):
        import time
        mon.enable()
        reg = MetricsRegistry()
        m = mon_memory.MemoryMonitor(interval_s=0.05, registry=reg)
        m.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if reg.get(mon.HOST_RSS_BYTES) is not None:
                    break
                time.sleep(0.05)
        finally:
            m.stop()
        assert reg.get(mon.HOST_RSS_BYTES) is not None

    def test_crash_dump_embeds_telemetry_and_flight_recorder(self,
                                                             tmp_path):
        from deeplearning4j_tpu.util.crash_reporting import \
            CrashReportingUtil
        mon.enable()
        net = _mlp()
        net.setListeners(MetricsListener())
        net.fit(_iterator(3), epochs=1, prefetch=0)
        mon_memory.sample(model=net)
        path = str(tmp_path / "dump.txt")
        CrashReportingUtil.writeMemoryCrashDump(
            net, RuntimeError("RESOURCE_EXHAUSTED: out of memory"), path)
        text = open(path).read()
        assert "Device memory telemetry" in text
        assert "model footprint" in text
        assert "Step-time flight recorder:" in text
        assert "wall_ms p50=" in text


# -- UI server endpoints ---------------------------------------------------
class TestEndpoints:
    def test_profile_and_steps_endpoints(self):
        from deeplearning4j_tpu.ui.server import UIServer
        server = UIServer.getInstance().start(port=0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            # arm via POST /profile?steps=2
            req = urllib.request.Request(base + "/profile?steps=2",
                                         method="POST", data=b"")
            armed = json.loads(urllib.request.urlopen(
                req, timeout=10).read().decode())
            assert armed == {"armed": True, "steps": 2}
            st = json.loads(urllib.request.urlopen(
                base + "/profile", timeout=10).read().decode())
            assert st["active"]["state"] == "armed"
            assert st["active"]["steps"] == 2

            net = _mlp()
            net.setListeners(MetricsListener())
            mon.step_recorder().clear()
            net.fit(_iterator(4), epochs=1, prefetch=0)

            st = json.loads(urllib.request.urlopen(
                base + "/profile", timeout=10).read().decode())
            assert st["active"] is None
            assert st["last"]["state"] == "done", st["last"]["error"]
            assert len(st["last"]["report"]["ops"]) >= 1

            sd = json.loads(urllib.request.urlopen(
                base + "/steps", timeout=10).read().decode())
            assert sd["summary"]["count"] == 4
            assert len(sd["records"]) == 4
            assert "dispatch" in sd["summary"]["phases"]

            html = urllib.request.urlopen(
                base + "/", timeout=10).read().decode()
            assert "Device profile" in html
            assert "Step-time attribution" in html

            # POST to an unknown endpoint 404s without killing the server
            bad = urllib.request.Request(base + "/nonsense",
                                         method="POST", data=b"")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(bad, timeout=10)
        finally:
            server.stop()


# -- ProfilerListener (subsumed surface) -----------------------------------
class TestProfilerListenerDelegation:
    def test_listener_window_also_yields_report(self, tmp_path):
        from deeplearning4j_tpu.optimize import ProfilerListener
        trace_dir = str(tmp_path / "trace")
        net = _mlp()
        listener = ProfilerListener(trace_dir=trace_dir, start_iter=1,
                                    trace_iters=2)
        net.setListeners(listener)
        x = np.zeros((8, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        for _ in range(5):
            net.fit(DataSet(x, y))
        assert listener.report is not None
        assert listener.report["op_count"] >= 1
        # listener-driven windows count their own steps (the trainers'
        # hooks only drive the global ACTIVE session)
        assert listener.report["steps"] == 2
        # the trace artifact contract is unchanged (kept on disk)
        from deeplearning4j_tpu.optimize import xplane
        assert xplane.find_xplane_files(trace_dir)
