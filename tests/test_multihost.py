"""Two-process jax.distributed execution test (≡ dl4j-spark ::
SharedTrainingMaster actually running across workers — round-1 VERDICT:
the multi-host path was gated code that had never executed).

Spawns two REAL processes, each with 4 virtual CPU devices; the dp mesh
spans all 8 devices across both processes and the gradient all-reduce
rides the distributed backend (gRPC here; DCN on a TPU pod).
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sharded_trainer(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "DL4J_TPU_TESTS_REEXEC"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    outs = [str(tmp_path / f"w{i}.json") for i in (0, 1)]
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(i), str(port), outs[i]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in (0, 1)]
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=400)
        logs.append(out)
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"

    results = [json.load(open(o)) for o in outs]
    # both processes observed the identical (replicated) loss trajectory
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    # training made progress
    assert results[0]["losses"][-1] < results[0]["losses"][0]
    # replicated params agree bit-for-bit across processes
    assert results[0]["checksum"] == results[1]["checksum"]

    # cluster metrics plane (ISSUE 15): process 0's /metrics carries
    # BOTH hosts' series (host="0"/"1" labels) plus the cluster
    # aggregate, published over the real coordination KV
    cm = results[0]["cluster_metrics"]
    assert cm["host0"] and cm["host1"], cm
    assert cm["cluster_sum"] and cm["age_gauge"], cm
    # /health aggregates the per-host snapshot meta on process 0
    hc = results[0]["health_cluster"]
    assert hc["published"] == 2 and sorted(hc["hosts"]) == ["0", "1"]
    assert all(v is not None
               for v in results[0]["peer_steps_per_s"].values())
    # forced SLO breach flips health to degraded with the objective
    # named, then auto-recovers once the breach clears
    assert results[0]["slo_breach"]["status"] == "degraded"
    assert results[0]["slo_breach"]["violated"] == ["worker_p99"]
    assert results[0]["slo_recovered"]["status"] == "ok"
    assert results[0]["slo_recovered"]["violated"] == []

    # straggler plane (ISSUE 16): process 0 gathered BOTH hosts' step
    # timelines over the KV and named the artificially slowed peer —
    # host 1, dispatch phase — with the skew quantified
    r0 = results[0]
    assert r0["timeline_hosts"] == ["0", "1"]
    assert all("dispatch" in ph for ph in r0["timeline_phases"].values())
    assert r0["straggler"]["host"] == "1"
    assert r0["straggler"]["phase"] == "dispatch"
    assert r0["straggler"]["ratio"] > 2.0
    # the derived multi-process exchange exposure is the cross-host
    # dispatch skew (60 vs 5 ms feeds)
    assert 50.0 <= r0["derived_exchange_ms"] <= 60.0
    # HTTP surfaces on process 0: /stragglers names the culprit,
    # /steps carries every host's digest, /trace has one lane per host
    assert r0["http_stragglers"]["host"] == "1"
    assert r0["http_stragglers"]["phase"] == "dispatch"
    assert r0["http_steps_hosts"] == ["0", "1"]
    assert r0["trace_lanes"] == ["train host 0", "train host 1"]
    # straggler SLO: degraded with the culprit named, auto-recovered
    # once both hosts republished healthy digests
    assert r0["straggler_breach"]["status"] == "degraded"
    assert r0["straggler_breach"]["violated"] == ["straggler_ratio"]
    assert r0["straggler_breach"]["culprit"] == {"host": "1",
                                                 "phase": "dispatch"}
    assert r0["straggler_recovered"]["status"] == "ok"
    assert r0["straggler_recovered"]["violated"] == []


def test_orbax_restore_across_mesh_shape_change(tmp_path, devices8):
    """Elastic resume must re-place a checkpoint saved on one mesh layout
    onto a DIFFERENT mesh (shape change on restart — the elastic story)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.parallel.elastic import ElasticCheckpointer

    rng = np.random.default_rng(3)
    W = rng.standard_normal((16, 32)).astype(np.float32)
    b = rng.standard_normal((32,)).astype(np.float32)

    # save under a 1-D dp=8 mesh, W sharded over rows
    mesh_a = Mesh(np.array(devices8), ("dp",))
    params_a = {
        "W": jax.device_put(W, NamedSharding(mesh_a, P("dp", None))),
        "b": jax.device_put(b, NamedSharding(mesh_a, P())),
    }
    ck = ElasticCheckpointer(tmp_path / "ck")
    ck.save(7, params_a, wait=True)

    # restore under a 2-D dp=2 x tp=4 mesh, W sharded over COLUMNS now
    mesh_b = Mesh(np.array(devices8).reshape(2, 4), ("dp", "tp"))
    like = {
        "W": jax.device_put(jnp.zeros_like(W),
                            NamedSharding(mesh_b, P(None, "tp"))),
        "b": jax.device_put(jnp.zeros_like(b), NamedSharding(mesh_b, P())),
    }
    step, state = ck.restore(like={"params": like})
    ck.close()
    assert step == 7
    got = state["params"]
    np.testing.assert_array_equal(np.asarray(got["W"]), W)
    np.testing.assert_array_equal(np.asarray(got["b"]), b)
    # and the restored arrays carry the NEW mesh's sharding
    assert got["W"].sharding.spec == P(None, "tp")
    assert got["W"].sharding.mesh.shape == {"dp": 2, "tp": 4}
