"""Elastic chaos worker: true mid-run join / leave / replace across
REAL process boundaries.

Run as:  python elastic_worker.py <pid> <kv_port> <out_json> <ckpt_dir>
             <mode>

Each worker is an INDEPENDENT single-process jax instance (its own 8
virtual CPU devices — `jax.distributed` cannot lose a member, see
kv_server.py); the coordination plane (heartbeats, membership
announcements, admission tickets, barriers) rides the harness-owned TCP
KV, and the checkpoint warm-start rides the shared filesystem. The dp
mesh is `mesh_factory(members)` → 4 local devices per member (capped at
8), so re-forms exercise real mesh narrowing/widening; batches are
keyed by the step number and `compress=False`, so every host computes
the same full-batch mean gradient regardless of width and a chaos run
must land within float-accumulation distance of a fixed-membership
reference.

mode (worker 0 always runs "clean"):
  clean     — pre-wired member [0, 1]: train to TOTAL, write params
  die@N     — hard-exit (os._exit 27) before step N: the survivor must
              re-form on the reduced roster and keep training from the
              newest verified checkpoint
  leave@N   — request_leave() at step N: drain-clean exit at the agreed
              boundary ("left" marker, exit 0)
  join      — a (re)started host: announce, await admission, warm-start
              from the drain checkpoint, train to TOTAL in lockstep
"""
import json
import os
import sys
import time

pid = int(sys.argv[1])
kv_port = int(sys.argv[2])
out_path = sys.argv[3]
ckpt_dir = sys.argv[4]
mode = sys.argv[5]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
for k in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(k, None)

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel.multihost import (ElasticMembership,
                                                   MultiHostRunner,
                                                   MultiHostTrainer,
                                                   PeerCoordinator,
                                                   global_batch)
from deeplearning4j_tpu.resilience.errors import PreemptionSignal
from jax.sharding import Mesh
from kv_server import TcpKV

TOTAL, SYNC, SAVE = 40, 2, 4
PEER_TIMEOUT = 8.0


def loss_fn(params, batch, rng_key):
    h = jnp.tanh(batch["x"] @ params["W1"])
    return jnp.mean(h * h)


def mesh_factory(members):
    n = min(4 * len(members), 8)
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def trainer_factory(mesh):
    return MultiHostTrainer(loss_fn, Sgd(0.3), mesh=mesh, compress=False)


def make_batch(trainer, step):
    r = np.random.default_rng(1000 + step)
    xs = r.standard_normal((8, 6)).astype(np.float32)
    return global_batch(trainer.mesh, {"x": xs})


def init_params():
    r = np.random.default_rng(0)
    return {"W1": (r.standard_normal((6, 5)) * 0.5).astype(np.float32)}


kv = TcpKV("localhost", kv_port)
coordinator = PeerCoordinator(sync_every=SYNC, peer_timeout=PEER_TIMEOUT,
                              client=kv, process_id=pid, num_processes=2,
                              dump_dir=os.path.dirname(out_path))

result = {"pid": pid, "mode": mode}
die_at = leave_at = None
if mode.startswith("die@"):
    die_at = int(mode.split("@")[1])
elif mode.startswith("leave@"):
    leave_at = int(mode.split("@")[1])

try:
    if mode == "join":
        runner, params, opt_state = MultiHostRunner.join_cluster(
            trainer_factory, ckpt_dir, coordinator, mesh_factory,
            init_params(), timeout=90.0, save_every=SAVE,
            monitor=False, sigterm=False)
        result["joined_at"] = runner.step
        print(f"worker {pid} joined at step {runner.step}", flush=True)
    else:
        membership = ElasticMembership(coordinator, members=[0, 1])
        runner = MultiHostRunner(
            trainer_factory(mesh_factory([0, 1])), ckpt_dir, coordinator,
            save_every=SAVE, elastic=True, mesh_factory=mesh_factory,
            membership=membership, monitor=False, sigterm=False)
        params, opt_state = runner.resume_or_init(init_params())
        result["resumed_at"] = runner.resumed_step

    left = False
    while runner.step < TOTAL:
        if die_at is not None and runner.step >= die_at:
            print(f"worker {pid} dying at step {runner.step}", flush=True)
            sys.stdout.flush()
            os._exit(27)
        if leave_at is not None and not left and runner.step >= leave_at:
            runner.request_leave()
            left = True
            print(f"worker {pid} announced leave at {runner.step}",
                  flush=True)
        if len(coordinator.members) == 1 and runner.step == TOTAL - 6:
            # solo survivor: hold the last stretch open so a restarted
            # peer's announcement (cold python+jax boot) can land — the
            # admission itself happens at the next sync inside fit_batch
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline and \
                    not kv.key_value_dir_get(coordinator._key("em/join/")):
                time.sleep(0.25)
        params, opt_state, loss = runner.fit_batch(
            params, opt_state, make_batch(runner.trainer, runner.step))
        print(f"worker {pid} step {runner.step} "
              f"members {len(coordinator.members)}", flush=True)
    runner.finalize(params, opt_state)
    result.update(done=True, steps=runner.step,
                  members=list(coordinator.members),
                  replaces=runner._replaces,
                  params={k: np.asarray(jax.device_get(v)).tolist()
                          for k, v in params.items()})
except PreemptionSignal as e:
    result.update(left=True, step=runner.step, reason=str(e))
    runner.close()
except BaseException as e:  # noqa: BLE001 — persist the evidence first
    import traceback
    result.update(crashed=repr(e), traceback=traceback.format_exc())
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("worker", pid, "CRASH:", repr(e), flush=True)
    sys.stdout.flush()
    os._exit(1)

with open(out_path, "w") as f:
    json.dump(result, f)
print("worker", pid, "exit:",
      {k: v for k, v in result.items() if k != "params"}, flush=True)
