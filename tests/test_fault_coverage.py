"""Tier-1 gate for scripts/check_fault_coverage.py: every fault
injection site declared in resilience/faults.py must be exercised by
at least one test, so a new site cannot ship untested (the same
run-the-lint-in-CI pattern as test_fastpath_lint.py)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import check_fault_coverage as cfc  # noqa: E402

from deeplearning4j_tpu.resilience import faults  # noqa: E402


def test_every_declared_site_is_covered():
    missing = cfc.uncovered_sites()
    assert missing == [], (
        "fault sites with no exercising test: "
        + ", ".join(f"{n} ({s})" for n, s in missing))


def test_declared_sites_match_the_harness():
    """The AST scrape agrees with what the faults module actually
    exports — a site constant the scrape misses would silently escape
    the coverage gate."""
    sites = cfc.declared_sites()
    exported = {n: getattr(faults, n) for n in faults.__all__
                if isinstance(getattr(faults, n), str)
                and cfc._SITE_RE.fullmatch(getattr(faults, n))}
    assert sites == exported
    assert "GENERATION_STEP" in sites and "CACHE_GROW" in sites


def test_detects_an_uncovered_site():
    sites = {"FAKE_SITE": "totally.uncovered"}
    sources = {"tests/test_x.py": "def test_nothing():\n    pass\n"}
    missing = cfc.uncovered_sites(sites, sources)
    assert missing == [("FAKE_SITE", "totally.uncovered")]
    # covered by constant name OR by the literal site string
    by_name = {"tests/test_x.py": "plan.fail_at(faults.FAKE_SITE, 1)"}
    assert cfc.uncovered_sites(sites, by_name) == []
    by_literal = {"tests/test_x.py": 'plan.fail_at("totally.uncovered")'}
    assert cfc.uncovered_sites(sites, by_literal) == []
