"""Zoo model tests (≡ deeplearning4j-zoo :: TestInstantiation — each
model builds, forwards the right shape, and takes a train step; tiny
input shapes keep the 1-vCPU suite fast)."""
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (AlexNet, Darknet19,
                                           InceptionResNetV1, LeNet,
                                           ResNet50, SimpleCNN, SqueezeNet,
                                           TextGenerationLSTM, TinyYOLO,
                                           UNet, VGG16, VGG19, Xception,
                                           ZooModel)


def _rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def _onehot(n, k, seed=0):
    return np.eye(k, dtype=np.float32)[
        np.random.default_rng(seed).integers(k, size=n)]


# (model ctor, input shape HWC, numClasses) — shapes shrunk for CPU.
# Darknet19, Xception and SqueezeNet (~13-15 s builds each, tier-1
# diet) run in the slow set; the equally-shaped InceptionResNetV1 row
# keeps the graph-model coverage in the fast lane.
SMALL_MODELS = [
    (lambda: LeNet(numClasses=10), (28, 28, 1), 10),
    (lambda: SimpleCNN(numClasses=5, inputShape=(32, 32, 3)), (32, 32, 3), 5),
    (lambda: AlexNet(numClasses=7, inputShape=(64, 64, 3)), (64, 64, 3), 7),
    pytest.param(
        lambda: Darknet19(numClasses=6, inputShape=(64, 64, 3)),
        (64, 64, 3), 6, marks=pytest.mark.slow),
    pytest.param(
        lambda: SqueezeNet(numClasses=4, inputShape=(64, 64, 3)),
        (64, 64, 3), 4, marks=pytest.mark.slow),
    pytest.param(
        lambda: Xception(numClasses=4, inputShape=(64, 64, 3),
                         middleFlowBlocks=1), (64, 64, 3), 4,
        marks=pytest.mark.slow),
    (lambda: InceptionResNetV1(numClasses=4, inputShape=(64, 64, 3),
                               blocks=(1, 1, 1)), (64, 64, 3), 4),
]


class TestInstantiation:
    @pytest.mark.parametrize("ctor,shape,ncls", SMALL_MODELS,
                             ids=lambda p: getattr(p, "__name__", str(p)))
    def test_build_forward_fit(self, ctor, shape, ncls):
        model = ctor()
        net = model.init()
        x = _rand((2,) + shape)
        out = net.output(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
        y = np.asarray(out)
        assert y.shape == (2, ncls)
        assert np.allclose(y.sum(-1), 1.0, atol=1e-4)  # softmax head
        net.fit(x, _onehot(2, ncls))
        assert np.isfinite(float(net.score()))

    def test_vgg16_vgg19_depths(self):
        # conv layer count is the models' defining difference: 13 vs 16
        c16 = sum(l.__class__.__name__ == "ConvolutionLayer"
                  for l in VGG16(numClasses=3,
                                 inputShape=(32, 32, 3)).conf().layers)
        c19 = sum(l.__class__.__name__ == "ConvolutionLayer"
                  for l in VGG19(numClasses=3,
                                 inputShape=(32, 32, 3)).conf().layers)
        assert (c16, c19) == (13, 16)

    def test_vgg19_forward(self):
        net = VGG19(numClasses=3, inputShape=(32, 32, 3)).init()
        y = np.asarray(net.output(_rand((2, 32, 32, 3))))
        assert y.shape == (2, 3)

    def test_resnet50_block_count(self):
        conf = ResNet50(numClasses=4, inputShape=(64, 64, 3)).conf()
        adds = [n for n in conf.nodes if n.endswith("_add")]
        assert len(adds) == 16  # 3+4+6+3 bottlenecks

    def test_unet_mask_output(self):
        net = UNet(numClasses=1, inputShape=(32, 32, 3)).init()
        out = net.output(_rand((1, 32, 32, 3)))
        y = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
        assert y.shape == (1, 32, 32, 1)
        assert (y >= 0).all() and (y <= 1).all()  # sigmoid pixels

    def test_tinyyolo_head_shape(self):
        m = TinyYOLO(numClasses=3, boxes=5, inputShape=(64, 64, 3))
        net = m.init()
        y = np.asarray(net.output(_rand((1, 64, 64, 3))))
        # 5 pools: 64→2; head channels B*(5+C)
        assert y.shape == (1, 2, 2, 5 * (5 + 3))

    def test_textgen_lstm(self):
        m = TextGenerationLSTM(numClasses=20, lstmLayerSize=32)
        net = m.init()
        x = _rand((2, 7, 20))
        y = np.asarray(net.output(x))
        assert y.shape == (2, 7, 20)

    def test_pretrained_gated(self):
        with pytest.raises(RuntimeError, match="egress"):
            LeNet().initPretrained()
        assert not LeNet().pretrainedAvailable("imagenet")


class TestNASNet:
    @pytest.mark.slow   # suite diet (ISSUE 14): ~9 s build+train —
    # the zoo build-forward-fit class stays tier-1 via
    # TestInstantiation's fast rows (incl. the graph-model
    # SqueezeNet/InceptionResNetV1); NASNet-specific wiring runs in
    # the slow set like Darknet19/Xception/EfficientNet
    def test_builds_and_trains(self):
        from deeplearning4j_tpu.models.zoo import NASNet
        m = NASNet(numClasses=4, inputShape=(32, 32, 3), numBlocks=1,
                   filters=8, stemFilters=8)
        net = m.init()
        x = _rand((2, 32, 32, 3))
        out = net.output(x)
        y = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
        assert y.shape == (2, 4)
        net.fit(x, _onehot(2, 4))
        assert np.isfinite(float(net.score()))


class TestEfficientNet:
    @pytest.mark.slow   # ~23 s compile soak (full B0 graph + grads on
    #                     1 vCPU); TestInstantiation still covers the
    #                     EfficientNet builder path in tier-1
    def test_b0_builds_forwards_and_trains(self):
        from deeplearning4j_tpu.models.zoo import EfficientNet
        net = EfficientNet("B0", numClasses=4,
                           inputShape=(64, 64, 3)).init()
        x = _rand((2, 64, 64, 3))
        y = np.asarray(net.output(x))
        assert y.shape == (2, 4)
        assert np.allclose(y.sum(-1), 1.0, atol=1e-4)
        net.fit(x, _onehot(2, 4))
        assert np.isfinite(float(net.score()))

    def test_compound_scaling(self):
        from deeplearning4j_tpu.models.zoo import EfficientNet
        # filter rounding matches the reference rule (divisor 8, >=90%)
        assert EfficientNet._round_filters(32, 1.0) == 32
        assert EfficientNet._round_filters(32, 1.1) == 32   # 35.2 -> 32
        assert EfficientNet._round_filters(320, 1.4) == 448
        assert EfficientNet._round_repeats(3, 1.8) == 6      # ceil(5.4)
        # B2 widens and deepens vs B0
        b0 = EfficientNet("B0", numClasses=3, inputShape=(32, 32, 3)).conf()
        b2 = EfficientNet("B2", numClasses=3, inputShape=(32, 32, 3)).conf()
        assert len(b2.nodes) > len(b0.nodes)
        assert EfficientNet("B4", numClasses=2).DEFAULT_INPUT == (380, 380, 3)

    def test_unknown_variant_rejected(self):
        from deeplearning4j_tpu.models.zoo import EfficientNet
        with pytest.raises(ValueError, match="variant"):
            EfficientNet("B9")

    def test_se_gating_present(self):
        from deeplearning4j_tpu.models.zoo import EfficientNet
        conf = EfficientNet("B0", numClasses=3,
                            inputShape=(32, 32, 3)).conf()
        muls = [n for n in conf.nodes if n.endswith("_se_mul")]
        adds = [n for n in conf.nodes if n.endswith("_add")]
        # B0: 16 MBConv blocks, each SE-gated; residuals where stride-1
        assert len(muls) == 16
        assert len(adds) == 9   # repeats beyond the first of each stage

    def test_variant_dropout_scales(self):
        from deeplearning4j_tpu.models.zoo import EfficientNet
        assert EfficientNet("B0", numClasses=2).dropout_rate == 0.2
        assert EfficientNet("B7", numClasses=2).dropout_rate == 0.5
        for variant, retain in (("B0", 0.8), ("B7", 0.5)):
            conf = EfficientNet(variant, numClasses=3,
                                inputShape=(32, 32, 3)).conf()
            drop = conf.nodes["drop"].ref   # retain probability = 1 - rate
            assert abs(drop.dropOut - retain) < 1e-9
