"""FleetRouter functional surface: health-driven routing across
GenerationServer replicas, the `adopt()` admission hook behind it, the
zero-admissions rule for burn-breached replicas, deadline propagation,
the autoscale signal, the cross-host replica registry, and the `/fleet`
observability endpoint.

The load-bearing invariant everything here leans on: a stream is a
pure function of (server seed, admission id, prompt, sampling config),
and the router assigns FLEET-wide admission ids over seed-aligned
replicas — so fleet output is bit-identical to the same workload on a
single bare server, whatever the replica count (the chaos twin of this
file extends that through mid-stream replica kills).
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitoring as mon
from deeplearning4j_tpu.generation import (FleetRouter, GenerationRequest,
                                           GenerationServer)
from deeplearning4j_tpu.generation import fleet as fleet_mod
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel.coordination import LocalKV, PeerCoordinator
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import (InferenceOverloadedError,
                                                  InferenceTimeoutError)

V = 16


@pytest.fixture(autouse=True)
def _clean_harness():
    faults.clear_plan()
    yield
    faults.clear_plan()
    mon.disable()


#: module-scoped on-disk executable cache: the FIRST server warmup
#: pays the XLA compiles, every later replica (and every supervisor
#: replacement) deserializes from disk
_CACHE = {"dir": None}


@pytest.fixture(scope="module", autouse=True)
def _exec_cache(tmp_path_factory):
    _CACHE["dir"] = str(tmp_path_factory.mktemp("fleet-exec"))
    yield
    _CACHE["dir"] = None


def _lstm_net(seed=3, hidden=16):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
         .weightInit("xavier").list()
         .layer(LSTM(nOut=hidden, activation="tanh"))
         .layer(RnnOutputLayer(lossFunction="mcxent", nOut=V,
                               activation="softmax"))
         .setInputType(InputType.recurrent(V)).build())).init()


@pytest.fixture(scope="module")
def net():
    return _lstm_net()


def _server(net, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("cache_lengths", [48])
    kw.setdefault("prompt_buckets", [8])
    kw.setdefault("method", "greedy")
    kw.setdefault("seed", 11)
    kw.setdefault("exec_cache_dir", _CACHE["dir"])
    return GenerationServer(net, **kw)


def _fleet(net, n=3, **kw):
    return FleetRouter(factory=lambda i: _server(net), num_replicas=n,
                       **kw)


#: mixed sampling configs: temperature/top-k requests prove the rng
#: identity (seed, admit id) survives routing, not just argmax
_WORKLOAD = [
    dict(prompt=[1, 2, 3], max_new_tokens=8),
    dict(prompt=[5, 4], max_new_tokens=10, method="sample",
         temperature=0.8),
    dict(prompt=[7, 3, 2, 1], max_new_tokens=12, method="top_k",
         temperature=0.9, top_k=3),
    dict(prompt=[2, 2, 5], max_new_tokens=6),
]


@pytest.fixture(scope="module")
def want_streams(net):
    """Fault-free single-server baseline for the shared workload, in
    the same submission order the fleet tests use."""
    srv = _server(net)
    srv.warmup()
    try:
        reqs = [srv.submit(**dict(w)) for w in _WORKLOAD]
        return [list(r.stream(timeout=60)) for r in reqs]
    finally:
        srv.shutdown()


# -- the adopt() hook (server side of the router contract) ----------------

def test_adopt_matches_submit_stream(net):
    """adopt() under an explicit admission id reproduces submit()'s
    stream exactly: admission ids, not admission order, drive the
    per-request rng."""
    srv = _server(net)
    srv.warmup()
    want = list(srv.submit(**dict(_WORKLOAD[0])).stream(timeout=60))
    srv.shutdown()
    srv2 = _server(net)
    srv2.warmup()
    try:
        w = dict(_WORKLOAD[0])
        req = GenerationRequest(np.asarray(w["prompt"], np.int32),
                                w["max_new_tokens"], None, 0, 1.0, 0)
        srv2.adopt(req, admit_id=1)
        assert list(req.stream(timeout=60)) == want
    finally:
        srv2.shutdown()


def test_adopt_with_delivered_prefix_streams_continuation_only(net):
    """A failover re-submission carries the delivered prefix: the
    adopting server replays it SUPPRESSED — the stream yields only the
    continuation, and the final token list is bit-identical."""
    srv = _server(net)
    srv.warmup()
    want = list(srv.submit(**dict(_WORKLOAD[0])).stream(timeout=60))
    srv.shutdown()
    srv2 = _server(net)
    srv2.warmup()
    try:
        w = dict(_WORKLOAD[0])
        req = GenerationRequest(np.asarray(w["prompt"], np.int32),
                                w["max_new_tokens"], None, 0, 1.0, 0)
        req.tokens = list(want[:3])
        srv2.adopt(req, admit_id=1)
        assert list(req.stream(timeout=60)) == want[3:]
        assert req.tokens == want
    finally:
        srv2.shutdown()


def test_adopt_with_terminal_prefix_finishes_immediately(net):
    """A prefix that already exhausted the token budget needs no decode
    at all — the adopting server just closes the request."""
    srv = _server(net)
    srv.warmup()
    want = list(srv.submit(**dict(_WORKLOAD[0])).stream(timeout=60))
    srv.shutdown()
    srv2 = _server(net)
    srv2.warmup()
    try:
        w = dict(_WORKLOAD[0])
        req = GenerationRequest(np.asarray(w["prompt"], np.int32),
                                w["max_new_tokens"], None, 0, 1.0, 0)
        req.tokens = list(want)
        srv2.adopt(req, admit_id=1)
        assert list(req.stream(timeout=60)) == []
        assert req.finish_reason == "length"
    finally:
        srv2.shutdown()


# -- routing ---------------------------------------------------------------

def test_fleet_streams_bit_identical_to_single_server(net, want_streams):
    """The tentpole identity: a 3-replica fleet serves the workload
    bit-identically to one bare server, and every admission went
    through exactly one replica."""
    with _fleet(net) as router:
        reqs = [router.submit(**dict(w)) for w in _WORKLOAD]
        got = [list(r.stream(timeout=60)) for r in reqs]
        assert got == want_streams
        st = router.status()
        assert sum(r["routed"] for r in st["replicas"]) == len(_WORKLOAD)
        assert st["completed"] == len(_WORKLOAD)
        assert st["failovers"] == 0 and st["failed"] == 0
        # warm spin-up: replicas 2 and 3 deserialized from replica 1's
        # disk writes — the fleet never compiled the same shape twice
        for rep in router._replicas[1:]:
            assert rep.server._store.stats["compiles"] == 0


def test_routing_spreads_load_least_loaded_first(net):
    """With every replica healthy and idle the router spreads the
    workload instead of piling onto one replica."""
    with _fleet(net) as router:
        reqs = [router.submit(**dict(_WORKLOAD[i % len(_WORKLOAD)]))
                for i in range(6)]
        for r in reqs:
            r.result(timeout=60)
        routed = [rep.routed for rep in router._replicas]
        assert sum(routed) == 6
        assert all(n >= 1 for n in routed), routed


def test_submit_validation_mirrors_server(net):
    with _fleet(net, n=1) as router:
        with pytest.raises(ValueError):
            router.submit(prompt=[])
        with pytest.raises(ValueError):
            router.submit(prompt=list(range(9)))      # > top bucket
        with pytest.raises(ValueError):
            router.submit(prompt=[1], max_new_tokens=0)
        with pytest.raises(ValueError):
            router.submit(prompt=[1], max_new_tokens=64)  # > top rung


def test_replicas_must_be_seed_aligned(net):
    a = _server(net, seed=11)
    b = _server(net, seed=12)
    try:
        with pytest.raises(ValueError, match="bit-identical"):
            FleetRouter(replicas=[a, b])
    finally:
        a.shutdown()
        b.shutdown()


# -- health gating ---------------------------------------------------------

def test_burn_breached_replica_gets_zero_admissions_until_recovery(net):
    """THE acceptance counter: a burn-rate-breached replica receives
    no new admissions while breached (events.REPLICA_UNHEALTHY marks
    the transition), and rejoins the pool once its windows age out."""
    mon.enable()
    clk = {"t": 100.0}
    with _fleet(net, clock=lambda: clk["t"]) as router:
        victim = router._replicas[0]
        # drive the victim's gauge over budget: all-failure windows
        for _ in range(6):
            victim.gauge.record(clk["t"], bad=True)
        assert victim.health(clk["t"]) == "unhealthy"
        before = victim.routed
        reqs = [router.submit(**dict(_WORKLOAD[i % len(_WORKLOAD)]))
                for i in range(4)]
        for r in reqs:
            r.result(timeout=60)
        assert victim.routed == before, \
            "a burn-breached replica must receive ZERO admissions"
        from deeplearning4j_tpu.monitoring import events
        kinds = [e["kind"]
                 for e in events.snapshot(last=None)["events"]]
        assert events.REPLICA_UNHEALTHY in kinds
        # recovery: bad samples age out of the long window
        clk["t"] += 30.0
        assert victim.health(clk["t"]) == "healthy"
        reqs = [router.submit(**dict(_WORKLOAD[0])) for _ in range(3)]
        for r in reqs:
            r.result(timeout=60)
        assert victim.routed > before, \
            "a recovered replica must rejoin the admission pool"


def test_pressure_degraded_replica_not_admitted(net):
    """The pressure ladder feeds routing: a degraded replica is
    skipped while healthy peers remain (shed-to-healthy)."""
    with _fleet(net, n=2) as router:
        victim = router._replicas[0]
        victim.server._pressure = 1
        victim.server._pressure_ts = time.monotonic()
        reqs = [router.submit(**dict(_WORKLOAD[0])) for _ in range(3)]
        for r in reqs:
            r.result(timeout=60)
        assert victim.routed == 0
        assert router._replicas[1].routed == 3
        assert router.fleet_state()["state"] == "degraded"


def test_all_degraded_sheds_typed(net):
    """Shed-to-floor: zero healthy replicas (but live ones) refuses
    typed instead of admitting to a degrading replica — and does NOT
    latch the fleet dead."""
    with _fleet(net, n=1) as router:
        router._replicas[0].server._pressure = 2
        router._replicas[0].server._pressure_ts = time.monotonic()
        req = router.submit(**dict(_WORKLOAD[0]))
        with pytest.raises(InferenceOverloadedError):
            req.result(timeout=30)
        assert router.status()["shed"] == 1
        assert router._dead is None
        # recovery: pressure clears, the same fleet serves again
        router._replicas[0].server._pressure = 0
        assert router.submit(
            **dict(_WORKLOAD[0])).result(timeout=60) is not None


def test_expired_deadline_fails_typed_before_dispatch(net):
    with _fleet(net, n=1) as router:
        req = router.submit(**dict(_WORKLOAD[0]), timeout_ms=-1.0)
        with pytest.raises(InferenceTimeoutError):
            req.result(timeout=30)


# -- observability / autoscale / registry ----------------------------------

def test_request_timeline_carries_route_entries(net):
    mon.enable()
    with _fleet(net, n=2) as router:
        req = router.submit(**dict(_WORKLOAD[0]))
        req.result(timeout=60)
        assert req.trace is not None and req.trace.kind == "fleet"
        evs = [e["event"] for e in req.trace.snapshot()["events"]]
        assert "route" in evs


def test_fleet_metrics_emitted_under_monitoring(net):
    mon.enable()
    with _fleet(net, n=2) as router:
        router.submit(**dict(_WORKLOAD[0])).result(timeout=60)
        router.autoscale()
        names = set(mon.get_registry().snapshot())
        assert mon.FLEET_ROUTED in names
        assert mon.FLEET_HEALTHY in names
        assert mon.FLEET_DESIRED_REPLICAS in names


def test_autoscale_signal_shape_and_floor(net):
    with _fleet(net, n=2) as router:
        sig = router.autoscale()
        assert sig["replicas_live"] == 2
        assert sig["replicas_healthy"] == 2
        assert sig["desired_replicas"] >= 1
        assert 0.0 <= sig["utilization"] <= 1.0
        assert sig["slo_burn"] >= 1.0
        # a dead pool asks for a full replacement roster
        for rep in router._replicas:
            rep.server._pressure = 3
            rep.server._pressure_ts = time.monotonic()
        assert router.autoscale()["replicas_healthy"] == 0


def test_fleet_status_and_health_snapshot(net):
    with _fleet(net, n=2) as router:
        router.submit(**dict(_WORKLOAD[0])).result(timeout=60)
        st = router.status()
        assert {r["name"] for r in st["replicas"]} == {"r0", "r1"}
        assert all(r["health"] == "healthy" for r in st["replicas"])
        fs = router.fleet_state()
        assert fs["state"] == "serving"
        from deeplearning4j_tpu import resilience
        snap = resilience.health_snapshot()
        assert snap["fleet"] is not None
        assert any(f["state"] == "serving" for f in snap["fleet"])
        assert fleet_mod.status()["routers"]


def test_replica_registry_publishes_over_coordination_kv(net):
    """The cross-host half: each process publishes its replica roster
    under fleet/<pid>; directory() merges the views."""
    kv = LocalKV()
    c0 = PeerCoordinator(sync_every=2, client=kv, process_id=0,
                         num_processes=2)
    c1 = PeerCoordinator(sync_every=2, client=kv, process_id=1,
                         num_processes=2)
    with _fleet(net, n=2) as router:
        doc = router.publish(coordinator=c0)
        assert doc["process_id"] == 0
        router.publish(coordinator=c1)
        view = fleet_mod.directory(coordinator=c0)
        assert set(view) == {"0", "1"}
        assert len(view["0"]["replicas"]) == 2
        assert view["1"]["autoscale"]["desired_replicas"] >= 1


def test_fleet_endpoint_serves_router_status(net):
    from deeplearning4j_tpu.ui.server import UIServer
    with _fleet(net, n=2) as router:
        router.submit(**dict(_WORKLOAD[0])).result(timeout=60)
        server = UIServer.getInstance()
        server.start(port=0)
        try:
            base = f"http://127.0.0.1:{server.port}"
            data = json.loads(urllib.request.urlopen(
                base + "/fleet", timeout=10).read().decode())
            routers = data["routers"]
            assert routers and len(routers[0]["replicas"]) == 2
            assert routers[0]["autoscale"]["desired_replicas"] >= 1
        finally:
            server.stop()


def test_shutdown_refuses_new_submits(net):
    router = _fleet(net, n=1)
    router.warmup()
    router.submit(**dict(_WORKLOAD[0])).result(timeout=60)
    router.shutdown()
    with pytest.raises(RuntimeError):
        router.submit(**dict(_WORKLOAD[0]))


def test_idle_replica_death_revived_off_the_dispatch_path(net):
    """An IDLE replica that dies (no in-flight stream to observe it)
    is revived by a background supervision kick from the next routed
    request — the dispatch itself lands on a healthy survivor and the
    roster returns to full strength without draining the fleet."""
    with _fleet(net, n=2) as router:
        victim = router._replicas[1]
        victim.server._die(RuntimeError("idle chaos kill"))
        assert victim.health(time.monotonic()) == "dead"
        # a routed request kicks the reviver and is served elsewhere
        assert router.submit(**dict(_WORKLOAD[0])).result(
            timeout=60) is not None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if victim.health(time.monotonic()) == "healthy":
                break
            time.sleep(0.05)
        assert victim.health(time.monotonic()) == "healthy"
        assert victim.replacements == 1
        assert victim.server._store.stats["compiles"] == 0
        assert router.fleet_state()["state"] == "serving"
