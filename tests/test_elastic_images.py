"""Elastic checkpoint/resume + image pipeline tests (≡ the reference's
fault-tolerance behaviour of SharedTrainingMaster and datavec-data-image
ImageRecordReaderTest)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.datavec.image_records import (
    FlipImageTransform, ImageRecordDataSetIterator, ImageRecordReader,
    ParentPathLabelGenerator, PipelineImageTransform, ResizeImageTransform)
from deeplearning4j_tpu.parallel.elastic import (ElasticCheckpointer,
                                                 ElasticTrainer,
                                                 initialize_multihost)
from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.parallel.sharded_trainer import ShardedTrainer
from deeplearning4j_tpu.nn.updaters import Adam


def _loss_fn(params, batch, rng):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _make_trainer():
    mesh = DeviceMesh(dp=-1).mesh
    return ShardedTrainer(_loss_fn, Adam(1e-2), mesh)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = rng.normal(size=(16, 2)).astype(np.float32)
    return x, y


class TestElastic:
    def test_save_restore_roundtrip(self, tmp_path):
        trainer = _make_trainer()
        params = trainer.shard_params(
            {"w": np.ones((4, 2), np.float32),
             "b": np.zeros((2,), np.float32)})
        opt = trainer.init(params)
        ck = ElasticCheckpointer(tmp_path / "ck")
        ck.save(7, params, opt, wait=True)
        step, state = ck.restore(like={"params": params, "opt_state": opt})
        assert step == 7
        assert np.allclose(np.asarray(state["params"]["w"]),
                           np.asarray(params["w"]))
        ck.close()

    def test_crash_resume_continues_exactly(self, tmp_path):
        """Train 10 steps with saves every 2; 'crash'; resume and check
        the restored state equals the pre-crash state at the last save."""
        ckdir = tmp_path / "elastic"
        trainer = _make_trainer()
        et = ElasticTrainer(trainer, ckdir, save_every=2)
        init = {"w": np.ones((4, 2), np.float32),
                "b": np.zeros((2,), np.float32)}
        params, opt = et.resume_or_init(init)
        assert et.step_num == 0
        rng = jax.random.PRNGKey(0)
        snapshots = {}
        for i in range(10):
            params, opt, _ = et.fit_batch(params, opt, _batch(i), rng)
            snapshots[et.step_num] = np.asarray(params["w"]).copy()
        et.ckpt.manager.wait_until_finished()

        # simulate restarted process
        trainer2 = _make_trainer()
        et2 = ElasticTrainer(trainer2, ckdir, save_every=2)
        params2, opt2 = et2.resume_or_init(init)
        assert et2.step_num == 10
        assert np.allclose(np.asarray(params2["w"]), snapshots[10])
        # and training continues
        params2, opt2, loss = et2.fit_batch(params2, opt2, _batch(99), rng)
        assert np.isfinite(float(loss))
        et2.finalize(params2, opt2)

    def test_resume_falls_back_past_corrupt_generation(self, tmp_path):
        """ElasticTrainer resumes through the integrity-verified path:
        a corrupted newest generation costs one generation of progress,
        not a silent resume from poisoned bytes."""
        from deeplearning4j_tpu.resilience import integrity
        ckdir = tmp_path / "elastic"
        trainer = _make_trainer()
        et = ElasticTrainer(trainer, ckdir, save_every=2)
        init = {"w": np.ones((4, 2), np.float32),
                "b": np.zeros((2,), np.float32)}
        params, opt = et.resume_or_init(init)
        rng = jax.random.PRNGKey(0)
        snapshots = {}
        for i in range(10):
            params, opt, _ = et.fit_batch(params, opt, _batch(i), rng)
            snapshots[et.step_num] = np.asarray(params["w"]).copy()
        et.ckpt.manager.wait_until_finished()

        mpath = integrity.manifest_path(ckdir, 10)
        doc = open(mpath).read().replace("crc32:", "crc32:dead", 1)
        open(mpath, "w").write(doc)

        trainer2 = _make_trainer()
        et2 = ElasticTrainer(trainer2, ckdir, save_every=2)
        params2, _ = et2.resume_or_init(init)
        assert et2.step_num == 8, "corrupt newest must fall back one gen"
        assert np.allclose(np.asarray(params2["w"]), snapshots[8])

    def test_multihost_noop_without_env(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert initialize_multihost() is False


def _write_image_tree(root):
    from PIL import Image
    rng = np.random.default_rng(0)
    for cls, color in [("cats", (255, 0, 0)), ("dogs", (0, 0, 255))]:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(3):
            arr = np.zeros((20 + i, 24, 3), np.uint8)
            arr[:] = color
            arr += rng.integers(0, 20, arr.shape).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img{i}.png"))


class TestImageRecordReader:
    def test_reads_and_labels(self, tmp_path):
        _write_image_tree(tmp_path)
        rr = ImageRecordReader(16, 16, 3).initialize(tmp_path)
        assert rr.getLabels() == ["cats", "dogs"]
        assert rr.numExamples() == 6
        img, lab = rr.next()
        assert img.shape == (16, 16, 3) and img.dtype == np.float32
        assert lab in (0, 1)

    def test_label_generator(self, tmp_path):
        _write_image_tree(tmp_path)
        g = ParentPathLabelGenerator()
        assert g.getLabelForPath(str(tmp_path / "cats" / "img0.png")) == \
            "cats"

    def test_transforms(self, tmp_path):
        _write_image_tree(tmp_path)
        tf = PipelineImageTransform(FlipImageTransform(),
                                    ResizeImageTransform(8, 8))
        rr = ImageRecordReader(16, 16, 3, imageTransform=tf).initialize(
            tmp_path)
        img, _ = rr.next()
        assert img.shape == (16, 16, 3)  # re-resized to reader dims

    def test_iterator_batches_and_trains(self, tmp_path):
        _write_image_tree(tmp_path)
        rr = ImageRecordReader(16, 16, 3).initialize(tmp_path,
                                                     shuffle=True)
        it = ImageRecordDataSetIterator(rr, batch_size=4)
        batches = list(it)
        assert batches[0].features.shape == (4, 16, 16, 3)
        assert batches[0].labels.shape == (4, 2)
        assert sum(b.features.shape[0] for b in batches) == 6
        # the two color classes are linearly separable: LeNet-ish learns
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(
            NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=4,
                                    convolutionMode="same",
                                    activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=2,
                               activation="softmax"))
            .setInputType(InputType.convolutional(16, 16, 3))
            .build()).init()
        from deeplearning4j_tpu.datasets.normalizers import \
            ImagePreProcessingScaler
        scaler = ImagePreProcessingScaler()
        it2 = ImageRecordDataSetIterator(rr, batch_size=6,
                                         preprocessor=scaler)
        for _ in range(20):
            net.fit(it2)
        ev_ds = next(iter(it2))
        preds = np.asarray(net.output(ev_ds.features))
        acc = (preds.argmax(1) == np.asarray(ev_ds.labels).argmax(1)).mean()
        assert acc == 1.0
