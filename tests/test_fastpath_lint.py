"""scripts/check_fastpath.py in tier-1: instrumented hot-path modules
must keep the disabled-monitoring path at one branch — no bare registry
calls outside the enabled-guard pattern."""
import os
import sys
import textwrap

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

import check_fastpath  # noqa: E402


def test_repo_hot_paths_are_clean():
    violations = check_fastpath.main()
    assert violations == [], "\n".join(
        f"{p}:{ln}: {msg}" for p, ln, msg in violations)


def test_lint_flags_unguarded_registry_call():
    bad = textwrap.dedent("""
        from deeplearning4j_tpu import monitoring as _mon

        def fit_batch(self, x):
            _mon.get_registry().counter("dl4j.train.steps").inc()
            return x
    """)
    v = check_fastpath.check_source(bad)
    assert len(v) == 2   # get_registry() AND .counter(...)
    assert all("outside the enabled-guard" in msg for _, _, msg in v)


def test_lint_accepts_guarded_patterns():
    good = textwrap.dedent("""
        from deeplearning4j_tpu import monitoring as _mon
        from deeplearning4j_tpu.monitoring.state import STATE

        def wrapped_guard(self, x):
            if _mon.enabled():
                _mon.get_registry().counter("a").inc()
            return x

        def early_return_guard(self, x):
            if not STATE.enabled:
                return x
            reg = _mon.get_registry()
            reg.histogram("b").observe(1.0)
            return x

        def cached_flag(self):
            mon_on = _mon.enabled()
            if not mon_on:
                return
            _mon.get_registry().gauge("c").set(1)
    """)
    assert check_fastpath.check_source(good) == []


def test_training_sync_lint_flags_host_sync_in_exchange():
    """The training-exchange rule: a host materialization reachable
    from the step builders / bucket planner is flagged; the declared
    encoder_stats boundary is not descended into."""
    bad = textwrap.dedent("""
        import numpy as np

        def make_step(self):
            def step(params, batch):
                return self._exchange(params, batch)
            return step

        def _exchange(self, params, batch):
            return np.asarray(params)      # host sync on the hot path

        def encoder_stats(self, opt_state):
            return np.asarray(opt_state)   # declared boundary: allowed
    """)
    v = check_fastpath.check_training_host_sync({"m.py": bad})
    assert len(v) == 1
    assert "declared" in v[0][2] and "_exchange" in v[0][2]


def test_training_sync_lint_accepts_current_exchange():
    """The real accumulation scan + bucket planner + bucketed exchange
    pass the rule (also covered by test_repo_hot_paths_are_clean; this
    pins the module set so a rename doesn't silently drop coverage)."""
    sources = {}
    for rel in check_fastpath.TRAIN_MODULES:
        path = os.path.join(check_fastpath.REPO_ROOT, rel)
        assert os.path.exists(path), f"lint module vanished: {rel}"
        with open(path) as f:
            sources[path] = f.read()
    assert check_fastpath.check_training_host_sync(sources) == []


def test_timeline_lint_module_groups_exist_and_pass():
    """The step-timeline publish rule over the real modules (also
    covered by test_repo_hot_paths_are_clean; this pins the group set
    so a rename doesn't silently drop coverage)."""
    for group in check_fastpath.TIMELINE_MODULE_GROUPS:
        sources = {}
        for rel in group:
            path = os.path.join(check_fastpath.REPO_ROOT, rel)
            assert os.path.exists(path), f"lint module vanished: {rel}"
            with open(path) as f:
                sources[path] = f.read()
        assert check_fastpath.check_timeline_host_sync(sources) == []


def test_timeline_lint_flags_device_touch_in_publish():
    """A device materialization reachable from the timeline publish
    path is flagged — publishing must stay pure host serialization."""
    bad = textwrap.dedent("""
        import json
        import numpy as np

        def publish(coordinator, recorder=None):
            snap = _digest(recorder)
            coordinator.publish("steps/0", json.dumps(snap))

        def _digest(recorder):
            return {"w": np.asarray(recorder.wall).tolist()}
    """)
    v = check_fastpath.check_timeline_host_sync({"m.py": bad})
    assert len(v) == 2   # asarray AND tolist
    assert all("publish path" in msg for _, _, msg in v)


def test_metrics_publish_guard_accepts_current_coordination():
    path = os.path.join(check_fastpath.REPO_ROOT,
                        check_fastpath.METRICS_PUBLISH_MODULES[0])
    assert os.path.exists(path)
    with open(path) as f:
        assert check_fastpath.check_metrics_publish_guarded(
            f.read(), path) == []


def test_metrics_publish_guard_flags_unguarded_publish():
    """An unguarded metrics-plane publish at the sync point is flagged;
    the coordinator's own control-plane publish (heartbeats) is
    exempt, and the guarded form passes."""
    bad = textwrap.dedent("""
        def _sync_point(self, rate):
            self.publish("hb/0/0", "{}")          # control plane: ok
            _cluster.publish(self, extra={})       # metrics: unguarded
            _stragglers.publish(self)              # timeline: unguarded
    """)
    v = check_fastpath.check_metrics_publish_guarded(bad)
    assert len(v) == 2
    assert all("enabled-guard" in msg for _, _, msg in v)

    good = textwrap.dedent("""
        def _sync_point(self, rate):
            self.publish("hb/0/0", "{}")
            if _mon.enabled():
                _cluster.publish(self, extra={})
                _stragglers.publish(self)
    """)
    assert check_fastpath.check_metrics_publish_guarded(good) == []


def test_generation_lint_pins_paging_module():
    """paging.py is IN the generation lint module set (a rename or a
    set edit can't silently drop the paged hot path from coverage),
    and the real allocator passes both the trace- and sync-rules: page
    allocation / prefix lookup / CoW planning / table build are pure
    host bookkeeping."""
    rel = "deeplearning4j_tpu/generation/paging.py"
    assert rel in check_fastpath.GENERATION_MODULES
    for root in ("_page_args", "admit_slot", "ensure_range",
                 "evict_cold", "release_slot", "build_table"):
        assert root in check_fastpath.GENERATION_SYNC_ROOTS
    path = os.path.join(check_fastpath.REPO_ROOT, rel)
    assert os.path.exists(path), "lint module vanished: paging.py"
    with open(path) as f:
        src = f.read()
    assert check_fastpath.check_generation_steady_state(
        {path: src}) == []
    assert check_fastpath.check_generation_host_sync({path: src}) == []


def test_generation_sync_lint_flags_sync_in_page_walk():
    """A host materialization reachable from the per-block page walk
    (_page_args → ensure_range/build_table) is flagged: page
    bookkeeping between dispatches must add ZERO host syncs per
    token."""
    bad = textwrap.dedent("""
        import numpy as np

        def _page_args(self, k):
            for slot in self._slot_req:
                self._pages.ensure_range(slot, 0, k)
            return self._pages.build_table(4, 4)

        def ensure_range(self, slot, lo, hi):
            return []

        def build_table(self, slots, maxp):
            return np.asarray(self._table).tolist()   # host sync!
    """)
    v = check_fastpath.check_generation_host_sync({"m.py": bad})
    assert len(v) == 2   # asarray AND tolist
    assert all("host sync" in msg or "asarray" in msg or "tolist" in msg
               for _, _, msg in v)


def test_generation_trace_lint_flags_compile_in_page_admission():
    """A live trace/compile reachable from the page-admission root is
    flagged — steady-state paging resolves everything from the warmed
    executable set."""
    bad = textwrap.dedent("""
        import jax

        def _admit_rec(self, rec):
            self._pages.admit_slot(0, rec, 8)

        def admit_slot(self, slot, prompt, pbucket):
            return jax.jit(lambda x: x)(prompt)   # live compile!
    """)
    v = check_fastpath.check_generation_steady_state({"m.py": bad})
    assert len(v) == 1
    assert "admit_slot" in v[0][2]


def test_event_emit_guard_pins_hook_modules_and_accepts_them():
    """Every module carrying ops-event emission hooks is IN the lint
    set (a rename can't silently drop one), and the real hooks all sit
    behind the enabled-guard."""
    expected = {
        "deeplearning4j_tpu/resilience/guardian.py",
        "deeplearning4j_tpu/resilience/watchdog.py",
        "deeplearning4j_tpu/resilience/faults.py",
        "deeplearning4j_tpu/generation/server.py",
        "deeplearning4j_tpu/parallel/coordination.py",
        "deeplearning4j_tpu/parallel/membership.py",
        "deeplearning4j_tpu/parallel/multihost.py",
        "deeplearning4j_tpu/monitoring/slo.py",
    }
    assert expected <= set(check_fastpath.EVENT_HOOK_MODULES)
    for rel in check_fastpath.EVENT_HOOK_MODULES:
        path = os.path.join(check_fastpath.REPO_ROOT, rel)
        assert os.path.exists(path), f"lint module vanished: {rel}"
        with open(path) as f:
            assert check_fastpath.check_event_emit_guarded(
                f.read(), path) == []


def test_event_emit_guard_flags_bare_emit():
    bad = textwrap.dedent("""
        from deeplearning4j_tpu.monitoring import events as _events

        def _flush(self):
            _events.emit("guardian", _events.GUARDIAN_RETRY)
    """)
    v = check_fastpath.check_event_emit_guarded(bad)
    assert len(v) == 1
    assert "one branch" in v[0][2]

    good = textwrap.dedent("""
        from deeplearning4j_tpu import monitoring as _mon
        from deeplearning4j_tpu.monitoring import events as _events

        def _flush(self):
            if _mon.enabled():
                _events.emit("guardian", _events.GUARDIAN_RETRY)
    """)
    assert check_fastpath.check_event_emit_guarded(good) == []


def test_event_emit_purity_accepts_journal_and_flags_sync():
    """The real journal emit path is pure host bookkeeping; a device
    materialization reachable from emit is flagged, while the declared
    bundle()/write_bundle() cold boundary is not descended into."""
    sources = {}
    for rel in check_fastpath.EVENT_JOURNAL_MODULES:
        path = os.path.join(check_fastpath.REPO_ROOT, rel)
        assert os.path.exists(path), f"lint module vanished: {rel}"
        with open(path) as f:
            sources[path] = f.read()
    assert check_fastpath.check_event_emit_host_pure(sources) == []

    bad = textwrap.dedent("""
        import numpy as np

        def emit(source, kind):
            return _correlate(kind)

        def _correlate(kind):
            return np.asarray(kind)     # host sync on the emit path!

        def bundle():
            return np.asarray([1]).tolist()   # declared boundary: ok
    """)
    v = check_fastpath.check_event_emit_host_pure({"m.py": bad})
    assert len(v) == 1
    assert "emit path" in v[0][2]


def test_lint_rejects_guard_after_the_call():
    # the guard must precede the call — a later early-return doesn't
    # protect the hot path
    bad = textwrap.dedent("""
        from deeplearning4j_tpu import monitoring as _mon
        from deeplearning4j_tpu.monitoring.state import STATE

        def f(self):
            _mon.get_registry().counter("a").inc()
            if not STATE.enabled:
                return
    """)
    assert len(check_fastpath.check_source(bad)) == 2


def test_fleet_lint_pins_fleet_module():
    """fleet.py is IN the guarded-hook module sets AND the fleet
    routing-walk lint (a set edit can't silently drop the router's
    hot path from coverage), and the real router passes both rules:
    route / dispatch / relay / failover are pure host plumbing."""
    rel = "deeplearning4j_tpu/generation/fleet.py"
    assert rel in check_fastpath.HOT_MODULES
    assert rel in check_fastpath.EVENT_HOOK_MODULES
    assert check_fastpath.FLEET_MODULES == [rel]
    for root in ("_route", "_dispatch", "_relay", "_failover"):
        assert root in check_fastpath.FLEET_ROOTS
    assert "_supervise" in check_fastpath.FLEET_BOUNDARY
    path = os.path.join(check_fastpath.REPO_ROOT, rel)
    assert os.path.exists(path), "lint module vanished: fleet.py"
    with open(path) as f:
        src = f.read()
    assert check_fastpath.check_fleet_trace_free({path: src}) == []
    assert check_fastpath.check_fleet_host_sync({path: src}) == []


def test_fleet_sync_lint_flags_sync_in_relay():
    """A device materialization reachable from the relay pump is
    flagged: the router moves already-fetched host ints between the
    replica stream and the client handle — never device values."""
    bad = textwrap.dedent("""
        import numpy as np

        def _relay(self, replica, freq, backend):
            for tok in self._pull(backend):
                freq._push(tok)

        def _pull(self, backend):
            return np.asarray(backend.tokens).tolist()   # host sync!
    """)
    v = check_fastpath.check_fleet_host_sync({"m.py": bad})
    assert len(v) == 2   # asarray AND tolist
    assert all("routing walk" in msg for _, _, msg in v)


def test_fleet_trace_lint_flags_compile_in_dispatch():
    """A live compile reachable from dispatch is flagged, while the
    SAME compile inside the declared _supervise boundary is accepted —
    replica replacement is the one place warmup may happen."""
    bad = textwrap.dedent("""
        import jax

        def _dispatch(self, replica, freq):
            return self._build(freq)

        def _build(self, freq):
            return jax.jit(lambda x: x)(freq.prompt)   # live compile!

        def _supervise(self, replica, cause):
            return jax.jit(lambda x: x)(0)   # cold boundary: ok
    """)
    v = check_fastpath.check_fleet_trace_free({"m.py": bad})
    assert len(v) == 1
    assert "_build" in v[0][2]
