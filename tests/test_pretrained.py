"""Pretrained-weight loading tests (round-1 VERDICT: initPretrained was
random-init only; nothing proved a real checkpoint flows through
featurize/fine-tune)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import LeNet
from deeplearning4j_tpu.util.model_serializer import ModelSerializer


def _mnist_batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, 28, 28, 1)).astype(np.float32)  # NHWC
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    return x, y


def _trained_lenet(tmp_path, steps=2):
    """Train a LeNet briefly and save it — the 'published checkpoint'."""
    net = LeNet(numClasses=10, inputShape=(28, 28, 1)).init()
    x, y = _mnist_batch()
    for _ in range(steps):
        net.fit(x, y)
    p = str(tmp_path / "lenet_mnist.zip")
    ModelSerializer.writeModel(net, p)
    return net, p


class TestInitPretrainedZip:
    def test_loads_checkpointed_weights(self, tmp_path):
        trained, path = _trained_lenet(tmp_path)
        loaded = LeNet(numClasses=10,
                       inputShape=(28, 28, 1)).initPretrained(path=path)
        x, _ = _mnist_batch(4, seed=1)
        np.testing.assert_allclose(np.asarray(trained.output(x)),
                                   np.asarray(loaded.output(x)), atol=1e-6)

    def test_env_dir_discovery(self, tmp_path, monkeypatch):
        _, path = _trained_lenet(tmp_path)
        model = LeNet(numClasses=10, inputShape=(28, 28, 1))
        assert not model.pretrainedAvailable("mnist")
        monkeypatch.setenv("DL4J_TPU_PRETRAINED_DIR", str(tmp_path))
        assert model.pretrainedAvailable("mnist")
        net = model.initPretrained("mnist")
        assert net is not None

    def test_missing_checkpoint_raises(self):
        with pytest.raises(RuntimeError, match="No local pretrained"):
            LeNet(numClasses=10).initPretrained("imagenet")


class TestInitPretrainedH5:
    def test_keras_h5_weights_land_in_layers(self, tmp_path):
        """A foreign (Keras-layout) .h5 checkpoint round-trips into our
        NHWC/HWIO layers by layer/dataset NAME — conv kernels are HWIO in
        both stacks so values carry over without transposes."""
        h5py = pytest.importorskip("h5py")
        rng = np.random.default_rng(5)
        # LeNet layer0 = Conv 5x5x1x20 (HWIO), layer4 = Dense, layer5 = Out
        k0 = rng.normal(size=(5, 5, 1, 20)).astype(np.float32)
        b0 = rng.normal(size=(20,)).astype(np.float32)
        p = str(tmp_path / "w.h5")
        with h5py.File(p, "w") as f:
            g = f.create_group("model_weights")
            conv = g.create_group("layer0").create_group("layer0")
            conv.create_dataset("kernel:0", data=k0)
            conv.create_dataset("bias:0", data=b0)
        net = LeNet(numClasses=10,
                    inputShape=(28, 28, 1)).initPretrained(path=p)
        np.testing.assert_allclose(np.asarray(net._params["0"]["W"]), k0)
        np.testing.assert_allclose(np.asarray(net._params["0"]["b"]), b0)
        x, _ = _mnist_batch(2, seed=2)
        assert np.asarray(net.output(x)).shape == (2, 10)


class TestTransferFromPretrained:
    @pytest.mark.slow   # suite diet (ISSUE 18): ~13 s — trains a LeNet
    # twice just to compose two already-covered contracts; freeze-keeps-
    # weights/head-trains stays tier-1 via tests/test_transfer.py::
    # {test_feature_extractor_freezes_params,
    #  test_frozen_training_still_learns_head} and checkpoint loading
    # via TestInitPretrainedZip::test_loads_checkpointed_weights
    def test_fine_tune_starts_from_loaded_weights(self, tmp_path):
        """TransferLearning on an initPretrained() network: frozen layers
        keep the CHECKPOINT's weights (not random init) while the new head
        trains."""
        from deeplearning4j_tpu.transfer import (FineTuneConfiguration,
                                                 TransferLearning)
        from deeplearning4j_tpu.nn.updaters import Adam

        trained, path = _trained_lenet(tmp_path)
        base = LeNet(numClasses=10,
                     inputShape=(28, 28, 1)).initPretrained(path=path)
        pretrained_conv = np.asarray(base._params["0"]["W"]).copy()

        new_net = (TransferLearning.Builder(base)
                   .fineTuneConfiguration(
                       FineTuneConfiguration.Builder()
                       .updater(Adam(1e-3)).build())
                   .setFeatureExtractor(4)  # freeze conv stack
                   .nOutReplace(5, 5, "xavier")  # new 5-class head
                   .build())
        # frozen conv layer came from the checkpoint, not fresh init
        np.testing.assert_array_equal(np.asarray(new_net._params["0"]["W"]),
                                      pretrained_conv)
        x, _ = _mnist_batch(8, seed=3)
        y5 = np.eye(5, dtype=np.float32)[
            np.random.default_rng(4).integers(0, 5, 8)]
        for _ in range(3):
            new_net.fit(x, y5)
        # frozen layer unchanged by fine-tuning; head trained
        np.testing.assert_array_equal(np.asarray(new_net._params["0"]["W"]),
                                      pretrained_conv)
        assert np.asarray(new_net.output(x)).shape == (8, 5)

    def test_h5_with_no_matching_names_raises(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        p = str(tmp_path / "foreign.h5")
        with h5py.File(p, "w") as f:
            g = f.create_group("model_weights")
            conv = g.create_group("conv_totally_other").create_group("x")
            conv.create_dataset("kernel:0",
                                data=np.zeros((5, 5, 1, 20), np.float32))
        with pytest.raises(RuntimeError, match="no layer names"):
            LeNet(numClasses=10, inputShape=(28, 28, 1)).initPretrained(path=p)
