"""MultiLayerNetwork end-to-end tests (SURVEY.md §4: config→init→fit;
≡ deeplearning4j-core MultiLayerTest / dl4j-examples LeNet MNIST)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (ArrayDataSetIterator, DataSet,
                                         IrisDataSetIterator,
                                         MnistDataSetIterator,
                                         NormalizerStandardize)
from deeplearning4j_tpu.nn import (Activation, Adam, BatchNormalization,
                                   ConvolutionLayer, DenseLayer, InputType,
                                   LossFunction, MultiLayerNetwork,
                                   Nesterovs, NeuralNetConfiguration,
                                   OutputLayer, SubsamplingLayer, WeightInit)


def _mlp_conf(n_in=4, n_hidden=16, n_out=3, seed=42, updater=None, l2=0.0):
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Adam(1e-2))
            .weightInit(WeightInit.XAVIER)
            .activation(Activation.RELU)
            .l2(l2)
            .list()
            .layer(DenseLayer.Builder().nOut(n_hidden).build())
            .layer(DenseLayer.Builder().nOut(n_hidden).build())
            .layer(OutputLayer.Builder(LossFunction.MCXENT)
                   .nOut(n_out).activation(Activation.SOFTMAX).build())
            .setInputType(InputType.feedForward(n_in))
            .build())


def test_build_and_init():
    net = MultiLayerNetwork(_mlp_conf()).init()
    assert net.getnLayers() == 3
    # nIn inference: 4 -> 16 -> 16 -> 3
    assert net.layers[0].nIn == 4
    assert net.layers[1].nIn == 16
    assert net.layers[2].nIn == 16
    expected = 4 * 16 + 16 + 16 * 16 + 16 + 16 * 3 + 3
    assert net.numParams() == expected
    assert net.params().length() == expected


def test_output_shape_and_softmax():
    net = MultiLayerNetwork(_mlp_conf()).init()
    x = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
    out = net.output(x).numpy()
    assert out.shape == (5, 3)
    np.testing.assert_allclose(out.sum(-1), np.ones(5), rtol=1e-5)


def test_feedforward_activations():
    net = MultiLayerNetwork(_mlp_conf()).init()
    x = np.zeros((2, 4), np.float32)
    acts = net.feedForward(x)
    assert len(acts) == 3
    assert acts[0].shape == (2, 16)
    assert acts[-1].shape == (2, 3)


def test_fit_decreases_loss_iris():
    it = IrisDataSetIterator(batch_size=50)
    norm = NormalizerStandardize().fit(it)
    it.setPreProcessor(norm)
    net = MultiLayerNetwork(_mlp_conf()).init()
    ds = it.next(150)
    first = net.score(ds)
    net.fit(it, epochs=30)
    assert net.score(ds) < first * 0.5
    e = net.evaluate(IrisDataSetIterator(batch_size=150))
    # fresh iterator has no normalizer; re-use training one for fairness
    it2 = IrisDataSetIterator(batch_size=150)
    it2.setPreProcessor(norm)
    e = net.evaluate(it2)
    assert e.accuracy() > 0.9


def test_score_and_listeners_called():
    calls = []

    class Listener:
        def iterationDone(self, model, iteration, epoch):
            calls.append((iteration, epoch))

    it = IrisDataSetIterator(batch_size=75)
    net = MultiLayerNetwork(_mlp_conf()).init()
    net.setListeners(Listener())
    net.fit(it, epochs=2)
    assert len(calls) == 4  # 2 batches x 2 epochs
    assert isinstance(net.score(), float)


def test_lenet_learns_synthetic_mnist():
    """The round-1 minimum slice: LeNet-style CNN on (synthetic) MNIST via
    the reference's exact builder idiom (dl4j-examples LenetMnistExample)."""
    conf = (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater(Nesterovs(0.05, 0.9))
            .weightInit(WeightInit.XAVIER)
            .list()
            .layer(ConvolutionLayer.Builder(5, 5)
                   .stride(1, 1).nOut(8).activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder("max")
                   .kernelSize(2, 2).stride(2, 2).build())
            .layer(ConvolutionLayer.Builder(5, 5)
                   .stride(1, 1).nOut(16).activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder("max")
                   .kernelSize(2, 2).stride(2, 2).build())
            .layer(DenseLayer.Builder().activation(Activation.RELU)
                   .nOut(64).build())
            .layer(OutputLayer.Builder(LossFunction.NEGATIVELOGLIKELIHOOD)
                   .nOut(10).activation(Activation.SOFTMAX).build())
            .setInputType(InputType.convolutionalFlat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    train = MnistDataSetIterator(64, train=True, num_examples=512)
    test = MnistDataSetIterator(256, train=False, num_examples=256)
    net.fit(train, epochs=3)
    acc = net.evaluate(test).accuracy()
    assert acc > 0.9, f"LeNet synthetic-MNIST accuracy {acc}"


def test_batchnorm_updates_running_stats():
    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer.Builder().nOut(8).activation("relu").build())
            .layer(BatchNormalization.Builder().build())
            .layer(OutputLayer.Builder("mcxent").nOut(2)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    before = np.array(net._state["1"]["mean"])
    x = np.random.default_rng(0).standard_normal((32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 32)]
    net.fit(x, y)
    after = np.array(net._state["1"]["mean"])
    assert not np.allclose(before, after)


def test_setparams_roundtrip():
    net = MultiLayerNetwork(_mlp_conf()).init()
    flat = net.params().numpy()
    net2 = MultiLayerNetwork(_mlp_conf(seed=7)).init()
    net2.setParams(flat)
    np.testing.assert_allclose(net2.params().numpy(), flat)
    x = np.random.default_rng(2).standard_normal((3, 4)).astype(np.float32)
    np.testing.assert_allclose(net.output(x).numpy(), net2.output(x).numpy(),
                               rtol=1e-5)


def test_l2_regularization_changes_loss():
    it = IrisDataSetIterator(batch_size=150)
    ds = it.next(150)
    net_plain = MultiLayerNetwork(_mlp_conf(l2=0.0)).init()
    net_l2 = MultiLayerNetwork(_mlp_conf(l2=0.1)).init()
    assert net_l2.score(ds) > net_plain.score(ds)


def test_dropout_only_at_train_time():
    conf = (NeuralNetConfiguration.Builder()
            .seed(3).updater(Adam(1e-3)).dropOut(0.5)
            .list()
            .layer(DenseLayer.Builder().nOut(32).activation("relu").build())
            .layer(OutputLayer.Builder("mcxent").nOut(2)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.ones((4, 8), np.float32)
    a = net.output(x, train=False).numpy()
    b = net.output(x, train=False).numpy()
    np.testing.assert_allclose(a, b)  # inference is deterministic


def test_fit_array_signature():
    net = MultiLayerNetwork(_mlp_conf(n_in=4, n_out=3)).init()
    x = np.random.default_rng(0).standard_normal((10, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(1).integers(0, 3, 10)]
    net.fit(x, y)
    net.fit(DataSet(x, y))
    assert net.getIterationCount() == 2


def test_summary_prints():
    net = MultiLayerNetwork(_mlp_conf()).init()
    s = net.summary()
    assert "DenseLayer" in s and "Total params" in s


def test_remat_layer_matches_plain():
    """remat=True (jax.checkpoint around the layer apply) must be
    numerically invisible: same outputs, same trained params."""
    import jax
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer,
                                       Sgd)
    from deeplearning4j_tpu.datasets import DataSet

    def _net(remat):
        conf = (NeuralNetConfiguration.Builder()
                .seed(5).updater(Sgd(0.1)).activation("tanh")
                .list()
                .layer(DenseLayer.Builder().nOut(16).remat(remat).build())
                .layer(DenseLayer.Builder().nOut(16).remat(remat).build())
                .layer(OutputLayer.Builder("mcxent").nOut(3)
                       .activation("softmax").build())
                .setInputType(InputType.feedForward(6))
                .build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(11)
    x = rng.standard_normal((32, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    plain, remat = _net(False), _net(True)
    ds = DataSet(x, y)
    for _ in range(4):
        plain.fit(ds)
        remat.fit(ds)
    np.testing.assert_allclose(plain.params().numpy(),
                               remat.params().numpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(plain.score(ds), remat.score(ds), rtol=1e-6)


def test_predict_and_f1score():
    """≡ Classifier.predict / f1Score conveniences."""
    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer,
                                       Sgd)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(2).updater(Sgd(0.2)).activation("relu")
            .list()
            .layer(DenseLayer.Builder().nOut(16).build())
            .layer(OutputLayer.Builder("mcxent").nOut(2)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    ds = DataSet(x, y)
    for _ in range(40):
        net.fit(ds)
    preds = net.predict(x)
    assert preds.shape == (64,)
    acc = (preds == y.argmax(1)).mean()
    assert acc > 0.9
    f1 = net.f1Score(ds)
    assert 0.9 < f1 <= 1.0
    assert abs(net.f1Score(x, y) - f1) < 1e-9


def test_bf16_momentum_tracks_fp32_momentum():
    """Nesterovs(momentumDtype='bfloat16') halves optimizer-state HBM
    traffic; training must stay loss-parity-close to the fp32 buffer."""
    import numpy as np

    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Nesterovs,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def train(updater):
        net = MultiLayerNetwork(
            NeuralNetConfiguration.Builder().seed(5).updater(updater)
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=32, activation="relu"))
            .layer(OutputLayer(nOut=4, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(8)).build()).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(4, size=64)]
        losses = []
        for _ in range(25):
            net.fit(x, y)
            losses.append(float(net.score()))
        return losses

    l32 = train(Nesterovs(0.05, 0.9))
    l16 = train(Nesterovs(0.05, 0.9, momentumDtype="bfloat16"))
    # same trajectory within bf16 rounding: final losses close, both
    # decreasing
    assert l16[-1] < l16[0] and l32[-1] < l32[0]
    assert abs(l16[-1] - l32[-1]) < 0.05 * max(abs(l32[-1]), 0.1)


def test_steps_per_dispatch_matches_sequential_fit():
    """fit(it, stepsPerDispatch=k) == plain fit(it): the scanned dispatch
    consumes the same rng subkey stream and applies the same update order,
    so params, score history, and iteration counts must match exactly."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Adam,
                                       OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

    rng = np.random.default_rng(3)
    sets = [DataSet(rng.normal(size=(16, 6)).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rng.integers(3, size=16)])
            for _ in range(6)]

    def build():
        return MultiLayerNetwork(
            NeuralNetConfiguration.Builder().seed(11).updater(Adam(1e-2))
            .weightInit("xavier").list()
            .layer(DenseLayer(nOut=24, activation="tanh"))
            .layer(OutputLayer(nOut=3, activation="softmax",
                               lossFunction="mcxent"))
            .setInputType(InputType.feedForward(6)).build()).init()

    seq, scan = build(), build()
    seq_scores, scan_scores = [], []
    seq.setListeners(ScoreIterationListener(1))
    seq.fit(ListDataSetIterator(sets, 16), epochs=2)
    # re-walk sequentially recording scores for comparison
    seq2 = build()
    it = ListDataSetIterator(sets, 16)
    for _ in range(2):
        it.reset()
        for ds in it:
            seq2.fit(ds)
            seq_scores.append(seq2.score())

    class Rec:
        def iterationDone(self, net, iteration, epoch):
            scan_scores.append(net.score())

    scan.setListeners(Rec())
    scan.fit(ListDataSetIterator(sets, 16), epochs=2, stepsPerDispatch=4)

    import jax
    for k in seq._params:
        for n, v in seq._params[k].items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(scan._params[k][n]),
                rtol=0, atol=1e-6, err_msg=f"{k}/{n}")
    assert scan._iteration == 12          # 6 batches x 2 epochs
    assert len(scan_scores) == 12
    np.testing.assert_allclose(scan_scores, seq_scores, rtol=1e-5, atol=1e-6)


def test_steps_per_dispatch_ragged_tail_and_masks():
    """Shape changes flush the group early: a ragged final batch and
    mask-carrying sequence data must train identically to sequential."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration,
                                       RmsProp)
    from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(7)

    def mkset(b):
        x = rng.normal(size=(b, 5, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(2, size=(b, 5))]
        lm = (rng.random((b, 5)) > 0.3).astype(np.float32)
        return DataSet(x, y, featuresMask=lm, labelsMask=lm)

    sets = [mkset(8), mkset(8), mkset(8), mkset(3)]   # ragged tail

    def build():
        return MultiLayerNetwork(
            NeuralNetConfiguration.Builder().seed(2).updater(RmsProp(1e-2))
            .weightInit("xavier").list()
            .layer(LSTM(nOut=8, activation="tanh"))
            .layer(RnnOutputLayer(nOut=2, activation="softmax",
                                  lossFunction="mcxent"))
            .setInputType(InputType.recurrent(4, 5)).build()).init()

    seq, scan = build(), build()
    it = ListDataSetIterator(sets, 8)
    for ds in it:
        seq.fit(ds)
    scan.fit(ListDataSetIterator(sets, 8), stepsPerDispatch=3)
    for k in seq._params:
        for n, v in seq._params[k].items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(scan._params[k][n]),
                rtol=0, atol=1e-6, err_msg=f"{k}/{n}")
    assert scan._iteration == 4


def test_upsampling1d_and_time_distributed():
    """Upsampling1D repeats timesteps (mask too); TimeDistributed applies
    a Dense layer per step == manual loop oracle, and trains."""
    import numpy as np

    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, Adam,
                                       TimeDistributed, Upsampling1D)
    from deeplearning4j_tpu.nn.conf.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(4).updater(Adam(1e-2))
        .weightInit("xavier").list()
        .layer(Upsampling1D(size=2))
        .layer(TimeDistributed(DenseLayer(nOut=6, activation="tanh")))
        .layer(RnnOutputLayer(nOut=2, activation="softmax",
                              lossFunction="mcxent"))
        .setInputType(InputType.recurrent(3, 4)).build()).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 4, 3)).astype(np.float32)
    out = net.output(x).numpy()
    assert out.shape == (5, 8, 2)          # time 4 -> 8

    # oracle: upsample then per-step dense with the initialized weights
    w = np.asarray(net._params["1"]["W"])
    b = np.asarray(net._params["1"]["b"])
    up = np.repeat(x, 2, axis=1)
    hid = np.tanh(up @ w + b)
    np.testing.assert_allclose(
        np.asarray(net.activateSelectedLayers(0, 1, x).numpy()), hid,
        rtol=2e-5, atol=2e-5)

    y = np.eye(2, dtype=np.float32)[rng.integers(2, size=(5, 8))]
    s0 = None
    for _ in range(30):
        net.fit(x, y)
        s0 = s0 or net.score()
    assert net.score() < s0


def test_time_distributed_delegates_regularization():
    """l2 on the wrapped layer must reach the penalty (review r4 finding):
    the network reads terms from the wrapper while params are the inner
    layer's."""
    from deeplearning4j_tpu.nn import DenseLayer, TimeDistributed

    td = TimeDistributed(DenseLayer(nOut=4, l2=0.5))
    td.apply_defaults({})
    assert td.regularization_terms() == (0.0, 0.5)
