"""Quantization + selective-recompute subsystem (quantize/):

Tier-1 acceptance anchors (ISSUE 11):
- int8 inference agrees with the fp reference on a zoo model (top-1)
  and on pointwise-residual graphs (both the per-layer int8-dot impl
  and the cache-resident chain executor);
- QAT fake-quant trains with finite gradients through the STE;
- remat ("blocks" / "layers") gradients equal the un-rematted step and
  the traffic ledger reports >= 30% fewer saved-for-backward bytes;
- int8 KV-cache decode matches fp decode within tolerance (logits and
  greedy token stream).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer,
                                               DenseLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.quantize import (PrecisionPolicy, fake_quant,
                                         per_channel_scales,
                                         quantize_network)
from deeplearning4j_tpu.quantize.core import INT8_MAX, dequantize, quantize
from deeplearning4j_tpu.quantize.traffic import activation_report


# ===================== shared fixtures ================================
def _residual_graph(remat="none", wide=12, narrow=6, blocks=2, hw=6,
                    seed=7):
    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(0.05))
         .weightInit("relu").graphBuilder()
         .addInputs("input")
         .setInputTypes(InputType.convolutional(hw, hw, wide)))
    if remat != "none":
        b.rematPolicy(remat)
    x = "input"
    for i in range(blocks):
        b.addLayer(f"r{i}_c1", ConvolutionLayer(
            kernelSize=(1, 1), nOut=narrow, convolutionMode="same",
            hasBias=False, activation="identity"), x)
        b.addLayer(f"r{i}_bn1", BatchNormalization(activation="relu"),
                   f"r{i}_c1")
        b.addLayer(f"r{i}_c2", ConvolutionLayer(
            kernelSize=(1, 1), nOut=wide, convolutionMode="same",
            hasBias=False, activation="identity"), f"r{i}_bn1")
        b.addLayer(f"r{i}_bn2",
                   BatchNormalization(activation="identity"), f"r{i}_c2")
        b.addVertex(f"r{i}_add", ElementWiseVertex("add"),
                    f"r{i}_bn2", x)
        b.addLayer(f"r{i}_relu", ActivationLayer(activation="relu"),
                   f"r{i}_add")
        x = f"r{i}_relu"
    b.addLayer("pool", GlobalPoolingLayer(poolingType="avg"), x)
    b.addLayer("out", OutputLayer(lossFunction="mcxent", nOut=4,
                                  activation="softmax"), "pool")
    b.setOutputs("out")
    return ComputationGraph(b.build()).init()


@pytest.fixture(scope="module")
def trained_graph():
    net = _residual_graph()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 6, 6, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    for _ in range(5):
        net.fit(DataSet(x, y))
    return net, x


# ===================== core primitives ================================
def test_quantize_round_trip_per_channel():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((16, 8)) * 3, jnp.float32)
    s = per_channel_scales(w, -1)
    assert s.shape == (8,)
    q = quantize(w, s, channel_axis=1)
    assert q.dtype == jnp.int8
    back = dequantize(q, s, channel_axis=1)
    # round-trip error bounded by half a quantization step per channel
    assert float(jnp.max(jnp.abs(back - w) / s[None, :])) <= 0.5 + 1e-6


def test_fake_quant_ste_gradients():
    x = jnp.asarray([-300.0, -1.0, 0.3, 0.5, 1.0, 300.0], jnp.float32)
    s = jnp.asarray(1.0 / INT8_MAX, jnp.float32)
    g = jax.grad(lambda v: jnp.sum(fake_quant(v, s)))(x)
    # straight-through inside the clip range, zero outside
    np.testing.assert_array_equal(np.asarray(g),
                                  [0.0, 1.0, 1.0, 1.0, 1.0, 0.0])
    assert np.all(np.isfinite(np.asarray(g)))


def test_qat_training_gradients_finite():
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1))
            .precisionPolicy(PrecisionPolicy.int8())
            .list()
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                               activation="softmax"))
            .setInputType(InputType.feedForward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((16, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    s0 = None
    for _ in range(5):
        net.fit(x, y)
        s = net.score()
        assert np.isfinite(s)
        s0 = s if s0 is None else s0
    g = net.computeGradients(x, y)
    for leaf in jax.tree_util.tree_leaves(g):
        assert bool(jnp.all(jnp.isfinite(leaf)))
    assert net.score() < s0   # STE gradients actually descend


# ===================== int8 inference =================================
def test_int8_zoo_model_top1_agreement():
    from deeplearning4j_tpu.models.zoo import LeNet
    net = LeNet(numClasses=10, inputShape=(14, 14, 1)).init()
    rng = np.random.default_rng(3)
    x = rng.standard_normal((32, 14, 14, 1)).astype(np.float32)
    q = quantize_network(net, data=[x])
    # LeNet: the 5x5 convs fall back to fp (counted), dense quantizes
    assert q._quant_stats["int8_layers"] >= 1
    assert q._quant_stats["fallbacks"] >= 2
    fp = net.output(x).numpy()
    qo = q.output(x).numpy()
    agree = float((fp.argmax(-1) == qo.argmax(-1)).mean())
    assert agree >= 0.95
    assert np.max(np.abs(fp - qo)) < 0.05


def test_int8_graph_chain_and_dot_agree(trained_graph):
    net, x = trained_graph
    fp = net.outputSingle(x).numpy()
    q_chain = quantize_network(net, data=[x], impl="chain")
    q_dot = quantize_network(net, data=[x], impl="dot")
    assert q_chain._quant_stats["chains"] >= 1
    assert q_chain._quant_stats["folded_bns"] == 4
    oc = q_chain.outputSingle(x).numpy()
    od = q_dot.outputSingle(x).numpy()
    assert float((fp.argmax(-1) == oc.argmax(-1)).mean()) == 1.0
    assert float((fp.argmax(-1) == od.argmax(-1)).mean()) == 1.0
    # both impls are int8-faithful; chain rounds less (cache-resident)
    assert np.max(np.abs(fp - oc)) < 0.05
    assert np.max(np.abs(fp - od)) < 0.05


def test_int8_bn_scale_calibration_without_data(trained_graph):
    net, x = trained_graph
    # no calibration data: conv2 nodes (fed by BN) derive scales from
    # the BN's gamma/beta; the rest fall back to the default
    q = quantize_network(net)
    srcs = {k: v[1] for k, v in q._quant_stats["scales"].items()}
    assert srcs["r0_c2"] == "bn-stats"
    assert srcs["r0_c1"] == "default"
    out = q.outputSingle(x).numpy()
    fp = net.outputSingle(x).numpy()
    assert float((fp.argmax(-1) == out.argmax(-1)).mean()) >= 0.75


def test_quantized_net_is_inference_only(trained_graph):
    net, x = trained_graph
    q = quantize_network(net, data=[x])
    with pytest.raises(RuntimeError, match="inference-only"):
        q.fit(None)


def test_quantize_policy_opt_out():
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(nOut=8, nIn=4, activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                               activation="softmax"))
            .setInputType(InputType.feedForward(4)).build())
    net = MultiLayerNetwork(conf).init()
    pol = PrecisionPolicy.int8(min_channels=100)   # nothing qualifies
    with pytest.raises(ValueError, match="nothing to quantize"):
        quantize_network(net, policy=pol)


def test_per_layer_precision_policy_opt_out():
    """`.precisionPolicy(None)` on a layer builder must really opt the
    layer out — of QAT fake-quant AND the int8 rewrite — despite None
    being the inherit sentinel for every other field."""
    from deeplearning4j_tpu.quantize.infer import QuantizedDense
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1))
            .precisionPolicy(PrecisionPolicy.int8())
            .list()
            .layer(DenseLayer.Builder().nOut(16).activation("relu")
                   .precisionPolicy(None).build())
            .layer(DenseLayer(nOut=16, activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                               activation="softmax"))
            .setInputType(InputType.feedForward(8)).build())
    assert conf.layers[0].precisionPolicy.enabled is False
    assert conf.layers[0].precisionPolicy.applies_to(
        conf.layers[0]) is False
    assert conf.layers[1].precisionPolicy.enabled is True
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).standard_normal((8, 8)).astype(
        np.float32)
    q = quantize_network(net, data=[x])
    assert not isinstance(q.layers[0], QuantizedDense)   # opted out
    assert isinstance(q.layers[1], QuantizedDense)
    assert q._quant_stats["fallbacks"] == 1


def test_quantized_metrics_counted(trained_graph):
    net, x = trained_graph
    monitoring.enable()
    try:
        reg = monitoring.get_registry()
        before = reg.get(monitoring.QUANT_INT8_LAYERS)
        base = before.value if before is not None else 0
        quantize_network(net, data=[x])
        c = reg.get(monitoring.QUANT_INT8_LAYERS)
        assert c is not None and c.value >= base + 4
        assert reg.get(monitoring.QUANT_CALIBRATIONS) is not None
    finally:
        monitoring.disable()


def test_quantized_serving_executable_store(trained_graph, tmp_path):
    """Serving compiles quantized executables: the store fingerprints
    the int8 twin separately, steady state resolves from the memory
    tier (zero further traces), and the AOT output matches eager."""
    from deeplearning4j_tpu.runtime.executables import (ExecutableStore,
                                                        model_fingerprint)
    net, x = trained_graph
    q = quantize_network(net, data=[x])
    assert model_fingerprint(q) != model_fingerprint(net)
    store = ExecutableStore(q, directory=str(tmp_path))
    sig = ((tuple(np.shape(x)), "float32"),)
    e = store.load_or_compile(sig)
    out = np.asarray(e.call(q._params, q._state, jnp.asarray(x))[0])
    ref = q.outputSingle(x).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-6)
    traces = store.trace_calls
    for _ in range(3):
        hit = store.lookup(sig)
        assert hit is not None
        hit.call(q._params, q._state, jnp.asarray(x))
    assert store.trace_calls == traces   # zero traces past warmup


# ===================== epilogue kernels ===============================
def test_matmul_epilogue_fused_matches_composition():
    from deeplearning4j_tpu.kernels import (int8_matmul_epilogue,
                                            matmul_epilogue)
    rng = np.random.default_rng(4)
    m, k, n = 70, 12, 9
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, n)) * 0.3, jnp.float32)
    s = jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32)
    b = jnp.asarray(rng.standard_normal(n) * 0.1, jnp.float32)
    res = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    ref = np.maximum((np.asarray(x) @ np.asarray(w)) * np.asarray(s)
                     + np.asarray(b) + np.asarray(res), 0)
    out = matmul_epilogue(x, w, s, b, residual=res, act="relu",
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    xq = jnp.asarray(rng.integers(-127, 128, (m, k)), jnp.int8)
    wq = jnp.asarray(rng.integers(-127, 128, (k, n)), jnp.int8)
    acc = np.asarray(xq, np.int32) @ np.asarray(wq, np.int32)
    ref8 = acc * np.asarray(s) * 1e-3 + np.asarray(b)
    out8 = int8_matmul_epilogue(xq, wq, s * 1e-3, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out8), ref8, rtol=1e-5,
                               atol=1e-4)


def test_fused_conv_bn_eval_epilogue():
    """fused.py's eval branch now folds BN+relu into the GEMM epilogue
    kernel — must equal the conv.apply→bn.apply composition."""
    from deeplearning4j_tpu.nn.fused import fused_apply
    rng = np.random.default_rng(5)
    conv = ConvolutionLayer(kernelSize=(1, 1), nIn=6, nOut=10,
                            hasBias=False, convolutionMode="same",
                            activation="identity")
    bn = BatchNormalization(nOut=10, activation="relu")
    bn.apply_defaults({})
    x = jnp.asarray(rng.standard_normal((2, 4, 4, 6)), jnp.float32)
    pc = {"W": jnp.asarray(rng.standard_normal((1, 1, 6, 10)) * 0.4,
                           jnp.float32)}
    pb = {"gamma": jnp.asarray(rng.uniform(0.5, 1.5, 10), jnp.float32),
          "beta": jnp.asarray(rng.standard_normal(10) * 0.1,
                              jnp.float32)}
    sb = {"mean": jnp.asarray(rng.standard_normal(10) * 0.05,
                              jnp.float32),
          "var": jnp.asarray(rng.uniform(0.5, 1.5, 10), jnp.float32)}
    z, ns, y = fused_apply(conv, bn, pc, pb, sb, x, train=False,
                           interpret=True)
    yc = conv.apply(pc, {}, x, train=False)[0]
    zr = bn.apply(pb, sb, yc, train=False)[0]
    np.testing.assert_allclose(np.asarray(z), np.asarray(zr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yc), atol=1e-5)

    # autodiff THROUGH the traced eval path (input saliency etc.):
    # the epilogue kernel carries a custom VJP — gradients must match
    # the unfused composition for every differentiable input
    def fused_sum(xi, w, gamma, beta):
        zz, _, _ = fused_apply(conv, bn, {"W": w},
                               {"gamma": gamma, "beta": beta}, sb, xi,
                               train=False, interpret=True)
        return jnp.sum(zz * jnp.cos(zz))

    def unfused_sum(xi, w, gamma, beta):
        yy = conv.apply({"W": w}, {}, xi, train=False)[0]
        zz = bn.apply({"gamma": gamma, "beta": beta}, sb, yy,
                      train=False)[0]
        return jnp.sum(zz * jnp.cos(zz))

    gf = jax.jit(jax.grad(fused_sum, argnums=(0, 1, 2, 3)))(
        x, pc["W"], pb["gamma"], pb["beta"])
    gu = jax.jit(jax.grad(unfused_sum, argnums=(0, 1, 2, 3)))(
        x, pc["W"], pb["gamma"], pb["beta"])
    for a, b in zip(gf, gu):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)


# ===================== selective recompute ============================
@pytest.mark.slow   # suite diet: ~13 s (grad-compiles BOTH the plain
# and rematted graph); remat stays tier-1 via the training-step and
# layers-policy tests below — this is the bit-equality oracle only
def test_remat_blocks_gradients_equal():
    plain = _residual_graph("none")
    remat = _residual_graph("blocks")
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((4, 6, 6, 12)), jnp.float32)
    y = jnp.asarray(np.eye(4, dtype=np.float32)[
        rng.integers(0, 4, 4)])
    ins, labels = {"input": x}, [y]
    key = jax.random.PRNGKey(3)

    def grads(net):
        g, _ = jax.grad(lambda p: net._loss(p, net._state, ins, labels,
                                            None, None, key),
                        has_aux=True)(net._params)
        return g

    gp, gr = grads(plain), grads(remat)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    lp, _ = plain._loss(plain._params, plain._state, ins, labels, None,
                        None, key)
    lr, _ = remat._loss(remat._params, remat._state, ins, labels, None,
                        None, key)
    assert float(lp) == pytest.approx(float(lr), abs=1e-6)


def test_remat_blocks_training_step_runs():
    net = _residual_graph("blocks")
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4, 6, 6, 12)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]
    net.fit(DataSet(x, y))
    assert np.isfinite(net.score())


def test_remat_layers_policy_multilayer():
    def build(remat):
        b = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1))
             .list()
             .layer(DenseLayer(nOut=16, activation="tanh"))
             .layer(DenseLayer(nOut=16, activation="tanh"))
             .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                                activation="softmax"))
             .setInputType(InputType.feedForward(8)))
        if remat:
            b.rematPolicy("layers")
        return MultiLayerNetwork(b.build()).init()

    plain, remat = build(False), build(True)
    assert remat.conf.layers[0].remat is True
    assert getattr(plain.conf.layers[0], "remat", None) is None
    rng = np.random.default_rng(8)
    x = rng.standard_normal((8, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    gp = plain.computeGradients(x, y)
    gr = remat.computeGradients(x, y)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_traffic_ledger_remat_reduction_and_gauge():
    plain = _residual_graph("none", wide=16, narrow=8, blocks=3, hw=8)
    remat = _residual_graph("blocks", wide=16, narrow=8, blocks=3, hw=8)
    rp = activation_report(plain, batch=4)
    rr = activation_report(remat, batch=4)
    assert rp["saved_bytes"] == rp["forward_bytes"]
    reduction = 1 - rr["saved_bytes"] / rp["saved_bytes"]
    assert reduction >= 0.30   # ISSUE acceptance bar
    monitoring.enable()
    try:
        from deeplearning4j_tpu.quantize.traffic import publish
        publish(remat, batch=4, model_name="resblock")
        text = monitoring.get_registry().prometheus_text()
        assert "dl4j_quant_activation_traffic_bytes" in text
    finally:
        monitoring.disable()


# ===================== int8 KV-cache decode ===========================
@pytest.fixture(scope="module")
def tiny_bert():
    from deeplearning4j_tpu.models.bert import bert_tiny, init_bert_params
    cfg = bert_tiny()
    params = init_bert_params(cfg, jax.random.PRNGKey(1))
    return cfg, params


def _decode_stream(dec, prompt, steps=6):
    margs = dec.model_args()
    plen = len(prompt)
    cache = dec.init_cache(2, 32)
    cache, logits = dec.prefill(
        margs, cache, jnp.int32(1),
        jnp.asarray(np.pad(prompt, (0, 16 - plen))), jnp.int32(plen))
    toks, lgs = [int(jnp.argmax(logits))], [np.asarray(logits)]
    for t in range(steps):
        tv = jnp.zeros((2,), jnp.int32).at[1].set(toks[-1])
        pos = jnp.zeros((2,), jnp.int32).at[1].set(plen + t)
        lg, cache = dec.step(margs, cache, tv, pos)
        lgs.append(np.asarray(lg[1]))
        toks.append(int(jnp.argmax(lg[1])))
    return toks, lgs


def test_int8_kv_cache_decode_matches_fp(tiny_bert):
    from deeplearning4j_tpu.generation import BertDecoder
    cfg, params = tiny_bert
    prompt = np.random.default_rng(9).integers(
        1, cfg.vocab_size, 7).astype(np.int32)
    fp_toks, fp_lgs = _decode_stream(BertDecoder(cfg, params), prompt)
    q_dec = BertDecoder(cfg, params, kv_dtype="int8")
    q_toks, q_lgs = _decode_stream(q_dec, prompt)
    assert q_toks == fp_toks          # greedy stream identical
    for a, b in zip(fp_lgs, q_lgs):
        np.testing.assert_allclose(a, b, atol=2e-3)
    # cache really is int8 + per-(head, position) scales
    cache = q_dec.init_cache(2, 16)
    assert cache["k"].dtype == jnp.int8
    assert cache["ks"].shape == cache["k"].shape[:4]
    # fingerprints differ: quantized executables cache separately
    assert (BertDecoder(cfg, params).fingerprint()
            != q_dec.fingerprint())


def test_int8_kv_cache_grow_pads_scales(tiny_bert):
    from deeplearning4j_tpu.generation import BertDecoder
    cfg, params = tiny_bert
    dec = BertDecoder(cfg, params, kv_dtype="int8")
    cache = dec.init_cache(2, 8)
    grown = dec.grow(cache, 16)
    assert grown["k"].shape[3] == 16
    assert grown["ks"].shape[3] == 16
    # padded scale rows are 1.0 (zero rows round-trip exactly)
    assert float(jnp.min(grown["ks"][:, :, :, 8:])) == 1.0


def test_flash_decode_quantized_matches_dequantized_reference():
    from deeplearning4j_tpu.kernels.flash_attention import \
        flash_attention_decode
    from deeplearning4j_tpu.quantize.kvcache import (dequantize_rows,
                                                     quantize_rows)
    rng = np.random.default_rng(10)
    b, h, c, d = 3, 2, 11, 8
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, c, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, c, d)), jnp.float32)
    lens = np.array([0, 4, 11])   # incl. an empty-mask row
    mask = jnp.asarray(
        (np.arange(c)[None, :] < lens[:, None]).astype(np.float32))
    kq, ks = quantize_rows(k)
    vq, vs = quantize_rows(v)
    fused = flash_attention_decode(q, kq, vq, mask, k_scale=ks,
                                   v_scale=vs)
    # oracle: dequantize the cache, run the stock dense reference
    ref = flash_attention_decode(q, dequantize_rows(kq, ks),
                                 dequantize_rows(vq, vs), mask,
                                 impl="dense")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    assert np.all(np.asarray(fused[0]) == 0)   # empty row zeroed
    with pytest.raises(ValueError, match="k_scale and v_scale"):
        flash_attention_decode(q, kq, vq, mask, k_scale=ks)
    with pytest.raises(ValueError, match="must be given together"):
        flash_attention_decode(q, kq, vq, mask, v_scale=vs)


def test_int8_generation_server_stream(tiny_bert):
    """End to end through the GenerationServer: int8-cache decode
    serves the same greedy stream the fp-cache server does."""
    from deeplearning4j_tpu.generation import (BertDecoder,
                                               GenerationServer)
    cfg, params = tiny_bert
    prompt = list(np.random.default_rng(11).integers(
        1, cfg.vocab_size, 5))

    def serve(kv_dtype):
        srv = GenerationServer(
            BertDecoder(cfg, params, kv_dtype=kv_dtype), slots=2,
            cache_lengths=[32], prompt_buckets=[8], method="greedy",
            max_new_tokens=5, seed=0)
        try:
            srv.warmup()
            return srv.generate(prompt, timeout=60)
        finally:
            srv.shutdown()

    assert serve("int8") == serve("fp")
