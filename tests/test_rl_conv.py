"""Pixel-input RL path (VERDICT r3 #7; ≡ rl4j HistoryProcessor /
DQNFactoryStdConv / QLearningDiscreteConv tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu.rl import (DQNConvNetworkConfiguration,
                                   DQNFactoryStdConv, HistoryProcessor,
                                   HistoryProcessorConfiguration,
                                   PixelGridWorld, QLearningConfiguration,
                                   QLearningDiscreteConv)


class TestHistoryProcessor:
    def test_grayscale_crop_rescale(self):
        conf = HistoryProcessorConfiguration(
            historyLength=3, rescaledWidth=4, rescaledHeight=4,
            croppingWidth=8, croppingHeight=8, offsetX=2, offsetY=2,
            skipFrame=1)
        hp = HistoryProcessor(conf)
        frame = np.zeros((12, 12, 3), np.uint8)
        frame[2:10, 2:10] = 255            # bright crop region
        f = hp.preProcess(frame)
        assert f.shape == (4, 4)
        np.testing.assert_allclose(f, 1.0, atol=1e-6)   # RGB→luma→/255

    def test_ring_cold_start_and_rotation(self):
        conf = HistoryProcessorConfiguration(
            historyLength=3, rescaledWidth=2, rescaledHeight=2, skipFrame=1)
        hp = HistoryProcessor(conf)
        with pytest.raises(RuntimeError, match="record"):
            hp.getHistory()
        hp.record(np.full((2, 2), 1.0, np.float32))
        h = hp.getHistory()
        # cold start: ring filled with the first frame
        assert h.shape == (2, 2, 3)
        np.testing.assert_array_equal(h, 1.0)
        hp.record(np.full((2, 2), 0.5, np.float32))
        h = hp.getHistory()
        # newest frame rides in the LAST channel
        np.testing.assert_array_equal(h[..., -1], 0.5)
        np.testing.assert_array_equal(h[..., 0], 1.0)
        hp.reset()
        with pytest.raises(RuntimeError):
            hp.getHistory()

    def test_nearest_resize_downscale(self):
        conf = HistoryProcessorConfiguration(
            historyLength=1, rescaledWidth=3, rescaledHeight=3, skipFrame=1)
        hp = HistoryProcessor(conf)
        frame = np.arange(36, dtype=np.float32).reshape(6, 6) / 36.0
        f = hp.preProcess(frame)
        assert f.shape == (3, 3)
        np.testing.assert_allclose(f, frame[::2, ::2], atol=1e-6)


class TestConvFactory:
    def test_builds_atari_shape_net(self):
        net = DQNFactoryStdConv(DQNConvNetworkConfiguration(
            filters=(16, 32), kernels=((8, 8), (4, 4)),
            strides=((4, 4), (2, 2)), denseUnits=64)).buildDQN(
                (84, 84, 4), 6, seed=0)
        q = np.asarray(net.output(
            np.zeros((2, 84, 84, 4), np.float32)).numpy())
        assert q.shape == (2, 6)


class TestQLearningDiscreteConv:
    def test_pixel_dqn_reaches_learning_criterion(self):
        """Synthetic pixel MDP → conv DQN → greedy policy reaches the
        optimal return (VERDICT r3 #7 acceptance)."""
        mdp = PixelGridWorld(size=6, scale=2, maxSteps=30)
        hp = HistoryProcessorConfiguration(
            historyLength=2, rescaledWidth=12, rescaledHeight=12,
            skipFrame=1)
        net = DQNConvNetworkConfiguration(
            learningRate=1e-3, filters=(8,), kernels=((3, 3),),
            strides=((2, 2),), denseUnits=32)
        ql = QLearningConfiguration(
            seed=1, maxEpochStep=30, maxStep=600, expRepMaxSize=5000,
            batchSize=16, targetDqnUpdateFreq=50, updateStart=20,
            gamma=0.95, minEpsilon=0.05, epsilonNbStep=300)
        learn = QLearningDiscreteConv(mdp, net, hp, ql)
        rewards = learn.train()
        assert len(rewards) > 10
        # optimal: 5 right moves = 4·(−0.01) + 1.0 = 0.96
        play = learn.getPolicy().play(
            PixelGridWorld(size=6, scale=2, maxSteps=30))
        assert play > 0.9

    def test_frame_skip_repeats_action(self):
        mdp = PixelGridWorld(size=8, scale=1, maxSteps=50)
        hp = HistoryProcessorConfiguration(
            historyLength=2, rescaledWidth=8, rescaledHeight=8,
            skipFrame=3)
        ql = QLearningConfiguration(seed=0, maxEpochStep=4, maxStep=4,
                                    updateStart=100, batchSize=4)
        net = DQNConvNetworkConfiguration(
            filters=(4,), kernels=((3, 3),), strides=((2, 2),),
            denseUnits=8)
        learn = QLearningDiscreteConv(mdp, net, hp, ql)
        learn.train()
        # each agent decision advances the env by `skipFrame` frames:
        # replay holds one transition per DECISION, the env counts frames
        assert 1 <= len(learn.replay) <= 4
        assert mdp._steps == 3 * len(learn.replay) or mdp.isDone()


class TestA3CDiscreteConv:
    def test_pixel_a3c_learns_optimal_play(self):
        from deeplearning4j_tpu.rl import (A3CConfiguration,
                                           A3CDiscreteConv)
        hp = HistoryProcessorConfiguration(
            historyLength=2, rescaledWidth=12, rescaledHeight=12,
            skipFrame=1)
        net = DQNConvNetworkConfiguration(
            filters=(8,), kernels=((3, 3),), strides=((2, 2),),
            denseUnits=32)
        conf = A3CConfiguration(seed=3, numEnvs=8, nstep=5, maxStep=4000,
                                learningRate=3e-3, gamma=0.95,
                                entropyCoef=0.01)
        a3c = A3CDiscreteConv(
            lambda: PixelGridWorld(size=6, scale=2, maxSteps=30),
            conf=conf, hp_conf=hp, net_conf=net)
        rewards = a3c.train()
        assert len(rewards) > 10
        # greedy play on a RAW pixel MDP: play() wires the pipeline
        total = a3c.play(PixelGridWorld(size=6, scale=2, maxSteps=30),
                         max_steps=30)
        assert total > 0.9   # optimal = 0.96

    def test_observation_shapes_flow(self):
        from deeplearning4j_tpu.rl import A3CConfiguration, A3CDiscreteConv
        hp = HistoryProcessorConfiguration(
            historyLength=3, rescaledWidth=8, rescaledHeight=8,
            skipFrame=2)
        net = DQNConvNetworkConfiguration(
            filters=(4,), kernels=((3, 3),), strides=((2, 2),),
            denseUnits=8)
        a3c = A3CDiscreteConv(
            lambda: PixelGridWorld(size=8, scale=1, maxSteps=10),
            conf=A3CConfiguration(seed=0, numEnvs=2, nstep=2, maxStep=8),
            hp_conf=hp, net_conf=net)
        assert a3c.envs[0].getObservationSpace().shape == (8, 8, 3)
        a3c.train()   # runs 2 updates without shape errors
        assert a3c.step_count >= 8
