#!/usr/bin/env python
"""Fast-path lint: instrumented hot-path modules must not call the
metrics registry outside an enabled-guard.

The monitoring contract since PR 1 is ONE branch on the disabled path:
every `registry.counter(...)` / `.gauge(...)` / `.histogram(...)` /
`get_registry()` reachable per-step must sit inside the
`if _mon.enabled():` / `if STATE.enabled:` guard pattern (or behind an
early `if not ...enabled...: return`). A bare registry call costs a
lock + dict lookup + possible allocation per step even with monitoring
off — exactly the always-on overhead the disabled-by-default design
exists to prevent, and the kind of regression that creeps in silently
with new instrumentation.

This script AST-walks the hot-path modules and reports violations;
`tests/test_fastpath_lint.py` runs it in tier-1 so a violating PR fails
CI. Run manually:  python scripts/check_fastpath.py  (exit 1 on
violations).

Intentionally NOT linted: `monitoring/` internals (they ARE the guard),
`_mon.span(...)` / `record_transfer(...)` / `step_recorder()` (each
internally one flag check), and cold-path modules (listeners, ui,
resilience policies) where a per-call registry lookup is irrelevant.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-step hot-path modules (relative to the repo root). The
#: resilience entries are the guardian/watchdog/fault hooks that sit
#: INSIDE every train step — their registry calls must be behind the
#: enabled-guard exactly like the trainers' own instrumentation
#: (resilience/policy.py stays unlinted: breaker trips and retry
#: backoffs are cold by definition).
HOT_MODULES = [
    "deeplearning4j_tpu/nn/multilayer.py",
    "deeplearning4j_tpu/nn/graph.py",
    "deeplearning4j_tpu/runtime/executioner.py",
    "deeplearning4j_tpu/runtime/pipeline.py",
    "deeplearning4j_tpu/runtime/executables.py",
    "deeplearning4j_tpu/parallel/wrapper.py",
    "deeplearning4j_tpu/parallel/sharded_trainer.py",
    "deeplearning4j_tpu/parallel/inference.py",
    # multi-host hot hooks: the per-step coordination/heartbeat/verdict
    # paths must stay one pointer compare when disabled, and their
    # sync-point registry calls guarded like everything else
    "deeplearning4j_tpu/parallel/coordination.py",
    "deeplearning4j_tpu/parallel/multihost.py",
    "deeplearning4j_tpu/resilience/guardian.py",
    "deeplearning4j_tpu/resilience/watchdog.py",
    "deeplearning4j_tpu/resilience/faults.py",
    "deeplearning4j_tpu/resilience/trainer.py",
]

# -- serving steady-state lint --------------------------------------------
#: modules forming the AOT serving hot path: everything REACHABLE from
#: the roots below (intra-repo call graph by function name) must never
#: trace or compile — `jax.jit` / `.lower()` / `.compile()` belong to
#: the declared miss-path boundary functions only
SERVING_MODULES = [
    "deeplearning4j_tpu/parallel/inference.py",
    "deeplearning4j_tpu/runtime/executables.py",
]
#: steady-state entry points: the collector's dispatch path and the
#: store/ring hot methods
SERVING_ROOTS = {"_dispatch", "_run", "lookup", "stage", "release"}
#: the documented miss-path boundary: steady state never crosses it
#: (`load_or_compile` runs only when `lookup` missed — i.e. a shape
#: outside the warmed ladder); the traversal does not descend into it
SERVING_MISS_BOUNDARY = {"load_or_compile", "warmup"}
#: calls that mean "a trace or an XLA compile happens here"
TRACE_CALL_NAMES = {"jit", "lower", "compile", "eval_shape", "trace"}

#: attribute calls that hit the registry
REGISTRY_ATTRS = {"counter", "gauge", "histogram"}
#: bare/attribute function names that resolve the registry
REGISTRY_FUNCS = {"get_registry"}

#: substrings that mark an `if` test (or early-return guard test) as the
#: enabled-guard: `_mon.enabled()`, `STATE.enabled`, a cached
#: `mon_on = _mon.enabled()`, or an armed-session check
GUARD_TOKENS = ("enabled", "STATE.", "mon_on", "ACTIVE")


def _is_registry_call(node):
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in REGISTRY_ATTRS:
        return f".{f.attr}(...)"
    name = None
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in REGISTRY_FUNCS:
        return f"{name}()"
    return None


def _test_is_guard(test):
    try:
        src = ast.unparse(test)
    except Exception:  # noqa: BLE001 — unparse of odd nodes
        return False
    return any(tok in src for tok in GUARD_TOKENS)


def _guarded(node, ancestors):
    """Inside an `if <enabled-ish>` block, or after an early-return
    `if not <enabled-ish>: return` in the enclosing function."""
    func = None
    for anc in reversed(ancestors):
        if isinstance(anc, ast.If) and _test_is_guard(anc.test):
            return True
        if func is None and isinstance(anc, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
            func = anc
    if func is not None:
        for stmt in ast.walk(func):
            if isinstance(stmt, ast.If) and _test_is_guard(stmt.test) \
                    and stmt.lineno < node.lineno \
                    and any(isinstance(s, (ast.Return, ast.Raise))
                            for s in stmt.body):
                return True
    return False


def check_source(source, path="<string>"):
    """[(path, lineno, description)] for unguarded registry calls."""
    tree = ast.parse(source, filename=path)
    violations = []

    def walk(node, ancestors):
        if isinstance(node, ast.Call):
            what = _is_registry_call(node)
            if what is not None and not _guarded(node, ancestors):
                violations.append(
                    (path, node.lineno,
                     f"{what} outside the enabled-guard fast path"))
        for child in ast.iter_child_nodes(node):
            walk(child, ancestors + [node])

    walk(tree, [])
    return violations


def check_file(path):
    with open(path) as f:
        return check_source(f.read(), path)


# -- serving steady-state lint (no trace/compile reachable from the
#    dispatch path) ---------------------------------------------------------
def _call_name(node):
    """Best-effort callee name of a Call: `f(...)` → f, `a.b.f(...)` →
    f. Good enough for an intra-repo method-name call graph."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_trace_call(node):
    name = _call_name(node)
    if name not in TRACE_CALL_NAMES:
        return None
    f = node.func
    # `jax.jit(...)` / `jit(...)` / `<lowered>.compile()` /
    # `jit(...).lower(...)` all count; plain `"x".lower()` string
    # methods share the name — accept the (theoretical) false positive
    # over missing a real trace on the serving path
    return f".{name}(...)" if isinstance(f, ast.Attribute) \
        else f"{name}(...)"


def check_serving_steady_state(sources):
    """sources: {path: source}. Walks the union call graph of every
    function/method defined in the serving modules, starting from
    SERVING_ROOTS and NOT descending into SERVING_MISS_BOUNDARY, and
    flags any trace/compile call inside the reachable set. Steady-state
    serving (post-`warmup()`) must resolve every dispatch from the
    in-memory executable tier — a reachable `jax.jit`/`lower`/`compile`
    means a novel shape could trace ON the request path."""
    defs = {}        # name -> (path, FunctionDef)
    for path, source in sources.items():
        tree = ast.parse(source, filename=path)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, (path, node))
    violations = []
    seen = set()
    frontier = [r for r in SERVING_ROOTS if r in defs]
    while frontier:
        name = frontier.pop()
        if name in seen or name in SERVING_MISS_BOUNDARY:
            continue
        seen.add(name)
        path, fn = defs[name]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            what = _is_trace_call(node)
            if what is not None:
                violations.append(
                    (path, node.lineno,
                     f"{what} reachable from the serving dispatch "
                     f"path (via {name}) — steady state must stay "
                     "inside the AOT executable cache"))
            callee = _call_name(node)
            if callee in defs and callee not in seen \
                    and callee not in SERVING_MISS_BOUNDARY:
                frontier.append(callee)
    return violations


def main(modules=None):
    violations = []
    for rel in modules or HOT_MODULES:
        path = os.path.join(REPO_ROOT, rel)
        if not os.path.exists(path):
            continue
        violations.extend(check_file(path))
    if modules is None:
        sources = {}
        for rel in SERVING_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    sources[path] = f.read()
        violations.extend(check_serving_steady_state(sources))
    for path, lineno, msg in violations:
        print(f"{os.path.relpath(path, REPO_ROOT)}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} fast-path violation(s): wrap "
              "registry calls in `if _mon.enabled():` (or an early "
              "`if not STATE.enabled: return`) so the disabled path "
              "stays one branch, and keep traces/compiles behind the "
              "executable-store miss boundary (load_or_compile).")
    return violations


if __name__ == "__main__":
    sys.exit(1 if main(sys.argv[1:] or None) else 0)
