#!/usr/bin/env python
"""Fast-path lint: instrumented hot-path modules must not call the
metrics registry outside an enabled-guard.

The monitoring contract since PR 1 is ONE branch on the disabled path:
every `registry.counter(...)` / `.gauge(...)` / `.histogram(...)` /
`get_registry()` reachable per-step must sit inside the
`if _mon.enabled():` / `if STATE.enabled:` guard pattern (or behind an
early `if not ...enabled...: return`). A bare registry call costs a
lock + dict lookup + possible allocation per step even with monitoring
off — exactly the always-on overhead the disabled-by-default design
exists to prevent, and the kind of regression that creeps in silently
with new instrumentation.

This script AST-walks the hot-path modules and reports violations;
`tests/test_fastpath_lint.py` runs it in tier-1 so a violating PR fails
CI. Run manually:  python scripts/check_fastpath.py  (exit 1 on
violations).

Intentionally NOT linted: `monitoring/` internals (they ARE the guard),
`_mon.span(...)` / `record_transfer(...)` / `step_recorder()` (each
internally one flag check), and cold-path modules (listeners, ui,
resilience policies) where a per-call registry lookup is irrelevant.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-step hot-path modules (relative to the repo root). The
#: resilience entries are the guardian/watchdog/fault hooks that sit
#: INSIDE every train step — their registry calls must be behind the
#: enabled-guard exactly like the trainers' own instrumentation
#: (resilience/policy.py stays unlinted: breaker trips and retry
#: backoffs are cold by definition).
HOT_MODULES = [
    "deeplearning4j_tpu/nn/multilayer.py",
    "deeplearning4j_tpu/nn/graph.py",
    "deeplearning4j_tpu/runtime/executioner.py",
    "deeplearning4j_tpu/runtime/pipeline.py",
    "deeplearning4j_tpu/parallel/wrapper.py",
    "deeplearning4j_tpu/parallel/sharded_trainer.py",
    "deeplearning4j_tpu/parallel/inference.py",
    "deeplearning4j_tpu/resilience/guardian.py",
    "deeplearning4j_tpu/resilience/watchdog.py",
    "deeplearning4j_tpu/resilience/faults.py",
    "deeplearning4j_tpu/resilience/trainer.py",
]

#: attribute calls that hit the registry
REGISTRY_ATTRS = {"counter", "gauge", "histogram"}
#: bare/attribute function names that resolve the registry
REGISTRY_FUNCS = {"get_registry"}

#: substrings that mark an `if` test (or early-return guard test) as the
#: enabled-guard: `_mon.enabled()`, `STATE.enabled`, a cached
#: `mon_on = _mon.enabled()`, or an armed-session check
GUARD_TOKENS = ("enabled", "STATE.", "mon_on", "ACTIVE")


def _is_registry_call(node):
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in REGISTRY_ATTRS:
        return f".{f.attr}(...)"
    name = None
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in REGISTRY_FUNCS:
        return f"{name}()"
    return None


def _test_is_guard(test):
    try:
        src = ast.unparse(test)
    except Exception:  # noqa: BLE001 — unparse of odd nodes
        return False
    return any(tok in src for tok in GUARD_TOKENS)


def _guarded(node, ancestors):
    """Inside an `if <enabled-ish>` block, or after an early-return
    `if not <enabled-ish>: return` in the enclosing function."""
    func = None
    for anc in reversed(ancestors):
        if isinstance(anc, ast.If) and _test_is_guard(anc.test):
            return True
        if func is None and isinstance(anc, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
            func = anc
    if func is not None:
        for stmt in ast.walk(func):
            if isinstance(stmt, ast.If) and _test_is_guard(stmt.test) \
                    and stmt.lineno < node.lineno \
                    and any(isinstance(s, (ast.Return, ast.Raise))
                            for s in stmt.body):
                return True
    return False


def check_source(source, path="<string>"):
    """[(path, lineno, description)] for unguarded registry calls."""
    tree = ast.parse(source, filename=path)
    violations = []

    def walk(node, ancestors):
        if isinstance(node, ast.Call):
            what = _is_registry_call(node)
            if what is not None and not _guarded(node, ancestors):
                violations.append(
                    (path, node.lineno,
                     f"{what} outside the enabled-guard fast path"))
        for child in ast.iter_child_nodes(node):
            walk(child, ancestors + [node])

    walk(tree, [])
    return violations


def check_file(path):
    with open(path) as f:
        return check_source(f.read(), path)


def main(modules=None):
    violations = []
    for rel in modules or HOT_MODULES:
        path = os.path.join(REPO_ROOT, rel)
        if not os.path.exists(path):
            continue
        violations.extend(check_file(path))
    for path, lineno, msg in violations:
        print(f"{os.path.relpath(path, REPO_ROOT)}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} fast-path violation(s): wrap the "
              "call in `if _mon.enabled():` (or an early "
              "`if not STATE.enabled: return`) so the disabled path "
              "stays one branch.")
    return violations


if __name__ == "__main__":
    sys.exit(1 if main(sys.argv[1:] or None) else 0)
