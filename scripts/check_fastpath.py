#!/usr/bin/env python
"""Fast-path lint: instrumented hot-path modules must not call the
metrics registry outside an enabled-guard.

The monitoring contract since PR 1 is ONE branch on the disabled path:
every `registry.counter(...)` / `.gauge(...)` / `.histogram(...)` /
`get_registry()` reachable per-step must sit inside the
`if _mon.enabled():` / `if STATE.enabled:` guard pattern (or behind an
early `if not ...enabled...: return`). A bare registry call costs a
lock + dict lookup + possible allocation per step even with monitoring
off — exactly the always-on overhead the disabled-by-default design
exists to prevent, and the kind of regression that creeps in silently
with new instrumentation.

This script AST-walks the hot-path modules and reports violations;
`tests/test_fastpath_lint.py` runs it in tier-1 so a violating PR fails
CI. Run manually:  python scripts/check_fastpath.py  (exit 1 on
violations).

Intentionally NOT linted: `monitoring/` internals (they ARE the guard),
`_mon.span(...)` / `record_transfer(...)` / `step_recorder()` (each
internally one flag check), and cold-path modules (listeners, ui,
resilience policies) where a per-call registry lookup is irrelevant.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-step hot-path modules (relative to the repo root). The
#: resilience entries are the guardian/watchdog/fault hooks that sit
#: INSIDE every train step — their registry calls must be behind the
#: enabled-guard exactly like the trainers' own instrumentation
#: (resilience/policy.py stays unlinted: breaker trips and retry
#: backoffs are cold by definition).
HOT_MODULES = [
    "deeplearning4j_tpu/nn/multilayer.py",
    "deeplearning4j_tpu/nn/graph.py",
    "deeplearning4j_tpu/runtime/executioner.py",
    "deeplearning4j_tpu/runtime/pipeline.py",
    "deeplearning4j_tpu/runtime/executables.py",
    "deeplearning4j_tpu/parallel/wrapper.py",
    "deeplearning4j_tpu/parallel/sharded_trainer.py",
    "deeplearning4j_tpu/parallel/inference.py",
    # multi-host hot hooks: the per-step coordination/heartbeat/verdict
    # paths must stay one pointer compare when disabled, and their
    # sync-point registry calls guarded like everything else
    "deeplearning4j_tpu/parallel/coordination.py",
    "deeplearning4j_tpu/parallel/multihost.py",
    # elastic membership: `pending()` folds into EVERY heartbeat, and
    # the reform/commit/reap paths live next to the runner's counters —
    # registry traffic there obeys the same enabled-guard contract
    "deeplearning4j_tpu/parallel/membership.py",
    "deeplearning4j_tpu/resilience/guardian.py",
    "deeplearning4j_tpu/resilience/watchdog.py",
    "deeplearning4j_tpu/resilience/faults.py",
    "deeplearning4j_tpu/resilience/trainer.py",
    # generation decode loop: per-token metric calls must stay behind
    # the enabled-guard (one dict-get + dispatch per token otherwise)
    "deeplearning4j_tpu/generation/server.py",
    "deeplearning4j_tpu/generation/decode.py",
    "deeplearning4j_tpu/generation/sampling.py",
    "deeplearning4j_tpu/generation/paging.py",
    # fleet router: routed/failover counters ride every request's
    # relay path — guarded, or the disabled fleet pays per request
    "deeplearning4j_tpu/generation/fleet.py",
    # quantized inference: the rewritten layers' apply() and the chain
    # executor run inside every served forward — registry calls belong
    # to the rewrite/calibration cold path only
    "deeplearning4j_tpu/quantize/core.py",
    "deeplearning4j_tpu/quantize/infer.py",
    "deeplearning4j_tpu/quantize/kvcache.py",
    # request-timeline module: its appends ride the decode/dispatch
    # hot paths, so any registry/exemplar traffic it ever grows must
    # sit behind the enabled guard like the call sites that feed it.
    # monitoring/slo.py and monitoring/cluster.py stay UNLINTED on
    # purpose: both are pull-driven (endpoint / sync-point cadence,
    # never per step) — the same cold-path class as listeners and ui.
    "deeplearning4j_tpu/monitoring/requests.py",
]

# -- serving steady-state lint --------------------------------------------
#: modules forming the AOT serving hot path: everything REACHABLE from
#: the roots below (intra-repo call graph by function name) must never
#: trace or compile — `jax.jit` / `.lower()` / `.compile()` belong to
#: the declared miss-path boundary functions only
SERVING_MODULES = [
    "deeplearning4j_tpu/parallel/inference.py",
    "deeplearning4j_tpu/runtime/executables.py",
    # request timelines are appended from the dispatch path — the
    # walker descends into the append helpers to prove they stay pure
    # host bookkeeping (no trace, no compile)
    "deeplearning4j_tpu/monitoring/requests.py",
]
#: steady-state entry points: the collector's dispatch path and the
#: store/ring hot methods
SERVING_ROOTS = {"_dispatch", "_run", "lookup", "stage", "release"}
#: the documented miss-path boundary: steady state never crosses it
#: (`load_or_compile` runs only when `lookup` missed — i.e. a shape
#: outside the warmed ladder); the traversal does not descend into it
SERVING_MISS_BOUNDARY = {"load_or_compile", "warmup"}
#: calls that mean "a trace or an XLA compile happens here"
TRACE_CALL_NAMES = {"jit", "lower", "compile", "eval_shape", "trace"}

# -- generation decode-loop lint -------------------------------------------
#: modules forming the generation hot path: the decode loop's
#: step/admit/retire must resolve every dispatch from pre-compiled
#: executables (trace rule) and the ONLY per-token host sync is the
#: sampled-token fetch (sync rule)
GENERATION_MODULES = [
    "deeplearning4j_tpu/generation/server.py",
    "deeplearning4j_tpu/generation/decode.py",
    "deeplearning4j_tpu/generation/sampling.py",
    # paged-KV bookkeeping runs BETWEEN every pair of decode dispatches
    # (page allocation, prefix lookup, CoW planning, table build) — it
    # must stay pure host numpy/python: no trace, no device sync
    "deeplearning4j_tpu/generation/paging.py",
    "deeplearning4j_tpu/runtime/executables.py",
    # the int8 KV-cache codec runs INSIDE the decode step (quantize the
    # new K/V row, dequant-in-attention) — it must obey the same
    # no-trace / no-host-sync rules as the rest of the loop
    "deeplearning4j_tpu/quantize/kvcache.py",
    "deeplearning4j_tpu/quantize/core.py",
    # request-timeline appends ride the decode loop's delivery path —
    # they must stay INSIDE the declared _deliver_block/_fetch_tokens
    # sync boundary: pure host bookkeeping, no device materialization,
    # no trace. The walker descends into event()/finish() to prove it.
    "deeplearning4j_tpu/monitoring/requests.py",
]
#: decode-loop entry points (GenerationServer hot methods) PLUS the
#: crash-replay/supervised-restart path: re-admission and the key
#: advance must also resolve entirely from the warmed executable set
#: (the supervisor promises restarts with ZERO live compiles). The
#: superstep pipeline's dispatch/deliver pair and the drafting
#: proposal/verify path are decode-loop steady state too.
GENERATION_ROOTS = {"_dispatch_block", "_deliver_block",
                    "_superstep_args", "_propose_drafts",
                    "_admit_pending", "_admit_one",
                    "_admit_rec", "_retire_slot", "_deliver",
                    "_survive", "_recover", "_replay_one",
                    "_advance_key", "_supervised_restart",
                    # paged-KV hot path: per-block page prep and the
                    # allocator's admission/eviction/prefix machinery
                    # resolve from pre-compiled executables only
                    "_page_args", "admit_slot", "ensure_range",
                    "evict_cold", "release_slot", "build_table"}
#: the declared warmup boundary — steady state never crosses it
GENERATION_MISS_BOUNDARY = {"load_or_compile", "warmup",
                            "_warmup_locked"}
#: per-superstep sync rule: only the declared fetch boundary may touch
#: device values — `_fetch_tokens` (the blocking materialization) and
#: `_start_fetch` (the non-blocking copy_to_host_async initiation that
#: overlaps the next dispatch). `_deliver`/`_push` are roots too: the
#: crash-replay journal append (the delivered-token list) must stay on
#: the existing `_fetch_tokens` host boundary — no extra syncs; the
#: drafting proposal must stay pure host numpy.
GENERATION_SYNC_ROOTS = {"_dispatch_block", "_deliver_block",
                         "_superstep_args", "_propose_drafts",
                         "_deliver", "_push",
                         # retirement closes the request timeline
                         # (trace.event/finish) — walked so the close
                         # path stays host-pure too
                         "_retire_slot", "_finish", "_fail",
                         # paged-KV page prep rides the dispatch
                         # boundary: allocation, prefix lookup, CoW
                         # planning, table build, and the pool metrics
                         # emit must add ZERO host syncs per token
                         "_page_args", "_emit_page_metrics",
                         "admit_slot", "abort_admit", "ensure_range",
                         "evict_cold", "release_slot", "build_table"}
GENERATION_SYNC_BOUNDARY = {"_fetch_tokens", "_start_fetch"}
#: calls that mean "the host blocks on (or copies back) device data"
SYNC_CALL_NAMES = {"asarray", "device_get", "block_until_ready",
                   "item", "tolist", "copy_to_host_async"}

# -- fleet-router hot-path lint --------------------------------------------
#: the fleet router's route / dispatch / relay / failover walk runs on
#: EVERY request (and every mid-stream failover): it must stay pure
#: host bookkeeping — no trace, no device sync. Linted on fleet.py
#: alone: the replica servers it drives are covered by the generation
#: lint above, and `submit()` is deliberately NOT a root (prompt
#: normalization np.asarray lives there, exactly like the server's).
FLEET_MODULES = ["deeplearning4j_tpu/generation/fleet.py"]
#: per-request / per-failover entry points: replica selection, the
#: adopt-hook dispatch, the stream relay pump, the failover decision,
#: and the health/burn bookkeeping they lean on
FLEET_ROOTS = {"_route", "_dispatch", "_relay", "_failover",
               "_health", "_mark", "_retryable", "_finalize"}
#: the declared cold boundary — replica replacement (supervision) may
#: warm executables from the shared disk store; the routing walk never
#: crosses into it
FLEET_BOUNDARY = {"_supervise", "warmup"}

# -- training-exchange lint (accumulation scan + bucketed exchange) --------
#: modules forming the distributed train-step hot path: the in-step
#: accumulation scan, the bucket planner, and the bucketed
#: encode→pmean→decode exchange must perform NO host sync — one
#: dispatch per optimizer step, and the per-optimizer-step fetch
#: (encoder_stats / guardian _materialize / lazy score) stays the one
#: declared boundary
TRAIN_MODULES = [
    "deeplearning4j_tpu/parallel/sharded_trainer.py",
    "deeplearning4j_tpu/parallel/multihost.py",
    "deeplearning4j_tpu/parallel/buckets.py",
    "deeplearning4j_tpu/parallel/compression.py",
    "deeplearning4j_tpu/nn/accum.py",
]
#: per-optimizer-step entry points: the step builders (their traced
#: bodies), the accumulation core, the bucket planner (host-side but
#: must stay shape-metadata-only), and the dispatch hook
TRAIN_SYNC_ROOTS = {"make_step", "make_guarded_step", "_make_exchange",
                    "accumulate_grads", "accum_scan", "fit_batch",
                    "plan_buckets", "concat", "split",
                    # the sparse wire codec runs INSIDE the traced
                    # exchange — encode, size-prefixed decode rows and
                    # the chain-sum accumulate are explicit roots so a
                    # host sync in the wire path can never hide behind
                    # a renamed call site
                    "sparse_encode", "sparse_decode", "_decode_row",
                    "wire_caps"}
#: the declared host-fetch boundary — stats/score materialize at sync
#: cadence, never per optimizer step; the traversal stops there
TRAIN_SYNC_BOUNDARY = {"encoder_stats", "_materialize",
                       "materialize_score"}

# -- step-timeline publish lint (straggler plane) --------------------------
#: the per-host step-timeline publish hooks (monitoring/stragglers.py,
#: fed from the coordination sync point) must be pure host
#: serialization: walking the publish path from each group's roots must
#: reach NO device materialization. Groups are linted SEPARATELY
#: because the walker's call graph is by bare function name and
#: `publish` exists in coordination.py (the KV write), cluster.py, and
#: stragglers.py — one union graph would shadow two of the three.
TIMELINE_MODULE_GROUPS = [
    # membership.py rides group 1: `pending()` (the join/leave fold)
    # runs inside EVERY heartbeat build — the walker descends from
    # _sync_point into it and proves the fold stays KV reads + JSON,
    # never a device touch
    ["deeplearning4j_tpu/parallel/coordination.py",
     "deeplearning4j_tpu/parallel/membership.py"],
    ["deeplearning4j_tpu/monitoring/stragglers.py",
     "deeplearning4j_tpu/monitoring/steps.py"],
    ["deeplearning4j_tpu/monitoring/cluster.py"],
]
#: publish-path entry points present in the groups: the sync-point
#: cadence hook (coordination), the digest publishers
#: (stragglers/cluster), and the ring digest they serialize (steps)
TIMELINE_SYNC_ROOTS = {"_sync_point", "publish", "compact_summary"}
#: forensics reports materialize freely — they run on the failure
#: path, never at the publish cadence
TIMELINE_SYNC_BOUNDARY = {"_write_report"}

#: coordination-module aliases whose `.publish(self, ...)` is a
#: METRICS-plane publish (cluster metrics / step timelines) — each such
#: call must sit inside the enabled-guard. The coordinator's own
#: `self.publish(...)` (heartbeats, guardian verdicts) is control
#: plane: it runs whether or not monitoring is on, and is exempt.
METRICS_PUBLISH_ALIASES = {"_cluster", "_stragglers"}
METRICS_PUBLISH_MODULES = ["deeplearning4j_tpu/parallel/coordination.py"]

#: attribute calls that hit the registry
REGISTRY_ATTRS = {"counter", "gauge", "histogram"}
#: bare/attribute function names that resolve the registry
REGISTRY_FUNCS = {"get_registry"}

#: substrings that mark an `if` test (or early-return guard test) as the
#: enabled-guard: `_mon.enabled()`, `STATE.enabled`, a cached
#: `mon_on = _mon.enabled()`, or an armed-session check
GUARD_TOKENS = ("enabled", "STATE.", "mon_on", "ACTIVE")


def _is_registry_call(node):
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in REGISTRY_ATTRS:
        return f".{f.attr}(...)"
    name = None
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name in REGISTRY_FUNCS:
        return f"{name}()"
    return None


def _test_is_guard(test):
    try:
        src = ast.unparse(test)
    except Exception:  # noqa: BLE001 — unparse of odd nodes
        return False
    return any(tok in src for tok in GUARD_TOKENS)


def _guarded(node, ancestors):
    """Inside an `if <enabled-ish>` block, or after an early-return
    `if not <enabled-ish>: return` in the enclosing function."""
    func = None
    for anc in reversed(ancestors):
        if isinstance(anc, ast.If) and _test_is_guard(anc.test):
            return True
        if func is None and isinstance(anc, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
            func = anc
    if func is not None:
        for stmt in ast.walk(func):
            if isinstance(stmt, ast.If) and _test_is_guard(stmt.test) \
                    and stmt.lineno < node.lineno \
                    and any(isinstance(s, (ast.Return, ast.Raise))
                            for s in stmt.body):
                return True
    return False


def check_source(source, path="<string>"):
    """[(path, lineno, description)] for unguarded registry calls."""
    tree = ast.parse(source, filename=path)
    violations = []

    def walk(node, ancestors):
        if isinstance(node, ast.Call):
            what = _is_registry_call(node)
            if what is not None and not _guarded(node, ancestors):
                violations.append(
                    (path, node.lineno,
                     f"{what} outside the enabled-guard fast path"))
        for child in ast.iter_child_nodes(node):
            walk(child, ancestors + [node])

    walk(tree, [])
    return violations


def check_file(path):
    with open(path) as f:
        return check_source(f.read(), path)


# -- serving steady-state lint (no trace/compile reachable from the
#    dispatch path) ---------------------------------------------------------
def _call_name(node):
    """Best-effort callee name of a Call: `f(...)` → f, `a.b.f(...)` →
    f. Good enough for an intra-repo method-name call graph."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _check_reachable(sources, roots, boundary, flag_names, describe):
    """Walk the union call graph (intra-repo, by function name) of
    every function/method defined in `sources`, starting from `roots`
    and NOT descending into `boundary`, and flag any call whose callee
    name is in `flag_names`. `describe(what, via)` renders the
    violation message. Matching is by bare callee name — a theoretical
    false positive (e.g. `"x".lower()`) is accepted over ever missing
    a real trace/sync on a hot path."""
    defs = {}        # name -> (path, FunctionDef)
    for path, source in sources.items():
        tree = ast.parse(source, filename=path)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, (path, node))
    violations = []
    seen = set()
    frontier = [r for r in roots if r in defs]
    while frontier:
        name = frontier.pop()
        if name in seen or name in boundary:
            continue
        seen.add(name)
        path, fn = defs[name]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node)
            if callee in flag_names:
                f = node.func
                what = (f".{callee}(...)" if isinstance(f, ast.Attribute)
                        else f"{callee}(...)")
                violations.append(
                    (path, node.lineno, describe(what, name)))
            if callee in defs and callee not in seen \
                    and callee not in boundary:
                frontier.append(callee)
    return violations


def check_serving_steady_state(sources):
    """sources: {path: source}. Steady-state serving (post-`warmup()`)
    must resolve every dispatch from the in-memory executable tier — a
    `jax.jit`/`lower`/`compile` reachable from the dispatch path means
    a novel shape could trace ON the request path."""
    return _check_reachable(
        sources, SERVING_ROOTS, SERVING_MISS_BOUNDARY, TRACE_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the serving dispatch path (via "
            f"{via}) — steady state must stay inside the AOT "
            "executable cache"))


def check_generation_steady_state(sources):
    """The generation decode loop (step / admit / retire) must reach no
    jit/lower/trace call past the declared warmup boundary: admitting a
    new sequence into an in-flight batch, stepping it, and retiring a
    finished slot are all pre-compiled fixed-shape dispatches."""
    return _check_reachable(
        sources, GENERATION_ROOTS, GENERATION_MISS_BOUNDARY,
        TRACE_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the generation decode loop (via "
            f"{via}) — step/admit/retire must stay inside the warmed "
            "executable set"))


def check_training_host_sync(sources):
    """Zero host syncs on the distributed train-step path: the
    accumulation scan dispatches once per optimizer step, the bucket
    planner reads only leaf SHAPES, and the bucketed exchange stays
    device-resident end to end — the stats/score fetch
    (encoder_stats / guardian _materialize) is the only declared
    per-optimizer-step host boundary."""
    return _check_reachable(
        sources, TRAIN_SYNC_ROOTS, TRAIN_SYNC_BOUNDARY,
        SYNC_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the distributed train step (via "
            f"{via}) — the accumulation scan / bucketed exchange must "
            "not sync the host; encoder_stats is the declared "
            "boundary"))


def check_generation_host_sync(sources):
    """Zero per-token host syncs beyond the sampled-token fetch: the
    decode step's only device materialization is the declared
    `_fetch_tokens` boundary — everything else (caches, carries,
    positions, rng) stays device-resident and donated."""
    return _check_reachable(
        sources, GENERATION_SYNC_ROOTS, GENERATION_SYNC_BOUNDARY,
        SYNC_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the decode step (via {via}) — the "
            "sampled-token fetch (_fetch_tokens) is the only allowed "
            "per-token host sync"))


def check_fleet_trace_free(sources):
    """Zero traces/compiles on the fleet routing walk: routing reads
    health snapshots and hands a pre-built request to `adopt()` — a
    compile reachable from route/dispatch/relay/failover would hide an
    unbounded stall inside what must be a bounded re-route."""
    return _check_reachable(
        sources, FLEET_ROOTS, FLEET_BOUNDARY, TRACE_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the fleet routing walk (via {via})"
            " — replica replacement (_supervise) is the only place a "
            "warmup may happen, and it warms from the shared disk "
            "store"))


def check_fleet_host_sync(sources):
    """Zero device syncs on the fleet routing walk: the router is pure
    host plumbing between the client and the replica decode loops —
    token relaying moves already-fetched ints, never device values."""
    return _check_reachable(
        sources, FLEET_ROOTS, FLEET_BOUNDARY, SYNC_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the fleet routing walk (via {via})"
            " — the router must never touch device data; the replica's"
            " _fetch_tokens boundary already did"))


def check_timeline_host_sync(sources):
    """Zero host syncs on the step-timeline publish path: publishing a
    per-host digest is JSON over numbers the flight recorder already
    holds — a device materialization reachable from `publish` /
    `compact_summary` / `_sync_point` would turn the metrics plane
    into a hidden per-sync host sync."""
    return _check_reachable(
        sources, TIMELINE_SYNC_ROOTS, TIMELINE_SYNC_BOUNDARY,
        SYNC_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the step-timeline publish path "
            f"(via {via}) — publishing must stay pure host "
            "serialization, never a device touch"))


def check_metrics_publish_guarded(source, path="<string>"):
    """Every metrics-plane publish in the coordination module
    (`_cluster.publish(...)` / `_stragglers.publish(...)`) must sit
    inside the enabled-guard: with monitoring off the sync point pays
    one branch, not a KV write per sync."""
    tree = ast.parse(source, filename=path)
    violations = []

    def walk(node, ancestors):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "publish" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in METRICS_PUBLISH_ALIASES \
                    and not _guarded(node, ancestors):
                violations.append(
                    (path, node.lineno,
                     f"{f.value.id}.publish(...) outside the "
                     "enabled-guard — the metrics/timeline planes must "
                     "cost one branch when monitoring is off"))
        for child in ast.iter_child_nodes(node):
            walk(child, ancestors + [node])

    walk(tree, [])
    return violations


# -- ops-event emission lint ------------------------------------------------
#: modules holding ops-event emission hooks (monitoring/events.py
#: `_events.emit(...)` call sites): every emit must sit inside the
#: enabled-guard — with monitoring off an event hook costs ONE branch,
#: never a lock + ring append. events.py itself stays out of
#: HOT_MODULES on purpose: it IS the guarded side, and its bundle()
#: crash path reads the registry unconditionally by design.
EVENT_HOOK_MODULES = [
    "deeplearning4j_tpu/resilience/guardian.py",
    "deeplearning4j_tpu/resilience/watchdog.py",
    "deeplearning4j_tpu/resilience/faults.py",
    "deeplearning4j_tpu/generation/server.py",
    "deeplearning4j_tpu/generation/fleet.py",
    "deeplearning4j_tpu/parallel/coordination.py",
    "deeplearning4j_tpu/parallel/membership.py",
    "deeplearning4j_tpu/parallel/multihost.py",
    "deeplearning4j_tpu/monitoring/slo.py",
]
#: the canonical import alias at every hook site
EVENT_EMIT_ALIASES = {"_events"}

#: the journal's own emit path (everything an `emit()` call can reach)
#: must stay pure host bookkeeping: no device touch, no trace. The
#: post-mortem side (`bundle`/`write_bundle`) is the declared boundary
#: — it runs on the failure path, never at emission cadence.
EVENT_JOURNAL_MODULES = ["deeplearning4j_tpu/monitoring/events.py"]
EVENT_EMIT_ROOTS = {"emit", "journal", "_correlate", "_sweep_quiet",
                    "_close", "_publish_locked", "snapshot",
                    "incidents", "absorb", "close"}
EVENT_EMIT_BOUNDARY = {"bundle", "write_bundle"}


def check_event_emit_guarded(source, path="<string>"):
    """Every ops-event emission hook (`_events.emit(...)`) must sit
    inside the enabled-guard: the event journal is monitoring-plane
    state, and a disabled run pays one branch per hook site, not a
    journal append per incident-adjacent code path."""
    tree = ast.parse(source, filename=path)
    violations = []

    def walk(node, ancestors):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "emit" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in EVENT_EMIT_ALIASES \
                    and not _guarded(node, ancestors):
                violations.append(
                    (path, node.lineno,
                     f"{f.value.id}.emit(...) outside the "
                     "enabled-guard — ops-event hooks must cost one "
                     "branch when monitoring is off"))
        for child in ast.iter_child_nodes(node):
            walk(child, ancestors + [node])

    walk(tree, [])
    return violations


def check_event_emit_host_pure(sources):
    """The journal emit path (emit → correlate → sweep → publish) rides
    failure-adjacent hot paths (decode loop, sync point, train step) —
    walking it must reach NO device materialization and NO trace; the
    post-mortem bundle writer is the declared cold boundary."""
    return _check_reachable(
        sources, EVENT_EMIT_ROOTS, EVENT_EMIT_BOUNDARY,
        SYNC_CALL_NAMES | TRACE_CALL_NAMES,
        lambda what, via: (
            f"{what} reachable from the event-journal emit path (via "
            f"{via}) — emission must stay pure host bookkeeping; only "
            "bundle()/write_bundle() may do heavyweight work"))


def main(modules=None):
    violations = []
    for rel in modules or HOT_MODULES:
        path = os.path.join(REPO_ROOT, rel)
        if not os.path.exists(path):
            continue
        violations.extend(check_file(path))
    if modules is None:
        sources = {}
        for rel in SERVING_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    sources[path] = f.read()
        violations.extend(check_serving_steady_state(sources))
        gen_sources = {}
        for rel in GENERATION_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    gen_sources[path] = f.read()
        violations.extend(check_generation_steady_state(gen_sources))
        violations.extend(check_generation_host_sync(gen_sources))
        fleet_sources = {}
        for rel in FLEET_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    fleet_sources[path] = f.read()
        violations.extend(check_fleet_trace_free(fleet_sources))
        violations.extend(check_fleet_host_sync(fleet_sources))
        train_sources = {}
        for rel in TRAIN_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    train_sources[path] = f.read()
        violations.extend(check_training_host_sync(train_sources))
        for group in TIMELINE_MODULE_GROUPS:
            tl_sources = {}
            for rel in group:
                path = os.path.join(REPO_ROOT, rel)
                if os.path.exists(path):
                    with open(path) as f:
                        tl_sources[path] = f.read()
            violations.extend(check_timeline_host_sync(tl_sources))
        for rel in METRICS_PUBLISH_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    violations.extend(
                        check_metrics_publish_guarded(f.read(), path))
        for rel in EVENT_HOOK_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    violations.extend(
                        check_event_emit_guarded(f.read(), path))
        ev_sources = {}
        for rel in EVENT_JOURNAL_MODULES:
            path = os.path.join(REPO_ROOT, rel)
            if os.path.exists(path):
                with open(path) as f:
                    ev_sources[path] = f.read()
        violations.extend(check_event_emit_host_pure(ev_sources))
    for path, lineno, msg in violations:
        print(f"{os.path.relpath(path, REPO_ROOT)}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} fast-path violation(s): wrap "
              "registry calls in `if _mon.enabled():` (or an early "
              "`if not STATE.enabled: return`) so the disabled path "
              "stays one branch, and keep traces/compiles behind the "
              "executable-store miss boundary (load_or_compile).")
    return violations


if __name__ == "__main__":
    sys.exit(1 if main(sys.argv[1:] or None) else 0)
