#!/usr/bin/env python
"""Bench-regression gate: a fresh `BENCH_*.json` must not fall more
than `--tolerance` below the best prior entry in the checked-in bench
trajectory.

The repo accumulates one headline artifact per bench round
(`BENCH_r<NN>.json` at the repo root), in two shapes:

- the wrapped driver format: `{"n", "cmd", "rc", "tail",
  "parsed": {"value", "error", "metric", "unit"} | null}` — `parsed`
  is null (or `rc` nonzero) when the round never produced a number;
- the flat local format: the parsed payload at top level
  (`{"value", "metric", "unit", ...extra section keys}`), values
  sometimes serialized as strings.

The headline is `parsed["value"]` (higher is better). Nothing has ever
compared one round against the previous — a silent throughput
regression would land unnoticed. This script is that comparison, and
`tests/test_bench_regression.py` pins its verdicts over the existing
artifacts in tier-1.

Exemption: the axon tunnel wedge (BENCH.md "Environment hazard"). A
round whose every attempt timed out before the device banner printed
(`value == 0.0`, "timeout" in the error trail, no "# device:" line in
the tail) measured the ENVIRONMENT, not the code — it is skipped as a
prior and tolerated as a fresh result (reported, exit 0): failing the
gate on an outage would teach people to ignore it.

Usage:
    python scripts/check_bench_regression.py BENCH_fresh.json
    python scripts/check_bench_regression.py --tolerance 0.05 fresh.json
Exit 0 = within tolerance (or no usable prior / fresh outage),
exit 1 = regression.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: tolerated fractional drop below the best prior headline (0.10 =
#: fresh may be up to 10% slower); override with --tolerance or
#: DL4J_BENCH_TOLERANCE
DEFAULT_TOLERANCE = 0.10


def load_artifact(path):
    with open(path) as f:
        return json.load(f)


def parsed_of(doc):
    """The parsed payload of either artifact shape, or None when the
    round produced no result (wrapped with `parsed: null` or a nonzero
    driver rc)."""
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc or "rc" in doc:
        if doc.get("rc") not in (0, None):
            return None
        p = doc.get("parsed")
        return p if isinstance(p, dict) else None
    return doc if "value" in doc else None


def headline_value(doc):
    """float headline (img/s — higher is better), or None. Flat local
    artifacts serialize numbers as strings, hence the float()."""
    p = parsed_of(doc)
    if p is None or p.get("value") is None:
        return None
    try:
        return float(p["value"])
    except (TypeError, ValueError):
        return None


def is_outage(doc):
    """The axon-tunnel-outage signature (BENCH.md): zero headline,
    every attempt a timeout, and the device banner never printed —
    the run never reached the accelerator."""
    p = parsed_of(doc)
    if p is None:
        return False
    v = headline_value(doc)
    if v is None or v != 0.0:
        return False
    blob = str(p.get("error") or "") + str(doc.get("tail") or "")
    return "timeout" in blob and "# device:" not in str(doc.get("tail")
                                                       or "")


def trajectory_paths(root=REPO_ROOT):
    return sorted(glob.glob(os.path.join(root, "BENCH_*.json")))


def best_prior(paths=None, exclude=()):
    """(value, path) of the best usable prior headline — outage rounds
    and no-result rounds are not priors. (None, None) when the
    trajectory holds nothing usable."""
    exclude = {os.path.abspath(p) for p in exclude}
    best_v, best_p = None, None
    for path in paths if paths is not None else trajectory_paths():
        if os.path.abspath(path) in exclude:
            continue
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        if is_outage(doc):
            continue
        v = headline_value(doc)
        if v is None or v <= 0.0:
            continue
        if best_v is None or v > best_v:
            best_v, best_p = v, path
    return best_v, best_p


def check(fresh_path, tolerance=DEFAULT_TOLERANCE, paths=None):
    """Verdict dict: {"ok", "reason", "fresh", "prior", "prior_path",
    "floor"}. ok=False only for a genuine regression — a fresh outage
    or an empty trajectory passes with the reason named."""
    doc = load_artifact(fresh_path)
    prior, prior_path = best_prior(paths=paths, exclude=(fresh_path,))
    out = {"ok": True, "fresh": headline_value(doc), "prior": prior,
           "prior_path": prior_path, "floor": None, "reason": None}
    if is_outage(doc):
        out["reason"] = ("fresh round matches the axon-tunnel-outage "
                         "signature — environment, not code; exempt")
        return out
    if out["fresh"] is None:
        out["ok"] = False
        out["reason"] = "fresh artifact holds no headline value"
        return out
    if prior is None:
        out["reason"] = "no usable prior in the bench trajectory"
        return out
    floor = prior * (1.0 - float(tolerance))
    out["floor"] = floor
    if out["fresh"] < floor:
        out["ok"] = False
        out["reason"] = (f"regression: {out['fresh']:.2f} < floor "
                         f"{floor:.2f} ({tolerance:.0%} below best "
                         f"prior {prior:.2f} from "
                         f"{os.path.basename(prior_path)})")
    else:
        out["reason"] = (f"{out['fresh']:.2f} within {tolerance:.0%} of "
                         f"best prior {prior:.2f} "
                         f"({os.path.basename(prior_path)})")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", help="fresh BENCH_*.json to gate")
    ap.add_argument("--tolerance", type=float,
                    default=float(os.environ.get("DL4J_BENCH_TOLERANCE",
                                                 DEFAULT_TOLERANCE)),
                    help="tolerated fractional drop below the best "
                         "prior (default %(default)s)")
    args = ap.parse_args(argv)
    verdict = check(args.fresh, tolerance=args.tolerance)
    print(json.dumps(verdict, indent=2))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
