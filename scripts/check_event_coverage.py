#!/usr/bin/env python
"""Event-kind coverage lint: every ops-event kind declared in
`monitoring/events.py` must be exercised by at least one test.

The event journal is an incident-forensics surface — an event kind no
test ever emits is a timeline entry nobody has ever seen rendered, and
its correlation behavior (does it open an incident? absorb? resolve?)
is unverified. This script parses events.py for the declared kind
constants (module-level ``UPPER_NAME = "dotted.kind"`` string
assignments) and greps the test tree for either the constant name
(``SERVER_DISRUPTED``) or the literal kind string
(``"server.disrupted"``). A kind referenced by neither fails the lint,
so a new event kind cannot ship untested.

Grep-based on purpose, exactly like `check_fault_coverage.py`: it runs
in tier-1 (tests/test_event_coverage.py) with zero imports of jax or
the package, and a textual reference is the right bar — the
referencing test, not this lint, is responsible for emitting the kind
through a production hook or asserting its correlation semantics.

Run manually:  python scripts/check_event_coverage.py
(prints uncovered kinds, exit 1 when any).
"""
from __future__ import annotations

import ast
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVENTS_MODULE = os.path.join(REPO_ROOT, "deeplearning4j_tpu",
                             "monitoring", "events.py")
TESTS_DIR = os.path.join(REPO_ROOT, "tests")

#: what a kind value looks like: lowercase dotted words
#: ("server.disrupted"). Filters out the other module-level string
#: constants (metric names carry the "dl4j." prefix but those live in
#: registry.py, not here; defaults and section tuples never match).
_KIND_RE = re.compile(r"[a-z_]+(\.[a-z_]+)+")


def declared_kinds(source=None):
    """{CONSTANT_NAME: "kind.string"} for every module-level kind
    declaration in events.py (or the given source override)."""
    if source is None:
        with open(EVENTS_MODULE) as f:
            source = f.read()
    kinds = {}
    for node in ast.parse(source).body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        value = node.value
        if (name.isupper() and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
                and _KIND_RE.fullmatch(value.value)):
            kinds[name] = value.value
    return kinds


def test_sources(tests_dir=None):
    """{path: source} for every python file under tests/."""
    tests_dir = tests_dir or TESTS_DIR
    out = {}
    for base, _, names in os.walk(tests_dir):
        for n in sorted(names):
            if n.endswith(".py"):
                path = os.path.join(base, n)
                with open(path) as f:
                    out[path] = f.read()
    return out


def uncovered_kinds(kinds=None, sources=None):
    """[(constant, kind)] declared kinds no test references by
    constant name (word-bounded) or literal string."""
    kinds = declared_kinds() if kinds is None else kinds
    sources = test_sources() if sources is None else sources
    blob = "\n".join(sources.values())
    missing = []
    for name, kind in sorted(kinds.items()):
        if re.search(rf"\b{re.escape(name)}\b", blob):
            continue
        if kind in blob:
            continue
        missing.append((name, kind))
    return missing


def main():
    missing = uncovered_kinds()
    for name, kind in missing:
        print(f"{name} ({kind!r}): no test references this ops-event "
              "kind")
    if missing:
        print(f"\n{len(missing)} uncovered event kind(s): every "
              "events.py kind must be exercised by at least one test "
              "(reference the constant or the kind string and drive "
              "the emission hook or its correlation semantics).")
    return missing


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
