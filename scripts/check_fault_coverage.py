#!/usr/bin/env python
"""Fault-site coverage lint: every injection site declared in
`resilience/faults.py` must be exercised by at least one test.

The fault harness only earns its keep if every site a production path
can fire is actually driven by a chaos/regression test — an uncovered
site is a failure mode nobody has ever watched happen. This script
parses faults.py for the declared site constants (module-level
``UPPER_NAME = "dotted.site"`` string assignments) and greps the test
tree for either the constant name (``GENERATION_STEP``) or the literal
site string (``"generation.step"``). A site referenced by neither
fails the lint, so a new fault site cannot ship untested.

Grep-based on purpose, exactly like `check_fastpath.py`: it runs in
tier-1 (tests/test_fault_coverage.py) with zero imports of jax or the
package, and a textual reference is the right bar — the referencing
test, not this lint, is responsible for driving the site meaningfully.

Run manually:  python scripts/check_fault_coverage.py
(prints uncovered sites, exit 1 when any).
"""
from __future__ import annotations

import ast
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAULTS_MODULE = os.path.join(REPO_ROOT, "deeplearning4j_tpu",
                             "resilience", "faults.py")
TESTS_DIR = os.path.join(REPO_ROOT, "tests")

#: what a site value looks like: lowercase dotted words ("cache.grow").
#: Filters out non-site module constants (ACTIVE/PROCESS_ID are None
#: assignments and never match the string form anyway).
_SITE_RE = re.compile(r"[a-z_]+(\.[a-z_]+)+")


def declared_sites(source=None):
    """{CONSTANT_NAME: "site.string"} for every module-level site
    declaration in faults.py (or the given source override)."""
    if source is None:
        with open(FAULTS_MODULE) as f:
            source = f.read()
    sites = {}
    for node in ast.parse(source).body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        value = node.value
        if (name.isupper() and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
                and _SITE_RE.fullmatch(value.value)):
            sites[name] = value.value
    return sites


def test_sources(tests_dir=None):
    """{path: source} for every python file under tests/."""
    tests_dir = tests_dir or TESTS_DIR
    out = {}
    for base, _, names in os.walk(tests_dir):
        for n in sorted(names):
            if n.endswith(".py"):
                path = os.path.join(base, n)
                with open(path) as f:
                    out[path] = f.read()
    return out


def uncovered_sites(sites=None, sources=None):
    """[(constant, site)] declared sites no test references by
    constant name (word-bounded) or literal string."""
    sites = declared_sites() if sites is None else sites
    sources = test_sources() if sources is None else sources
    blob = "\n".join(sources.values())
    missing = []
    for name, site in sorted(sites.items()):
        if re.search(rf"\b{re.escape(name)}\b", blob):
            continue
        if site in blob:
            continue
        missing.append((name, site))
    return missing


def main():
    missing = uncovered_sites()
    for name, site in missing:
        print(f"{name} ({site!r}): no test references this fault "
              "injection site")
    if missing:
        print(f"\n{len(missing)} uncovered fault site(s): every "
              "faults.py injection site must be exercised by at least "
              "one test (reference the constant or the site string "
              "and drive the production hook).")
    return missing


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
