"""Character-level text generation with stacked LSTMs (≡ dl4j-examples ::
GravesLSTMCharModellingExample): overfit a tiny corpus, then sample."""
import numpy as np

from deeplearning4j_tpu.nn import (Adam, MultiLayerNetwork,
                                   NeuralNetConfiguration, InputType)
from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer

CORPUS = ("the quick brown fox jumps over the lazy dog. "
          "pack my box with five dozen liquor jugs. ") * 20


def main():
    chars = sorted(set(CORPUS))
    c2i = {c: i for i, c in enumerate(chars)}
    n = len(chars)
    seq_len = 32

    conf = (NeuralNetConfiguration.Builder()
            .seed(12).updater(Adam(1e-2)).weightInit("xavier")
            .list()
            .layer(LSTM(nOut=96, activation="tanh"))
            .layer(RnnOutputLayer(lossFunction="mcxent", nOut=n,
                                  activation="softmax"))
            .setInputType(InputType.recurrent(n))
            .build())
    net = MultiLayerNetwork(conf).init()

    # build (B, T, C) one-hot batches
    ids = np.asarray([c2i[c] for c in CORPUS])
    starts = np.arange(0, len(ids) - seq_len - 1, seq_len)
    x = np.eye(n, dtype=np.float32)[
        np.stack([ids[s:s + seq_len] for s in starts])]
    y = np.eye(n, dtype=np.float32)[
        np.stack([ids[s + 1:s + seq_len + 1] for s in starts])]

    for epoch in range(60):
        net.fit(x, y)
    print("final loss:", net.score())

    # sample greedily from a seed character
    rng = np.random.default_rng(0)
    out = "t"
    net.rnnClearPreviousState()
    for _ in range(80):
        step = np.eye(n, dtype=np.float32)[[c2i[out[-1]]]][None]
        probs = np.asarray(net.rnnTimeStep(step))[0, 0]
        out += chars[int(rng.choice(n, p=probs / probs.sum()))]
    print("sampled:", out)


if __name__ == "__main__":
    main()
