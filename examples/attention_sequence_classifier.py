"""Sequence classification with the first-class attention layers (round-3:
≡ dl4j-examples attention usage of SelfAttentionLayer /
LearnedSelfAttentionLayer). A LearnedSelfAttentionLayer pools ragged
sequences into a fixed-length representation; padding masks flow through
the whole stack (and into the Pallas flash-attention kernel on TPU)."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn import (Adam, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.attention import (LearnedSelfAttentionLayer,
                                                  SelfAttentionLayer)
from deeplearning4j_tpu.nn.conf.layers import OutputLayer
from deeplearning4j_tpu.nn.conf.recurrent import LastTimeStep, LSTM

T, F = 24, 8


def make_data(n=128, seed=0):
    """Task: does the (variable-length) sequence contain a spike > 2 ?"""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, T, F)).astype(np.float32) * 0.5
    lengths = rng.integers(8, T + 1, n)
    mask = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float32)
    labels = rng.integers(0, 2, n)
    for i in np.where(labels == 1)[0]:
        t = rng.integers(0, lengths[i])
        x[i, t] += 3.0
    x *= mask[:, :, None]
    y = np.eye(2, dtype=np.float32)[labels]
    ds = DataSet(x, y)
    ds.featuresMask = mask
    return ds


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(3e-3)).weightInit("xavier")
            .list()
            .layer(SelfAttentionLayer(nOut=32, nHeads=4))
            .layer(LearnedSelfAttentionLayer(nOut=32, nHeads=4, nQueries=4))
            .layer(LastTimeStep(LSTM(nOut=16)))
            .layer(OutputLayer(lossFunction="mcxent", nOut=2,
                               activation="softmax"))
            .setInputType(InputType.recurrent(F, T))
            .build())
    net = MultiLayerNetwork(conf).init()
    train, test = make_data(256, 0), make_data(64, 1)
    for epoch in range(30):
        net.fit(train)
    preds = net.output(test.features, fmask=test.featuresMask).numpy()
    acc = (preds.argmax(-1) == test.labels.argmax(-1)).mean()
    print(f"test accuracy: {acc:.3f}")
    assert acc > 0.8, "attention stack failed to learn the spike task"


if __name__ == "__main__":
    main()
