"""Transfer learning (≡ dl4j-examples :: EditLastLayerOthersFrozen):
freeze a trained feature extractor, swap the output head, fine-tune."""
import numpy as np

from deeplearning4j_tpu.nn import (Adam, DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.transfer.transfer_learning import (
    FineTuneConfiguration, TransferLearning)


def main():
    base = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2))
        .weightInit("xavier").list()
        .layer(DenseLayer(nOut=64, activation="relu"))
        .layer(DenseLayer(nOut=32, activation="relu"))
        .layer(OutputLayer(lossFunction="mcxent", nOut=5,
                           activation="softmax"))
        .setInputType(InputType.feedForward(20)).build()).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 20)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(5, size=64)]
    for _ in range(10):
        base.fit(x, y)
    print("base loss:", base.score())

    # new 3-class task: freeze everything up to layer 1, replace the head
    transferred = (TransferLearning.Builder(base)
                   .fineTuneConfiguration(
                       FineTuneConfiguration.Builder()
                       .updater(Adam(1e-3)).build())
                   .setFeatureExtractor(1)
                   .removeOutputLayer()
                   .addLayer(OutputLayer(lossFunction="mcxent", nOut=3,
                                         activation="softmax"))
                   .build())
    y3 = np.eye(3, dtype=np.float32)[rng.integers(3, size=64)]
    for _ in range(10):
        transferred.fit(x, y3)
    print("fine-tuned loss:", transferred.score())


if __name__ == "__main__":
    main()
