"""Multi-chip data parallelism (≡ dl4j-examples :: MultiGpuLenetMnist via
ParallelWrapper). Run on a TPU pod slice, or simulate with
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
"""
import jax

from deeplearning4j_tpu.datasets.iterators import MnistDataSetIterator
from deeplearning4j_tpu.nn import (Adam, ConvolutionLayer, DenseLayer,
                                   InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SubsamplingLayer)
from deeplearning4j_tpu.parallel import ParallelWrapper


def main():
    print("devices:", jax.devices())
    conf = (NeuralNetConfiguration.Builder()
            .seed(123).updater(Adam(1e-3)).weightInit("xavier")
            .list()
            .layer(ConvolutionLayer(kernelSize=(5, 5), nOut=16,
                                    activation="relu",
                                    convolutionMode="same"))
            .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(nOut=128, activation="relu"))
            .layer(OutputLayer(lossFunction="mcxent", nOut=10,
                               activation="softmax"))
            .setInputType(InputType.convolutionalFlat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()

    # ≡ ParallelWrapper.Builder(model).workers(N)...build()
    wrapper = (ParallelWrapper.Builder(net)
               .workers(len(jax.devices()))
               .prefetchBuffer(2)
               .averagingFrequency(1)
               .build())
    wrapper.fit(MnistDataSetIterator(64 * len(jax.devices())))
    ev = net.evaluate(MnistDataSetIterator(256, train=False))
    print("accuracy:", ev.accuracy())


if __name__ == "__main__":
    main()
