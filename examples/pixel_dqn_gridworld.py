"""Pixel-input DQN (≡ rl4j-examples :: ALE/A3C ALE pixel agents, scaled
to a zero-egress synthetic env): HistoryProcessor frame pipeline + conv
Q-network + frame skip on a rendered grid world."""
from deeplearning4j_tpu.rl import (DQNConvNetworkConfiguration,
                                   HistoryProcessorConfiguration,
                                   PixelGridWorld, QLearningConfiguration,
                                   QLearningDiscreteConv)


def main():
    mdp = PixelGridWorld(size=6, scale=2, maxSteps=30)
    learner = QLearningDiscreteConv(
        mdp,
        DQNConvNetworkConfiguration(learningRate=1e-3, filters=(8,),
                                    kernels=((3, 3),), strides=((2, 2),),
                                    denseUnits=32),
        HistoryProcessorConfiguration(historyLength=2, rescaledWidth=12,
                                      rescaledHeight=12, skipFrame=1),
        QLearningConfiguration(seed=1, maxEpochStep=30, maxStep=600,
                               expRepMaxSize=5000, batchSize=16,
                               targetDqnUpdateFreq=50, updateStart=20,
                               gamma=0.95, minEpsilon=0.05,
                               epsilonNbStep=300))
    rewards = learner.train()
    print(f"episodes: {len(rewards)}; "
          f"last-5 rewards: {[round(r, 2) for r in rewards[-5:]]}")
    play = learner.getPolicy().play(PixelGridWorld(size=6, scale=2,
                                                   maxSteps=30))
    print(f"greedy play reward: {play:.2f} (optimal 0.96)")
    assert play > 0.9


if __name__ == "__main__":
    main()
