"""Object detection end to end (≡ dl4j-examples :: TinyYoloHouseNumber
style): train a YOLOv2 head on a synthetic scene, then extract final
detections with confidence threshold + per-class NMS
(YoloUtils.getPredictedObjects)."""
import numpy as np

from deeplearning4j_tpu.nn import (Adam, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer

GRID, N_CLS = 8, 3
ANCHORS = [[1.0, 1.0], [3.0, 3.0]]


def scene():
    """One image: a bright square; gt box centered on it, class 1."""
    x = np.zeros((1, GRID, GRID, 3), np.float32)
    x[0, 2:5, 3:6, :] = 1.0
    lab = np.zeros((1, GRID, GRID, 4 + N_CLS), np.float32)
    lab[0, 3, 4, :4] = [4.5, 3.5, 2.0, 2.0]    # (x, y, w, h) grid units
    lab[0, 3, 4, 4 + 1] = 1.0
    return x, lab


def main():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(0).updater(Adam(5e-3))
        .weightInit("relu").list()
        .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=32,
                                convolutionMode="same", activation="relu"))
        .layer(ConvolutionLayer(kernelSize=(1, 1),
                                nOut=len(ANCHORS) * (5 + N_CLS),
                                convolutionMode="same",
                                activation="identity"))
        .layer(Yolo2OutputLayer(boundingBoxes=ANCHORS))
        .setInputType(InputType.convolutional(GRID, GRID, 3))
        .build()).init()
    x, lab = scene()
    for i in range(150):
        net.fit(x, lab)
        if i % 50 == 49:
            print(f"iter {i + 1}: loss {float(net.score()):.4f}")
    dets = net.getPredictedObjects(x, confThreshold=0.3, nmsThreshold=0.4)
    print(f"{len(dets[0])} detection(s):")
    for d in dets[0]:
        tl, br = d.getTopLeftXY(), d.getBottomRightXY()
        print(f"  class={d.getPredictedClass()} conf={d.confidence:.2f} "
              f"box=({tl[0]:.1f},{tl[1]:.1f})-({br[0]:.1f},{br[1]:.1f})")
    assert dets[0] and dets[0][0].getPredictedClass() == 1


if __name__ == "__main__":
    main()
