"""Hyperparameter search (≡ arbiter examples): tune lr + width for a
tiny classifier with TPE."""
import numpy as np

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        IntegerParameterSpace,
                                        LocalOptimizationRunner,
                                        TPEGenerator)
from deeplearning4j_tpu.nn import (Adam, DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)

rng = np.random.default_rng(0)
X = rng.normal(size=(128, 10)).astype(np.float32)
W = rng.normal(size=(10, 3)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[(X @ W).argmax(-1)]


def build_and_score(params):
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(1)
        .updater(Adam(params["lr"])).weightInit("xavier").list()
        .layer(DenseLayer(nOut=params["width"], activation="relu"))
        .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                           activation="softmax"))
        .setInputType(InputType.feedForward(10)).build()).init()
    for _ in range(30):
        net.fit(X, Y)
    return net.score()


def main():
    space = {"lr": ContinuousParameterSpace(1e-4, 1e-1, log=True),
             "width": IntegerParameterSpace(4, 64)}
    runner = LocalOptimizationRunner(
        TPEGenerator(space, seed=5, startupTrials=6),
        model_builder=lambda p: p, scorer=build_and_score,
        maxCandidates=15)
    best = runner.execute()
    print("best:", best.params, "loss:", round(best.score, 4))


if __name__ == "__main__":
    main()
