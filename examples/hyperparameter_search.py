"""Hyperparameter search (≡ arbiter examples): tune lr + width for a
tiny classifier with TPE."""
import numpy as np

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        IntegerParameterSpace,
                                        LocalOptimizationRunner,
                                        TPEGenerator)
from deeplearning4j_tpu.nn import (Adam, DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)

rng = np.random.default_rng(0)
X = rng.normal(size=(128, 10)).astype(np.float32)
W = rng.normal(size=(10, 3)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[(X @ W).argmax(-1)]


def train_and_score(net, epochs=30):
    for _ in range(epochs):
        net.fit(X, Y)
    return net.score()


def build_and_score(params):
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(1)
        .updater(Adam(params["lr"])).weightInit("xavier").list()
        .layer(DenseLayer(nOut=params["width"], activation="relu"))
        .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                           activation="softmax"))
        .setInputType(InputType.feedForward(10)).build()).init()
    return train_and_score(net)


def main():
    space = {"lr": ContinuousParameterSpace(1e-4, 1e-1, log=True),
             "width": IntegerParameterSpace(4, 64)}
    runner = LocalOptimizationRunner(
        TPEGenerator(space, seed=5, startupTrials=6),
        model_builder=lambda p: p, scorer=build_and_score,
        maxCandidates=15)
    best = runner.execute()
    print("best:", best.params, "loss:", round(best.score, 4))


def main_declarative():
    """Same search through the declarative network-space DSL (≡
    arbiter-deeplearning4j :: MultiLayerSpace) — no hand-written
    model_builder: the space compiles sampled candidates into real
    configurations itself."""
    from deeplearning4j_tpu.arbiter import (AdamSpace, LayerSpace,
                                            MultiLayerSpace,
                                            RandomSearchGenerator)

    mls = (MultiLayerSpace.Builder()
           .seed(1)
           .updater(AdamSpace(ContinuousParameterSpace(1e-4, 1e-1,
                                                       log=True)))
           .weightInit("xavier")
           .addLayer(LayerSpace(DenseLayer,
                                nOut=IntegerParameterSpace(4, 64),
                                activation="relu"))
           .addLayer(LayerSpace(OutputLayer, lossFunction="mcxent",
                                nOut=3, activation="softmax"))
           .setInputType(InputType.feedForward(10))
           .build())

    runner = LocalOptimizationRunner(
        RandomSearchGenerator(mls.collectLeaves(), seed=5),
        model_builder=mls, scorer=train_and_score, maxCandidates=8)
    best = runner.execute()
    print("declarative best:", best.params, "loss:", round(best.score, 4))


if __name__ == "__main__":
    main()
    main_declarative()
