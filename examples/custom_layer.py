"""Define a CUSTOM layer outside the framework (round-3: ≡ dl4j-examples ::
CustomLayerExample on conf.layers.samediff.SameDiffLayer): declare param
shapes, write the forward as plain jax.numpy, train + serialize like any
built-in layer."""
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import (Adam, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import OutputLayer
from deeplearning4j_tpu.nn.conf.samediff_layers import SameDiffLayer


class MaxoutDense(SameDiffLayer):
    """Maxout unit: y_j = max_k (x·W_k)_j — not in the built-in catalog."""

    def __init__(self, nOut=None, pieces=3, **kw):
        super().__init__(**kw)
        self.nOut = nOut
        self.pieces = int(pieces)

    def defineParameters(self):
        return {"W": (self.pieces, self.nIn, self.nOut),
                "b": (self.pieces, self.nOut)}

    def defineLayer(self, params, x, mask=None):
        z = jnp.einsum("bi,pio->bpo", x, params["W"]) + params["b"]
        return jnp.max(z, axis=1)


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(3).updater(Adam(1e-2)).weightInit("xavier")
            .list()
            .layer(MaxoutDense(nOut=16, pieces=3))
            .layer(OutputLayer(lossFunction="mcxent", nOut=3,
                               activation="softmax"))
            .setInputType(InputType.feedForward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((96, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(np.abs(x).argmax(-1) % 3)]
    for _ in range(60):
        net.fit(x, y)
    acc = (net.output(x).numpy().argmax(-1) == y.argmax(-1)).mean()
    print(f"train accuracy: {acc:.3f}")
    net.save("/tmp/maxout_net.zip")
    restored = MultiLayerNetwork.load("/tmp/maxout_net.zip")
    assert isinstance(restored.layers[0], MaxoutDense)
    assert np.allclose(restored.output(x).numpy(), net.output(x).numpy())
    print("custom layer round-tripped through ModelSerializer")


if __name__ == "__main__":
    main()
