"""Word2Vec on a text corpus (≡ dl4j-examples :: Word2VecRawTextExample)."""
from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory, Word2Vec)

import numpy as np

# two topics whose words co-occur within-topic but never across — skip-gram
# places words with similar CONTEXTS near each other
_TIME = ["day", "night", "morning", "evening", "noon", "dusk"]
_SKY = ["sun", "moon", "stars", "clouds", "comet", "nebula"]
_rng = np.random.default_rng(7)
SENTENCES = ["{} {} {} {} {} {}".format(
    *_rng.choice(fam, 6)) for _ in range(300)
    for fam in (_TIME if _rng.random() < 0.5 else _SKY,)]


def main():
    tok = DefaultTokenizerFactory()
    tok.setTokenPreProcessor(CommonPreprocessor())
    vec = (Word2Vec.Builder()
           .minWordFrequency(2)
           .layerSize(32)
           .seed(42)
           .windowSize(3)
           .learningRate(0.05)
           .epochs(20)
           .sampling(0)  # tiny corpus: keep every token
           .iterate(CollectionSentenceIterator(SENTENCES))
           .tokenizerFactory(tok)
           .build()
           .fit())
    print("vocab:", vec.vocabSize())
    print("closest to 'day':", vec.wordsNearest("day", 5))
    print("sim(day, night) =", vec.similarity("day", "night"))
    print("sim(day, stars) =", vec.similarity("day", "stars"))


if __name__ == "__main__":
    main()
