"""LeNet on MNIST (≡ dl4j-examples :: MnistClassifier) — the canonical
first example: build with the config DSL, fit, evaluate."""
from deeplearning4j_tpu.datasets.iterators import MnistDataSetIterator
from deeplearning4j_tpu.nn import (Adam, ConvolutionLayer, DenseLayer,
                                   InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SubsamplingLayer)
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener


def main():
    conf = (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater(Adam(1e-3))
            .weightInit("xavier")
            .list()
            .layer(ConvolutionLayer(kernelSize=(5, 5), nOut=20,
                                    activation="relu",
                                    convolutionMode="same"))
            .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(kernelSize=(5, 5), nOut=50,
                                    activation="relu",
                                    convolutionMode="same"))
            .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(nOut=500, activation="relu"))
            .layer(OutputLayer(lossFunction="negativeloglikelihood",
                               nOut=10, activation="softmax"))
            .setInputType(InputType.convolutionalFlat(28, 28, 1))
            .build())

    net = MultiLayerNetwork(conf).init()
    net.setListeners(ScoreIterationListener(10))
    train = MnistDataSetIterator(128, train=True)
    test = MnistDataSetIterator(128, train=False)
    net.fit(train, epochs=2)
    ev = net.evaluate(test)
    print(ev.stats())


if __name__ == "__main__":
    main()
