"""SameDiff graph building + training (≡ samediff-examples): define an
MLP as a graph, train with the TrainingConfig, inspect gradients."""
import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.nn.updaters import Adam


def main():
    sd = SameDiff.create()
    x = sd.placeHolder("x", None, 4)
    labels = sd.placeHolder("labels", None, 3)
    w0 = sd.var("w0", (4, 16))
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", (16, 3))
    b1 = sd.var("b1", np.zeros(3, np.float32))

    h = sd.nn.relu(sd.nn.linear(x, w0, b0))
    logits = sd.nn.linear(h, w1, b1).rename("logits")
    sd.loss.softmaxCrossEntropy("loss", labels, logits)
    sd.setLossVariables("loss")
    sd.setTrainingConfig(TrainingConfig.Builder()
                         .updater(Adam(1e-2)).l2(1e-4)
                         .dataSetFeatureMapping("x")
                         .dataSetLabelMapping("labels").build())

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(3, size=64)]
    for i in range(50):
        loss = sd.fit(X, Y)
    print("final loss:", loss)
    grads = sd.calculateGradients({"x": X, "labels": Y}, "w0", "w1")
    print("grad norms:", {k: float(np.linalg.norm(np.asarray(v.jax())))
                          for k, v in grads.items()})
    probs = sd.outputSingle({"x": X[:4]}, "logits")
    print("logits[0]:", np.asarray(probs.jax())[0])


if __name__ == "__main__":
    main()
