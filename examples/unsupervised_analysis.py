"""Unsupervised analysis toolchain (≡ dl4j-examples usage of
deeplearning4j-clustering KMeansClustering, VPTree nearest neighbors,
BarnesHutTsne visualization, and deeplearning4j-graph DeepWalk):
cluster a feature set, find nearest neighbors, project to 2-D, and embed
a graph's vertices — all on the accelerator (the Lloyd loop, the kNN
distance matrix, and the t-SNE descent each run as one jitted program).
"""
import numpy as np

from deeplearning4j_tpu.clustering import (BarnesHutTsne, KMeansClustering,
                                           Point, VPTree, knn)
from deeplearning4j_tpu.graph import DeepWalk, Graph


def make_blobs(n_per=60, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[0, 0, 0, 0], [6, 6, 0, 0], [0, 0, 6, 6]], np.float32)
    x = np.concatenate([rng.randn(n_per, 4).astype(np.float32) * 0.6 + c
                        for c in centers])
    return x, np.repeat(np.arange(3), n_per)


def main():
    x, true_labels = make_blobs()

    # 1. KMeans: whole Lloyd refinement is one jitted while_loop
    kmc = KMeansClustering.setup(3, maxIterationCount=50,
                                 useKMeansPlusPlus=True)
    cluster_set = kmc.applyTo(Point.toPoints(x))
    for cl in cluster_set.getClusters():
        print(f"cluster {cl.getId()}: {len(cl.getPoints())} points, "
              f"center {np.round(cl.getCenter(), 1)}")
    pc = cluster_set.classifyPoint(Point([6.1, 5.8, 0.2, -0.1]))
    print(f"query point -> cluster {pc.getCluster().getId()} "
          f"(distance {pc.getDistanceFromCenter():.2f})")

    # 2. Nearest neighbors: batched exact kNN = one GEMM + top-k on device
    idx, dist = knn(x[:5], x, k=4)
    print("kNN of point 0 (self first):", idx[0], np.round(dist[0], 2))
    # ... and the API-parity host-side VPTree for trickle queries
    tree = VPTree(x, "euclidean")
    results, dists = tree.search(x[0], 4)
    assert [r.getIndex() for r in results] == list(idx[0])

    # 3. t-SNE: exact O(N^2) gradients on the MXU, one jitted descent
    tsne = (BarnesHutTsne.Builder().setMaxIter(400).perplexity(20)
            .learningRate(200).seed(0).build())
    emb = tsne.fit(x).getData()
    d = np.sqrt(((emb[:, None] - emb[None, :]) ** 2).sum(-1))
    same = d[true_labels[:, None] == true_labels[None, :]].mean()
    diff = d[true_labels[:, None] != true_labels[None, :]].mean()
    print(f"t-SNE 2-D embedding: intra-blob dist {same:.2f} "
          f"vs inter-blob {diff:.2f}")

    # 4. DeepWalk: random walks host-side, skip-gram updates on device
    g = Graph(16)
    for base in (0, 8):                      # two 8-cliques + one bridge
        for i in range(8):
            for j in range(i + 1, 8):
                g.addEdge(base + i, base + j)
    g.addEdge(7, 8)
    dw = (DeepWalk.Builder().vectorSize(16).windowSize(4)
          .learningRate(0.5).epochs(40).batchSize(256).seed(1).build())
    dw.fit(g, walk_length=10)
    print(f"DeepWalk: sim(0,3) same community = {dw.similarity(0, 3):.2f}, "
          f"sim(0,12) across bridge = {dw.similarity(0, 12):.2f}")
    print("nearest to vertex 0:", dw.verticesNearest(0, top=4))


if __name__ == "__main__":
    main()
