"""Mixture density network regression (≡ LossMixtureDensity use case):
the target is BIMODAL per input — plain MSE would predict the useless
mean, the mixture places mass on both modes and sample() draws from
them."""
import jax
import numpy as np

from deeplearning4j_tpu.nn import (Adam, DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.nn.losses import LossMixtureDensity


def main():
    loss = LossMixtureDensity(gaussians=2, labelWidth=1)
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
        .weightInit("xavier").list()
        .layer(DenseLayer(nOut=32, activation="tanh"))
        .layer(OutputLayer(nOut=loss.nOut(), activation="identity",
                           lossFunction=loss))
        .setInputType(InputType.feedForward(1)).build()).init()

    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, size=(256, 1)).astype(np.float32)
    sign = rng.choice([-1.0, 1.0], size=(256, 1))
    y = (sign * 2.0 + 0.05 * rng.standard_normal((256, 1))
         ).astype(np.float32)

    for i in range(300):
        net.fit(x, y)
        if i % 100 == 99:
            print(f"iter {i + 1}: NLL {float(net.score()):.3f}")

    pre = np.asarray(net.output(x[:5]).numpy())
    samples = np.asarray(loss.sample(pre, jax.random.PRNGKey(0)))
    print("mixture samples for 5 inputs:", np.round(samples.ravel(), 2))
    # samples land near one of the two modes, not the mean (0)
    assert (np.abs(np.abs(samples) - 2.0) < 1.0).mean() > 0.5


if __name__ == "__main__":
    main()
