"""Durable model artifacts (round-5): SameDiff full-graph save/load.

Shows the three persistence forms a reference user expects:
  1. SameDiff.save/load — the whole graph (ops + values + training
     config) as one self-contained zip, restored with NO defining code;
  2. save_updater=True — optimizer moments travel too, so fit() resumes
     mid-momentum bit-exactly;
  3. ModelGuesser — "load whatever this file is".

Run: python examples/model_artifacts.py
"""
import os
import tempfile

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.util import ModelGuesser


def build():
    sd = SameDiff.create()
    x = sd.placeHolder("x", None, 8)
    w1 = sd.var("w1", np.random.RandomState(0).randn(8, 16).astype(
        np.float32) * 0.3)
    b1 = sd.var("b1", np.zeros(16, np.float32))
    h = sd.nn.relu(sd.nn.linear(x, w1, b1))
    w2 = sd.var("w2", np.random.RandomState(1).randn(16, 3).astype(
        np.float32) * 0.3)
    logits = h.mmul(w2).rename("logits")
    sd.nn.softmax(logits).rename("probs")
    labels = sd.placeHolder("labels", None, 3)
    sd.loss.softmaxCrossEntropy("loss", labels, logits)
    sd.setLossVariables("loss")
    sd.setTrainingConfig(TrainingConfig(updater=Adam(1e-2),
                                        dataSetFeatureMapping=["x"],
                                        dataSetLabelMapping=["labels"]))
    return sd


def main():
    rng = np.random.RandomState(2)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]

    sd = build()
    for i in range(10):
        loss = sd.fit(xs, ys)
    print(f"trained 10 steps, loss={loss:.4f}")

    with tempfile.TemporaryDirectory() as d:
        art = os.path.join(d, "classifier.sdz")
        # 1+2: full graph + optimizer moments, one zip, no pickle
        sd.save(art, save_updater=True)
        print(f"saved {os.path.getsize(art)} bytes -> {art}")

        restored = SameDiff.load(art)       # no build() needed
        probs = restored.outputSingle({"x": xs[:4]}, "probs")
        print("restored probs[0]:", np.asarray(probs.jax())[0].round(3))

        resumed_loss = restored.fit(xs, ys)  # continues mid-momentum
        print(f"resumed training, loss={resumed_loss:.4f}")

        # 3: the load-anything surface recognizes the artifact
        guessed = ModelGuesser.loadModelGuess(art)
        print("ModelGuesser ->", type(guessed).__name__)


if __name__ == "__main__":
    main()
