"""Classic text classification two ways (≡ dl4j-examples' bag-of-words /
CnnSentenceDataSetIterator text pipelines):

1. TfidfVectorizer → dense MLP (the classic sparse-features path)
2. StaticWordVectors + CnnSentenceDataSetIterator → Conv1D sentence
   classifier with padding masks (the Kim-CNN path)

Both run end-to-end on a tiny synthetic corpus.
"""
import numpy as np

from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                    CollectionLabeledSentenceProvider,
                                    StaticWordVectors, TfidfVectorizer)
from deeplearning4j_tpu.nn import (Adam, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (Convolution1DLayer,
                                               DenseLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer)


def corpus(n=120, seed=0):
    rng = np.random.RandomState(seed)
    pos = ["great", "wonderful", "excellent", "loved", "amazing"]
    neg = ["awful", "terrible", "boring", "hated", "dreadful"]
    fill = ["the", "movie", "plot", "acting", "film"]
    docs, labels = [], []
    for _ in range(n):
        good = rng.rand() < 0.5
        words = list(rng.choice(pos if good else neg, 3)) + \
            list(rng.choice(fill, 4))
        rng.shuffle(words)
        docs.append(" ".join(words))
        labels.append("pos" if good else "neg")
    return docs, labels


def tfidf_mlp(docs, labels):
    v = (TfidfVectorizer.Builder().minWordFrequency(1)
         .iterate(docs).labels(labels).build().fit())
    x = v.transformAll(docs)
    classes = list(dict.fromkeys(labels))
    y = np.eye(len(classes), dtype=np.float32)[
        [classes.index(l) for l in labels]]
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2)).list()
        .layer(DenseLayer(nOut=16, activation="relu"))
        .layer(OutputLayer(lossFunction="mcxent", nOut=len(classes),
                           activation="softmax"))
        .setInputType(InputType.feedForward(x.shape[1])).build()).init()
    for _ in range(40):
        net.fit(x, y)
    acc = (np.asarray(net.output(x)).argmax(-1) == y.argmax(-1)).mean()
    print(f"1. TF-IDF MLP train accuracy: {acc:.2f} "
          f"(vocab {v.vocabSize()})")


def cnn_sentence(docs, labels):
    vocab = sorted({w for d in docs for w in d.split()})
    rng = np.random.RandomState(1)
    wv = StaticWordVectors(rng.randn(len(vocab), 16).astype(np.float32),
                           vocab)
    it = (CnnSentenceDataSetIterator.Builder("RNN")
          .sentenceProvider(CollectionLabeledSentenceProvider(docs, labels))
          .wordVectors(wv).minibatchSize(32).maxSentenceLength(12).build())
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2)).list()
        .layer(Convolution1DLayer(nOut=24, kernelSize=3,
                                  convolutionMode="same",
                                  activation="relu"))
        .layer(GlobalPoolingLayer("max"))
        .layer(OutputLayer(lossFunction="mcxent", nOut=2,
                           activation="softmax"))
        .setInputType(InputType.recurrent(16)).build()).init()
    # iterator emits (B, vecSize, maxLen); our 1D layers take (B, T, F)
    for epoch in range(12):
        it.reset()
        for ds in iter_batches(it):
            net.fit(ds)
    it.reset()
    correct = total = 0
    for ds in iter_batches(it):
        pred = np.asarray(net.output(ds.features)).argmax(-1)
        correct += (pred == ds.labels.argmax(-1)).sum()
        total += len(pred)
    print(f"2. Conv1D sentence classifier train accuracy: "
          f"{correct / total:.2f}")


def iter_batches(it):
    while it.hasNext():
        ds = it.next()
        ds.features = ds.features.transpose(0, 2, 1)  # (B, T, F)
        yield ds


def main():
    docs, labels = corpus()
    tfidf_mlp(docs, labels)
    cnn_sentence(docs, labels)


if __name__ == "__main__":
    main()
