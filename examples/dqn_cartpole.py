"""DQN on CartPole (≡ rl4j-examples :: Cartpole DQN example)."""
from deeplearning4j_tpu.rl import (CartpoleNative,
                                   DQNDenseNetworkConfiguration,
                                   QLearningConfiguration,
                                   QLearningDiscreteDense)


def main():
    conf = QLearningConfiguration(
        seed=123, maxEpochStep=200, maxStep=12000, expRepMaxSize=10000,
        batchSize=64, targetDqnUpdateFreq=200, updateStart=128,
        gamma=0.99, minEpsilon=0.05, epsilonNbStep=6000)
    dqn = QLearningDiscreteDense(
        CartpoleNative(seed=0),
        DQNDenseNetworkConfiguration(numLayers=2, numHiddenNodes=64,
                                     learningRate=1e-3),
        conf)
    rewards = dqn.train()
    recent = rewards[-10:]
    print(f"episodes: {len(rewards)}; last-10 mean reward: "
          f"{sum(recent) / len(recent):.1f}")
    print("greedy play:", dqn.getPolicy().play(CartpoleNative(seed=99)))


if __name__ == "__main__":
    main()
