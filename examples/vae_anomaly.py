"""Variational autoencoder for anomaly scoring (≡ dl4j-examples ::
VariationalAutoEncoderExample): pretrain unsupervised, score outliers by
reconstruction error."""
import numpy as np

from deeplearning4j_tpu.nn import (Adam, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   VariationalAutoencoder)


def main():
    net = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-3))
        .weightInit("xavier").activation("tanh").list()
        .layer(VariationalAutoencoder(
            nOut=2, encoderLayerSizes=(32,), decoderLayerSizes=(32,),
            reconstructionDistribution="gaussian"))
        .layer(OutputLayer(lossFunction="mse", nOut=1,
                           activation="identity"))
        .setInputType(InputType.feedForward(8)).build()).init()

    rng = np.random.default_rng(0)
    normal = rng.normal(0, 1, size=(256, 8)).astype(np.float32)
    net.pretrainLayer(0, normal, epochs=150)

    vae = net.layers[0]
    params = net._params["0"]
    inliers = rng.normal(0, 1, size=(16, 8)).astype(np.float32)
    outliers = rng.normal(6, 1, size=(16, 8)).astype(np.float32)

    def recon_error(batch):
        rec = np.asarray(vae.reconstruct(params, batch))
        return float(((rec - batch) ** 2).mean())

    print("inlier reconstruction MSE: ", round(recon_error(inliers), 3))
    print("outlier reconstruction MSE:", round(recon_error(outliers), 3))


if __name__ == "__main__":
    main()
