"""Long-sequence BERT training: ring attention over the sequence axis +
per-layer rematerialization + ZeRO-1 state sharding in one jitted step.

The three memory levers compose:
- sp (sequence parallel): each device holds T/sp of the sequence; the
  ring attention kernel streams K/V shards around the ICI ring
  (parallel/ring_attention.py), so no device ever materializes the full
  (T, T) score matrix.
- remat: encoder layers recompute activations in backward
  (BertConfig(remat=True) -> jax.checkpoint per layer).
- ZeRO-1: Adam moments shard over dp (parallel/zero.py).

Run on a TPU slice, or simulate with
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
"""
import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.bert import (bert_tiny, classification_loss,
                                            init_bert_params, sharding_rules)
from deeplearning4j_tpu.parallel.ring_attention import make_ring_attention
from deeplearning4j_tpu.parallel.zero import shard_optimizer_state


def main():
    devices = jax.devices()
    dp, sp = 2, len(devices) // 2
    mesh = Mesh(np.array(devices[:dp * sp]).reshape(dp, sp), ("dp", "sp"))
    T = 64 * sp   # sequence length scales with the sp axis
    B = 2 * dp

    cfg = bert_tiny(max_position_embeddings=T, remat=True)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    rules = sharding_rules(cfg, mesh, dp="dp", tp=None)  # no tp axis here
    params = jax.tree_util.tree_map(jax.device_put, params, rules)

    tx = optax.adam(1e-4)
    opt_state = shard_optimizer_state(tx.init(params), mesh, axis="dp")

    ring = make_ring_attention(mesh, "sp")
    spec = P(None, None, "sp", None)
    ring_sharded = jax.shard_map(ring, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False)

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jax.device_put(
            rng.integers(0, cfg.vocab_size, (B, T)),
            NamedSharding(mesh, P("dp", "sp"))),
        "labels": jax.device_put(rng.integers(0, cfg.num_labels, (B,)),
                                 NamedSharding(mesh, P("dp"))),
    }

    @jax.jit
    def train_step(params, opt_state, batch):
        def loss_fn(p):
            return classification_loss(cfg, p, batch, train=False,
                                       attn_impl=ring_sharded)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for step in range(3):
        params, opt_state, loss = train_step(params, opt_state, batch)
        print(f"step {step}: T={T} loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
