#!/bin/bash
cd /root/repo
for i in $(seq 1 40); do
  date -u +"probe %H:%M:%S"
  if timeout 130 python _probe.py 2>&1 | grep -q "PROBE devices"; then
    echo "TUNNEL HEALTHY at $(date -u) — launching campaign"
    exec /root/repo/_campaign.sh
  fi
  sleep 780
done
echo "gave up after 40 probes"
exit 1
