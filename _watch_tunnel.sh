#!/bin/bash
# Probe the axon tunnel every 15 min; exit 0 the moment it is healthy.
# The probe self-deadlines (os._exit) and never holds the chip while hung:
# a hung init is waiting in the relay queue, not holding a grant.
cd /root/repo
for i in $(seq 1 40); do
  date -u +"probe %H:%M:%S"
  if timeout 130 python _probe.py 2>&1 | grep -q "PROBE devices"; then
    echo "TUNNEL HEALTHY at $(date -u)"
    exit 0
  fi
  sleep 780
done
echo "gave up after 40 probes"
exit 1
