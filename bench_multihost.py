#!/usr/bin/env python
"""CPU microbench: in-step gradient accumulation + bucketed overlapped
exchange vs the naive per-microbatch loop (parallel/ — ISSUE 14), one
JSON line.

Measures the dispatch-amortization the accumulated step exists for,
with bench.py's median-of-≥5-windows + recorded-spread methodology
(VERDICT r4: a point sample of a ±20%-noise distribution is not a
measurement), on the 8-virtual-device CPU mesh (dispatch/IO-bound: the
model is small, so per-dispatch host round-trips dominate — the same
regime the tunnelled-TPU BENCH rounds measured):

- **naive arm** — what a G-sized effective batch costs today without
  in-step accumulation: G per-microbatch optimizer steps, i.e. G
  dispatches + G updater applications per effective batch.
- **accumulated arm** — `MultiHostTrainer(accumulation=G)`: ONE jitted
  dispatch per effective batch (the step scans the G microbatches,
  accumulates on device, applies one update), threshold-encoded and
  exchanged through byte-balanced buckets.

Acceptance: dispatches-per-optimizer-step == 1 at G=4 and G=8 for the
accumulated arm (vs G for naive), effective-batch/s ≥ 1.3× naive at
both G, and the compiled step's HLO passes the structural overlap
assertion (bucket k's collective scheduled before bucket k+1's encode
— `parallel.buckets.check_overlap_structure`). Also reports the
per-bucket encoded-bytes ledger from the encoder state.

The **sparse-wire arm** (ISSUE 17) measures the ragged wire format
against the dense pmean baseline at the MEASURED nnz: per-worker
per-bucket wire bytes ((capacity + header) int32 slots vs 4 bytes per
element dense), the nnz ledger those bytes track, and the wall cost of
an elastic re-form (mid-run JOIN: drain save + leader commit + mesh
rebuild 4→8 devices + encoder re-stack + re-place). Headline `value`
is the dense/wire byte ratio (higher = fewer bytes on the wire);
`scripts/check_bench_regression.py` gates successive MULTIHOST_*
artifacts on it.

Run:  JAX_PLATFORMS=cpu python bench_multihost.py
"""
import argparse
import json
import os
import time

# 8 virtual devices BEFORE jax initializes (mirror tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xf = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xf:
    os.environ["XLA_FLAGS"] = (
        _xf + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

# bench.py is import-safe (no device init at module scope) — share THE
# windowing helper instead of copying it, so the methodology cannot
# drift between benches
from bench import _median_of_windows

G_VALUES = (4, 8)
MICRO_BATCH = 64
FEATURES = 256
HIDDEN = 256
CLASSES = 16
STEPS_PER_WINDOW = 6      # effective (super-batch) steps per window
NUM_BUCKETS = 4
SPEEDUP_TARGET = 1.3


def _loss_fn(params, batch, rng):
    import jax
    import jax.numpy as jnp
    h = jnp.tanh(batch["x"] @ params["W1"] + params["b1"])
    logits = h @ params["W2"] + params["b2"]
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.mean(jnp.sum(batch["y"] * logp, -1))


def _init_params(seed=0):
    r = np.random.default_rng(seed)
    return {
        "W1": (r.standard_normal((FEATURES, HIDDEN)) * 0.05
               ).astype(np.float32),
        "b1": np.zeros(HIDDEN, np.float32),
        "W2": (r.standard_normal((HIDDEN, CLASSES)) * 0.05
               ).astype(np.float32),
        "b2": np.zeros(CLASSES, np.float32),
    }


def _micro_batches(g, seed=1):
    r = np.random.default_rng(seed)
    xs = r.standard_normal((g, MICRO_BATCH, FEATURES)).astype(np.float32)
    ys = np.eye(CLASSES, dtype=np.float32)[
        r.integers(0, CLASSES, (g, MICRO_BATCH))]
    return xs, ys


def _make_trainer(g):
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.multihost import MultiHostTrainer
    return MultiHostTrainer(
        _loss_fn, Sgd(0.05), compress=True, accumulation=g,
        buckets=NUM_BUCKETS, compression_kw={"initial_threshold": 1e-4})


def _bench_arms(g):
    """Naive (G per-microbatch optimizer steps) vs accumulated (one
    jitted step per effective batch) at accumulation G. Returns the
    per-arm rates + dispatch counts + the accumulated trainer's wire
    ledger and HLO overlap verdict."""
    import jax

    from deeplearning4j_tpu.parallel.buckets import \
        check_overlap_structure
    from deeplearning4j_tpu.parallel.multihost import global_batch

    xs, ys = _micro_batches(g)
    key = jax.random.PRNGKey(0)

    # -- accumulated arm -------------------------------------------------
    acc = _make_trainer(g)
    p, s = acc.init(_init_params())
    super_batch = global_batch(acc.mesh, {"x": xs, "y": ys},
                               accumulation=g)
    step = acc.make_step()
    dispatches = {"accum": 0}

    def accum_step(p, s, rng):
        dispatches["accum"] += 1
        return step(p, s, super_batch, rng)

    p, s, _ = accum_step(p, s, key)          # warm the compile
    jax.block_until_ready(p)
    hlo = step.lower(p, s, super_batch, key).compile().as_text()
    overlap_problems = check_overlap_structure(
        hlo, acc.bucket_plan.num_buckets)
    # settle after the HLO lowering (it compiles a second executable,
    # which would otherwise cold-start the first timed window)
    p, s, _ = accum_step(p, s, key)
    jax.block_until_ready(p)

    def accum_window(i):
        nonlocal p, s
        dispatches["accum"] = 0
        t0 = time.perf_counter()
        for n in range(STEPS_PER_WINDOW):
            p, s, loss = accum_step(p, s, jax.random.fold_in(key, n))
        jax.block_until_ready(loss)
        wall = time.perf_counter() - t0
        assert dispatches["accum"] == STEPS_PER_WINDOW
        return STEPS_PER_WINDOW / wall

    acc_rate, acc_vals, acc_spread = _median_of_windows(accum_window)
    ledger = acc.encoder_stats(s)

    # -- naive arm: G separate optimizer steps per effective batch ------
    naive = _make_trainer(1)
    np_, ns_ = naive.init(_init_params())
    micro = [global_batch(naive.mesh, {"x": xs[i], "y": ys[i]})
             for i in range(g)]
    nstep = naive.make_step()

    def naive_effective_batch(p, s, rng):
        for i in range(g):
            dispatches["naive"] += 1
            p, s, loss = nstep(p, s, micro[i],
                               jax.random.fold_in(rng, i))
        return p, s, loss

    dispatches["naive"] = 0
    np_, ns_, _ = naive_effective_batch(np_, ns_, key)   # warm
    jax.block_until_ready(np_)

    def naive_window(i):
        nonlocal np_, ns_
        dispatches["naive"] = 0
        t0 = time.perf_counter()
        for n in range(STEPS_PER_WINDOW):
            np_, ns_, loss = naive_effective_batch(
                np_, ns_, jax.random.fold_in(key, n))
        jax.block_until_ready(loss)
        wall = time.perf_counter() - t0
        assert dispatches["naive"] == STEPS_PER_WINDOW * g
        return STEPS_PER_WINDOW / wall

    nv_rate, nv_vals, nv_spread = _median_of_windows(naive_window)

    return {
        "accumulation": g,
        "accum_steps_per_s": round(acc_rate, 2),
        "accum_windows": [round(v, 2) for v in acc_vals],
        "accum_spread_pct": round(acc_spread * 100, 1),
        "naive_steps_per_s": round(nv_rate, 2),
        "naive_windows": [round(v, 2) for v in nv_vals],
        "naive_spread_pct": round(nv_spread * 100, 1),
        "speedup": round(acc_rate / nv_rate, 2),
        "dispatches_per_opt_step": {"accum": 1, "naive": g},
        "num_buckets": acc.bucket_plan.num_buckets,
        "bucket_bytes": list(acc.bucket_plan.bucket_bytes),
        "bucket_encoded_bytes": ledger["bucket_encoded_bytes"],
        "encoded_bytes": ledger["encoded_bytes"],
        "overlap_structure_ok": not overlap_problems,
        "overlap_problems": overlap_problems,
    }


def _bench_sparse_wire(wire_capacity=0.05, steps=8):
    """Sparse ragged wire vs the dense pmean baseline at the measured
    nnz, on the same bucketed MLP: the dense exchange moves 4 bytes per
    PARAMETER per worker per step regardless of sparsity; the sparse
    wire moves (capacity + header) int32 slots per bucket — sized to
    the nnz ledger, not the parameter count."""
    import jax

    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.multihost import (MultiHostTrainer,
                                                       global_batch)
    tr = MultiHostTrainer(
        _loss_fn, Sgd(0.05), compress=True, buckets=NUM_BUCKETS,
        wire="sparse", wire_capacity=wire_capacity,
        compression_kw={"initial_threshold": 1e-4})
    p, s = tr.init(_init_params())
    xs, ys = _micro_batches(1)
    batch = global_batch(tr.mesh, {"x": xs[0], "y": ys[0]})
    key = jax.random.PRNGKey(0)
    for n in range(steps):
        p, s, loss = tr.fit_batch(p, s, batch, jax.random.fold_in(key, n))
    jax.block_until_ready(loss)
    ledger = tr.encoder_stats(s)
    return {
        "wire_capacity_frac": wire_capacity,
        "wire_capacity_tokens": ledger["wire_capacity"],
        "nnz": ledger["nnz"],
        "nnz_wire_cost_bytes": ledger["encoded_bytes"],
        "wire_bytes": ledger["wire_bytes"],
        "dense_bytes": ledger["dense_bytes"],
        "dense_over_wire": round(
            ledger["dense_bytes"] / ledger["wire_bytes"], 2),
        "bucket_wire_bytes": ledger["bucket_wire_bytes"],
    }


def _bench_elastic_reform():
    """Wall cost of one mid-run JOIN re-form (drain save + leader
    commit + trainer rebuild on the widened 4→8-device mesh + encoder
    re-stack + re-place), measured around the runner's own `_reform` on
    the live coordination-KV flow."""
    import tempfile
    import threading

    import jax

    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.multihost import (ElasticMembership,
                                                       LocalKV,
                                                       MultiHostRunner,
                                                       MultiHostTrainer,
                                                       PeerCoordinator,
                                                       global_batch)
    from jax.sharding import Mesh

    def mesh_factory(members):
        return Mesh(np.array(jax.devices()[:4 * len(members)]), ("dp",))

    kv, tmp = LocalKV(), tempfile.mkdtemp()
    c0 = PeerCoordinator(sync_every=2, peer_timeout=8.0, client=kv,
                         process_id=0, num_processes=1, dump_dir=tmp)
    tr = MultiHostTrainer(_loss_fn, Sgd(0.05), compress=True,
                          mesh=mesh_factory([0]), buckets=NUM_BUCKETS,
                          compression_kw={"initial_threshold": 1e-4})
    runner = MultiHostRunner(tr, tmp + "/ck", c0, save_every=100,
                             elastic=True, mesh_factory=mesh_factory,
                             monitor=False, sigterm=False)
    p, s = runner.resume_or_init(_init_params())
    xs, ys = _micro_batches(1)
    key = jax.random.PRNGKey(0)

    reform_ms = []
    orig = runner._reform

    def timed(*a, **k):
        t0 = time.perf_counter()
        out = orig(*a, **k)
        reform_ms.append((time.perf_counter() - t0) * 1000.0)
        return out

    runner._reform = timed

    def joiner():
        c1 = PeerCoordinator(sync_every=2, peer_timeout=12.0, client=kv,
                             process_id=1, num_processes=1, dump_dir=tmp)
        m1 = ElasticMembership(c1, members=[1])
        m1.announce_join()
        info = m1.await_admission(timeout=30.0)
        c1.step, c1.rounds = int(info["cstep"]), int(info["rounds"])
        # the runner drives 4 more fit_batch after the step-2 re-form
        # (sync_every=2 → 2 rounds): pump exactly those, or the runner
        # times out on a missing heartbeat and spuriously replaces us
        for _ in range(4):
            c1.on_step()

    t = threading.Thread(target=joiner)
    t.start()
    time.sleep(0.3)      # let the announcement land before step 1
    for n in range(6):   # the join lands at the first sync boundary
        batch = global_batch(runner.trainer.mesh,
                             {"x": xs[0], "y": ys[0]})
        p, s, _ = runner.fit_batch(p, s, batch,
                                   jax.random.fold_in(key, n))
    t.join(timeout=60)
    runner.close()
    assert reform_ms, "the join never re-formed — bench harness bug"
    return {"join_reform_ms": round(reform_ms[0], 1),
            "dp_after": int(s["encoder"]["threshold"].shape[0])}


def run():
    import jax
    result = {
        "devices": len(jax.devices()),
        "micro_batch": MICRO_BATCH,
        "model": f"mlp {FEATURES}x{HIDDEN}x{CLASSES}",
        "steps_per_window": STEPS_PER_WINDOW,
    }
    for g in G_VALUES:
        result[f"g{g}"] = _bench_arms(g)
    result["sparse_wire"] = _bench_sparse_wire()
    result["elastic_reform"] = _bench_elastic_reform()
    # flat-local artifact headline for check_bench_regression.py: the
    # dense/wire byte ratio at the measured nnz (higher is better)
    result["value"] = result["sparse_wire"]["dense_over_wire"]
    result["metric"] = "dense_bytes / sparse_wire_bytes"
    result["unit"] = "x"
    return result


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.parse_args()
    result = run()
    print(json.dumps(result))
    bad = []
    for g in G_VALUES:
        arm = result[f"g{g}"]
        if arm["speedup"] < SPEEDUP_TARGET:
            bad.append(f"g{g} speedup {arm['speedup']} < "
                       f"{SPEEDUP_TARGET}")
        if not arm["overlap_structure_ok"]:
            bad.append(f"g{g} overlap structure: "
                       + "; ".join(arm["overlap_problems"]))
    sw = result["sparse_wire"]
    if sw["wire_bytes"] >= sw["dense_bytes"]:
        bad.append(f"sparse wire moved {sw['wire_bytes']} bytes ≥ dense "
                   f"{sw['dense_bytes']} — the ragged format lost its "
                   f"reason to exist")
    if bad:
        raise SystemExit("bench targets missed: " + " | ".join(bad))


if __name__ == "__main__":
    main()
