#!/usr/bin/env python
"""CPU microbench: decode superstep pipeline vs the per-token decode
loop (generation/ — ISSUE 13), one JSON line.

Steady-state decode throughput over a char-RNN-sized
TextGenerationLSTM-style model, measured with bench.py's
median-of-≥5-windows + recorded-spread methodology (VERDICT r4: a
point sample of a ±20%-noise distribution is not a measurement):

- **per-token arm (k=1)** — the PR 8 decode loop: one fixed-shape
  dispatch and ONE host token fetch per token.
- **superstep arms (k=4, k=8)** — k decode steps run as one `lax.scan`
  dispatch; the sampled-token block's host copy overlaps the next
  block's compute. Acceptance: ≥2x tokens/s over the per-token arm at
  BOTH k, with the greedy token streams of all arms identical.
- **drafting arm** — exact greedy drafting on a bert-tiny KV-cache
  server (`draft=3`): host n-gram proposals verified in one
  multi-query dispatch, only exact greedy matches delivered. Stream
  must be token-identical to the undrafted bert arm (exactness is the
  contract; acceptance RATE is workload-dependent).
- **admission mid-flight** — continuous batching under churn at k=8:
  admissions land between supersteps with zero compiles.

Each arm also reports tokens-per-dispatch and host-syncs-per-token —
the dispatch-amortization counters the superstep exists to move.

Run:  JAX_PLATFORMS=cpu python bench_generation.py
"""
import argparse
import json
import time

# bench.py is import-safe (no device init at module scope) — share THE
# windowing helper instead of copying it, so the methodology cannot
# drift between benches
from bench import _median_of_windows

VOCAB = 32
CACHE_LEN = 256
WINDOW_TOKENS = 120
PROMPT = [1, 5, 3, 7, 2, 6, 4, 8]


def _build_net(hidden=192, seed=7):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .weightInit("xavier").list()
            .layer(LSTM(nOut=hidden, activation="tanh"))
            .layer(LSTM(nOut=hidden, activation="tanh"))
            .layer(RnnOutputLayer(lossFunction="mcxent", nOut=VOCAB,
                                  activation="softmax"))
            .setInputType(InputType.recurrent(VOCAB)).build())
    return MultiLayerNetwork(conf).init()


def _bench_decode_arm(net, k):
    """Steady-state greedy decode tokens/s at superstep k (k=1 = the
    per-token loop), median over ≥5 generate() windows; asserts zero
    compiles past warmup and returns the greedy stream for the
    cross-arm identity check."""
    from deeplearning4j_tpu.generation import GenerationServer
    srv = GenerationServer(net, slots=1, cache_lengths=[CACHE_LEN],
                           prompt_buckets=[8], method="greedy", seed=0,
                           superstep=k)
    warm = srv.warmup()
    try:
        stream = srv.generate(PROMPT, max_new_tokens=WINDOW_TOKENS,
                              timeout=600)    # warm the loop + capture
        compiles0 = srv._store.stats["compiles"]
        traces0 = srv._store.trace_calls

        def window(_i):
            t0 = time.perf_counter()
            toks = srv.generate(PROMPT, max_new_tokens=WINDOW_TOKENS,
                                timeout=600)
            wall = time.perf_counter() - t0
            assert toks == stream, "greedy stream changed mid-bench"
            return WINDOW_TOKENS / wall

        rate, vals, spread = _median_of_windows(window)
        assert srv._store.stats["compiles"] == compiles0, \
            "steady-state decode must not compile"
        assert srv._store.trace_calls == traces0
        st = srv.status()
        return {"superstep": k,
                "tokens_per_s": round(rate, 1),
                "per_token_ms": round(1e3 / rate, 4),
                "windows": [round(v, 1) for v in vals],
                "spread_pct": round(spread * 100, 1),
                "tokens_per_dispatch": st["tokens_per_dispatch"],
                "host_syncs_per_token": st["host_syncs_per_token"],
                "per_token_p50_ms": st["per_token_p50_ms"],
                "per_token_p99_ms": st["per_token_p99_ms"],
                "warmup_s": round(warm["seconds"], 3)}, stream
    finally:
        srv.shutdown()


def _bench_drafting_arm():
    """Exact greedy drafting on a bert-tiny KV-cache server: stream
    token-identical to the undrafted arm (the exactness contract),
    accept/reject tallies reported."""
    import jax
    from deeplearning4j_tpu.generation import GenerationServer
    from deeplearning4j_tpu.generation.decode import BertDecoder
    from deeplearning4j_tpu.models.bert import bert_tiny, init_bert_params
    cfg = bert_tiny()
    params = init_bert_params(cfg, jax.random.PRNGKey(1))
    tokens = 48          # prompt 8 + 48 fits bert_tiny's 64 positions
    out = {}
    streams = {}
    for name, kw in (("plain", {}), ("drafting", {"draft": 3})):
        srv = GenerationServer(BertDecoder(cfg, params), slots=1,
                               cache_lengths=[64], prompt_buckets=[8],
                               method="greedy", seed=0, **kw)
        srv.warmup()
        try:
            streams[name] = srv.generate(PROMPT, max_new_tokens=tokens,
                                         timeout=600)

            def window(_i):
                t0 = time.perf_counter()
                got = srv.generate(PROMPT, max_new_tokens=tokens,
                                   timeout=600)
                wall = time.perf_counter() - t0
                assert got == streams[name]
                return tokens / wall

            rate, vals, spread = _median_of_windows(window)
            st = srv.status()
            out[name] = {"tokens_per_s": round(rate, 1),
                         "windows": [round(v, 1) for v in vals],
                         "spread_pct": round(spread * 100, 1),
                         "tokens_per_dispatch": st["tokens_per_dispatch"],
                         "host_syncs_per_token":
                             st["host_syncs_per_token"],
                         "draft_accepts": srv.stats["draft_accepts"],
                         "draft_rejects": srv.stats["draft_rejects"]}
        finally:
            srv.shutdown()
    assert streams["drafting"] == streams["plain"], \
        "drafted greedy stream must be token-identical to vanilla"
    out["greedy_tokens_agree"] = True
    return out


def _bench_admission_mid_flight(net):
    """Continuous batching under churn at k=8: two long decodes run
    while two more admit into the in-flight batch between supersteps;
    aggregate throughput, zero compiles."""
    from deeplearning4j_tpu.generation import GenerationServer
    srv = GenerationServer(net, slots=4, cache_lengths=[CACHE_LEN],
                           prompt_buckets=[8], method="greedy", seed=0,
                           superstep=8)
    srv.warmup()
    try:
        compiles0 = srv._store.stats["compiles"]
        t0 = time.perf_counter()
        first = [srv.submit([1, 2, 3], max_new_tokens=120)
                 for _ in range(2)]
        while srv.stats["tokens"] < 60:     # mid-flight...
            time.sleep(0.01)
        late = [srv.submit([4, 5], max_new_tokens=80)
                for _ in range(2)]
        total = sum(len(r.result(timeout=600)) for r in first + late)
        wall = time.perf_counter() - t0
        assert srv._store.stats["compiles"] == compiles0, \
            "mid-flight admission must not compile"
        return {"requests": 4,
                "tokens": total,
                "seconds": round(wall, 3),
                "tokens_per_s": round(total / wall, 1),
                "admissions": srv.stats["admissions"],
                "supersteps": srv.stats["supersteps"]}
    finally:
        srv.shutdown()


def run():
    net = _build_net()
    arms = {}
    streams = {}
    for k in (1, 4, 8):
        arms[f"k{k}"], streams[k] = _bench_decode_arm(net, k)
    return {
        "cache_len": CACHE_LEN,
        "vocab": VOCAB,
        "window_tokens": WINDOW_TOKENS,
        "greedy_tokens_agree_across_k": streams[1] == streams[4]
        == streams[8],
        "per_token": arms["k1"],
        "superstep_k4": arms["k4"],
        "superstep_k8": arms["k8"],
        "speedup_k4": round(arms["k4"]["tokens_per_s"]
                            / arms["k1"]["tokens_per_s"], 2),
        "speedup_k8": round(arms["k8"]["tokens_per_s"]
                            / arms["k1"]["tokens_per_s"], 2),
        "drafting": _bench_drafting_arm(),
        "admission_mid_flight": _bench_admission_mid_flight(net),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.parse_args()
    result = run()
    print(json.dumps(result))
    if not result["greedy_tokens_agree_across_k"]:
        raise SystemExit("greedy streams diverged across block sizes")
    bad = [k for k in ("speedup_k4", "speedup_k8") if result[k] < 2.0]
    if bad:
        raise SystemExit(
            f"superstep speedups below the 2x target: "
            + ", ".join(f"{k}={result[k]}" for k in bad))


if __name__ == "__main__":
    main()
