#!/usr/bin/env python
"""CPU microbench: KV/carry-cache decode vs per-token full-sequence
re-forward (generation/ — ROADMAP item 2), one JSON line.

Three measurements over a char-RNN-sized TextGenerationLSTM-style
model at sequence length 256:

- **cached decode** — GenerationServer steady state: prefill once, then
  one fixed-shape step executable per token (O(1) work/token). Reports
  tokens/s and per-token ms; asserts the store never compiled past
  warmup.
- **full re-forward** — the no-decode-path baseline this PR removes:
  every new token re-runs the whole fixed-shape (1, 256, F) masked
  forward (one jit compile up front, O(T) work/token — the honest
  "no incremental decode" serving strategy with static shapes).
  Acceptance target: cached decode >= 5x tokens/s.
- **admission mid-flight** — continuous batching under churn: two long
  requests decode while two more are admitted into the in-flight
  batch; reports aggregate tokens/s and asserts zero compiles and
  zero extra traces during the whole run.

Run:  JAX_PLATFORMS=cpu python bench_generation.py
"""
import argparse
import json
import time

import numpy as np

SEQ_LEN = 256
VOCAB = 32


def _build_net(hidden=192, seed=7):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .weightInit("xavier").list()
            .layer(LSTM(nOut=hidden, activation="tanh"))
            .layer(LSTM(nOut=hidden, activation="tanh"))
            .layer(RnnOutputLayer(lossFunction="mcxent", nOut=VOCAB,
                                  activation="softmax"))
            .setInputType(InputType.recurrent(VOCAB)).build())
    return MultiLayerNetwork(conf).init()


def _bench_cached_decode(net, prompt, new_tokens):
    from deeplearning4j_tpu.generation import GenerationServer
    srv = GenerationServer(net, slots=1, cache_lengths=[SEQ_LEN],
                           prompt_buckets=[8], method="greedy", seed=0)
    warm = srv.warmup()
    try:
        compiles0 = srv._store.stats["compiles"]
        traces0 = srv._store.trace_calls
        t0 = time.perf_counter()
        toks = srv.generate(prompt, max_new_tokens=new_tokens,
                            timeout=600)
        wall = time.perf_counter() - t0
        assert len(toks) == new_tokens
        assert srv._store.stats["compiles"] == compiles0, \
            "steady-state decode must not compile"
        assert srv._store.trace_calls == traces0
        return {"tokens": new_tokens,
                "seconds": round(wall, 3),
                "tokens_per_s": round(new_tokens / wall, 1),
                "per_token_ms": round(wall * 1e3 / new_tokens, 3),
                "warmup_s": round(warm["seconds"], 3)}, toks
    finally:
        srv.shutdown()


def _bench_full_reforward(net, prompt, new_tokens):
    """Per-token FULL fixed-shape re-forward: the pre-decode-path
    serving strategy — static (1, SEQ_LEN, F) masked forward, logits
    read at the last real position, one whole-sequence scan per
    token."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fwd(params, state, x, mask):
        _, preact, _, _ = net._forward(params, state, x, False, None,
                                       mask=mask)
        return preact

    seq = list(prompt)
    x = np.zeros((1, SEQ_LEN, VOCAB), np.float32)
    for i, t in enumerate(seq):
        x[0, i, t] = 1.0
    mask = np.zeros((1, SEQ_LEN), np.float32)
    # compile once outside the timed loop (shapes never change)
    mask[0, :len(seq)] = 1.0
    fwd(net._params, net._state, jnp.asarray(x),
        jnp.asarray(mask)).block_until_ready()
    toks = []
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        n = len(seq)
        mask[0, :n] = 1.0
        logits = fwd(net._params, net._state, jnp.asarray(x),
                     jnp.asarray(mask))
        tok = int(np.argmax(np.asarray(logits[0, n - 1])))
        toks.append(tok)
        if n < SEQ_LEN:
            x[0, n, tok] = 1.0
            seq.append(tok)
    wall = time.perf_counter() - t0
    return {"tokens": new_tokens,
            "seconds": round(wall, 3),
            "tokens_per_s": round(new_tokens / wall, 1),
            "per_token_ms": round(wall * 1e3 / new_tokens, 3)}, toks


def _bench_admission_mid_flight(net):
    """Continuous batching under churn: start two long decodes, admit
    two more mid-flight; aggregate throughput, zero compiles."""
    from deeplearning4j_tpu.generation import GenerationServer
    srv = GenerationServer(net, slots=4, cache_lengths=[SEQ_LEN],
                           prompt_buckets=[8], method="greedy", seed=0)
    srv.warmup()
    try:
        compiles0 = srv._store.stats["compiles"]
        t0 = time.perf_counter()
        first = [srv.submit([1, 2, 3], max_new_tokens=120)
                 for _ in range(2)]
        while srv.stats["tokens"] < 60:     # mid-flight...
            time.sleep(0.01)
        late = [srv.submit([4, 5], max_new_tokens=80)
                for _ in range(2)]
        total = sum(len(r.result(timeout=600)) for r in first + late)
        wall = time.perf_counter() - t0
        assert srv._store.stats["compiles"] == compiles0, \
            "mid-flight admission must not compile"
        return {"requests": 4,
                "tokens": total,
                "seconds": round(wall, 3),
                "tokens_per_s": round(total / wall, 1),
                "admissions": srv.stats["admissions"]}
    finally:
        srv.shutdown()


def run(new_tokens=None):
    prompt = [1, 5, 3, 7, 2, 6, 4, 8]
    new_tokens = new_tokens or (SEQ_LEN - len(prompt))
    net = _build_net()
    cached, toks_c = _bench_cached_decode(net, prompt, new_tokens)
    full, toks_f = _bench_full_reforward(net, prompt, new_tokens)
    admission = _bench_admission_mid_flight(net)
    return {
        "seq_len": SEQ_LEN,
        "vocab": VOCAB,
        "greedy_tokens_agree": toks_c == toks_f,
        "cached_decode": cached,
        "full_reforward": full,
        "speedup_tokens_per_s": round(
            cached["tokens_per_s"] / full["tokens_per_s"], 2),
        "admission_mid_flight": admission,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tokens", type=int, default=None)
    args = ap.parse_args()
    result = run(new_tokens=args.tokens)
    print(json.dumps(result))
    if result["speedup_tokens_per_s"] < 5.0:
        raise SystemExit(
            f"cached decode speedup {result['speedup_tokens_per_s']}x "
            "below the 5x target")


if __name__ == "__main__":
    main()
