#!/usr/bin/env python
"""CPU microbench: async host pipeline vs the old per-step-sync loop.

Measures the overlap win the host pipeline (runtime/pipeline.py) buys
against an IO-bound synthetic loader — each `next()` sleeps `io_ms` to
model disk/decode/augment latency, the way a real input pipeline stalls
the host:

- **sync arm** (the pre-pipeline fit loop): prefetch disabled, plus a
  listener that reads `score()` every iteration — i.e. a blocking
  `float(loss)` per step. Each step costs loader + compute, serially.
- **async arm** (the pipeline): listener-free fit with the background
  device-staging prefetcher. Loader latency overlaps device compute, so
  a step costs ~max(loader, compute).

Why a microbench and not the TPU harness: the axon tunnel to the real
chip is flaky (BENCH.md round-5 outage), so the steady-state overlap
measurement is bench-measurement debt; this CPU-runnable bench
demonstrates the same host-side mechanism anywhere:

    JAX_PLATFORMS=cpu python bench_pipeline.py

Prints one JSON line: steps/s for both arms + speedup. Acceptance
target for the PR: >= 1.3x with the default io-bound loader.
"""
import argparse
import json
import time

import numpy as np


def _build_net(seed=7):
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration, OutputLayer,
                                       Sgd)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.05)).activation("relu")
            .list()
            .layer(DenseLayer.Builder().nOut(512).build())
            .layer(DenseLayer.Builder().nOut(512).build())
            .layer(DenseLayer.Builder().nOut(512).build())
            .layer(OutputLayer.Builder("mcxent").nOut(10)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(256))
            .build())
    return MultiLayerNetwork(conf).init()


class SlowLoader:
    """IO-bound DataSetIterator: deterministic in-memory batches plus a
    sleep per next() modelling loader latency (read/decode/augment)."""

    def __init__(self, n_batches, batch=256, n_in=256, n_classes=10,
                 io_ms=12.0, seed=0):
        rng = np.random.default_rng(seed)
        self._x = rng.standard_normal((n_batches, batch, n_in)) \
            .astype(np.float32)
        y = rng.integers(0, n_classes, (n_batches, batch))
        self._y = np.eye(n_classes, dtype=np.float32)[y]
        self._io_s = io_ms / 1e3
        self._cursor = 0

    def batch(self):
        return self._x.shape[1]

    def numExamples(self):
        return self._x.shape[0] * self._x.shape[1]

    def hasNext(self):
        return self._cursor < len(self._x)

    def next(self, num=None):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        time.sleep(self._io_s)     # the modelled IO stall
        ds = DataSet(self._x[self._cursor], self._y[self._cursor])
        self._cursor += 1
        return ds

    def reset(self):
        self._cursor = 0

    def resetSupported(self):
        return True

    def asyncSupported(self):
        return True

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self.hasNext():
            raise StopIteration
        return self.next()


class _SyncEveryStep:
    """The old loop's behavior as a listener: float(loss) every step."""

    def iterationDone(self, model, iteration, epoch):
        model.score()


def _time_fit(net, loader, steps, sync):
    t0 = time.perf_counter()
    net.fit(loader, epochs=1, prefetch=0 if sync else None)
    if not sync:
        # flush the async tail so the measurement covers ALL steps'
        # compute, not just their dispatch
        net.score()
    return steps / (time.perf_counter() - t0)


def run(steps=60, io_ms=None, warmup=6, batch=256, n_in=256):
    sync_net, async_net = _build_net(seed=7), _build_net(seed=7)
    sync_net.setListeners(_SyncEveryStep())

    # compile + cache warm for BOTH nets (identical shapes)
    for net in (sync_net, async_net):
        net.fit(SlowLoader(warmup, batch, n_in, io_ms=0.1), epochs=1,
                prefetch=0)
        net.score()

    if io_ms is None:
        # calibrate the IO stall to THIS host's measured step time, so
        # the ideal overlap win (~2x: loader fully hidden behind
        # compute) — and therefore the 1.3x acceptance margin — is
        # machine- and load-independent
        t0 = time.perf_counter()
        async_net.fit(SlowLoader(12, batch, n_in, io_ms=0.0), epochs=1,
                      prefetch=0)
        async_net.score()
        io_ms = max(2.0, (time.perf_counter() - t0) / 12 * 1e3)

    sync_sps = _time_fit(sync_net,
                         SlowLoader(steps, batch, n_in, io_ms=io_ms),
                         steps, sync=True)
    async_sps = _time_fit(async_net,
                          SlowLoader(steps, batch, n_in, io_ms=io_ms),
                          steps, sync=False)
    return {
        "steps": steps,
        "io_ms": round(io_ms, 2),
        "sync_steps_per_s": round(sync_sps, 2),
        "async_steps_per_s": round(async_sps, 2),
        "speedup": round(async_sps / sync_sps, 3),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--io-ms", type=float, default=None,
                    help="IO stall per batch; default: auto-calibrate to the measured step time")
    ap.add_argument("--warmup", type=int, default=6)
    args = ap.parse_args()
    result = run(steps=args.steps, io_ms=args.io_ms, warmup=args.warmup)
    print(json.dumps(result))
    if result["speedup"] < 1.3:
        raise SystemExit(
            f"speedup {result['speedup']}x below the 1.3x target")


if __name__ == "__main__":
    main()
