"""Round-4 TPU experiment runner — ONE serialized chip session per mode.

Follows the tunnel-safety pattern (see tests/conftest.py + bench.py): the
process sets its own internal deadline and ALWAYS exits on its own — never
SIGKILL a TPU-holding process, never run two TPU processes concurrently.

Modes (positional arg):
  smoke   — compile+run the round-4 Pallas paths on the real chip:
            cross-length flash fwd/bwd (with kv-mask), masked self flash
            (regression), LearnedSelfAttention layer forward.
  lstm    — char-LSTM throughput sweep: scanUnroll x batch x dtype
            (VERDICT r3 #2: find the 13 ms/iter overhead empirically).
  resnet  — quick ResNet-50 step timing + optional xplane trace with the
            new memory_breakdown table (VERDICT r3 #3 groundwork).

Each mode prints JSON lines prefixed '##' for easy grepping.
"""
from __future__ import annotations

import json
import os

os.environ.setdefault("DL4J_TPU_WANT_TPU", "1")  # TPU dev tool: explicit chip opt-in
import sys
import threading
import time

#: per-mode defaults — lstm is a 24-fresh-compile sweep (+1 trace pass).
#: Every deadline must exceed the remote compile service's own ~500 s
#: timeout with slack: exiting (even cleanly, via os._exit) while a compile
#: RPC is in flight wedges the tunnel exactly like a SIGKILL — observed
#: 2026-07-30 ~19:51 UTC when a 360 s smoke deadline fired mid-compile.
_DEFAULT_DEADLINES = {"probe": 90, "smoke": 900, "lstm": 2400,
                      "resnet": 900, "spd": 900, "longseq": 1200,
                      "bert": 1500, "clustering": 1200}


def _arm_deadline(mode):
    deadline = float(os.environ.get(
        "EXP_DEADLINE", _DEFAULT_DEADLINES.get(mode, 360)))

    def bail():
        time.sleep(deadline)
        print(f"## {json.dumps({'error': 'internal deadline'})}", flush=True)
        os._exit(3)

    threading.Thread(target=bail, daemon=True).start()


def _fresh_dir(path):
    """Trace dirs must start empty: find_xplane_files globs EVERY
    timestamped subdir, so a reused dir would sum stale runs into the
    per-op tables."""
    import shutil

    shutil.rmtree(path, ignore_errors=True)
    return path


def _emit(obj):
    print("## " + json.dumps(obj), flush=True)


def mode_probe():
    """Tunnel-health check: device init + one tiny matmul. The 90 s
    deadline fires only while WAITING for a relay grant (not holding
    one), so bailing is safe — see BENCH.md outage log."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    devs = jax.devices()
    x = jnp.ones((8, 128)) @ jnp.ones((128, 128))
    _emit({"devices": str(devs),
           "matmul_ok": float(x.sum()) == 8 * 128 * 128,
           "init_s": round(time.perf_counter() - t0, 1)})


def mode_smoke():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.kernels import flash_attention

    devs = jax.devices()
    _emit({"devices": str(devs)})
    b, h, d = 2, 4, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    # cross-length: Tq=128, Tk=384, ragged kv mask
    q = jax.random.normal(kq, (b, h, 128, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, 384, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, 384, d), jnp.float32)
    kv_mask = (jnp.arange(384)[None, :]
               < jnp.asarray([300, 384])[:, None]).astype(jnp.int32)
    t0 = time.perf_counter()
    out = flash_attention(q, k, v, kv_mask=kv_mask)
    out.block_until_ready()
    _emit({"cross_fwd_compile_s": round(time.perf_counter() - t0, 1),
           "cross_fwd_finite": bool(jnp.isfinite(out).all())})
    # dense oracle check ON CHIP
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
    s = jnp.where(kv_mask[:, None, None, :] > 0, s, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    err = float(jnp.abs(out - ref).max())
    _emit({"cross_fwd_max_abs_err_vs_dense": err, "ok": err < 3e-3})

    t0 = time.perf_counter()

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_mask=kv_mask) ** 2)

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    jax.block_until_ready((gq, gk, gv))
    ref_g = jax.grad(lambda q, k, v: jnp.sum(jnp.einsum(
        "bhqk,bhkd->bhqd",
        jax.nn.softmax(jnp.where(kv_mask[:, None, None, :] > 0,
                                 jnp.einsum("bhqd,bhkd->bhqk", q, k)
                                 / (d ** 0.5), -1e30), -1), v) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gerr = max(float(jnp.abs(a - b_).max())
               for a, b_ in zip((gq, gk, gv), ref_g))
    _emit({"cross_bwd_compile_s": round(time.perf_counter() - t0, 1),
           "cross_bwd_max_abs_err_vs_dense": gerr, "ok": gerr < 3e-2})

    # masked self-attention regression (hardware-proven path, re-check)
    qs = jax.random.normal(kq, (b, h, 256, d), jnp.float32)
    m = (jnp.arange(256)[None, :]
         < jnp.asarray([200, 256])[:, None]).astype(jnp.int32)
    o2 = flash_attention(qs, qs, qs, mask=m)
    o2.block_until_ready()
    _emit({"self_masked_ok": bool(jnp.isfinite(o2).all())})

    # causal flash ring, 1-device sp mesh: n=1 means only the diagonal
    # (causal-kernel) step runs, but that IS the Mosaic-lowering risk —
    # pallas inside lax.cond inside scan inside shard_map, on hardware
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import (
        dense_attention, make_ring_attention)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    ring = make_ring_attention(mesh, "sp", causal=True, use_flash=True,
                               interpret=None)  # compiled on TPU
    spec = P(None, None, "sp", None)
    f = jax.shard_map(ring, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, check_vma=False)
    qr = jax.random.normal(kq, (2, 2, 256, 64), jnp.float32)
    got = f(qr, qr, qr)
    want = dense_attention(qr, qr, qr, causal=True)
    rerr = float(jnp.abs(got - want).max())
    # tol: MXU default-precision noise at T=256 — the dense oracle itself
    # moves 2.1e-2 between default and highest matmul precision on chip,
    # and the hardware-proven noncausal ring sits at the same 7e-3 level
    _emit({"causal_ring_flash_max_abs_err": rerr, "ok": rerr < 2e-2})

    # layer-level: LearnedSelfAttention now routes flash cross on TPU
    from deeplearning4j_tpu.nn.conf.attention import \
        LearnedSelfAttentionLayer
    layer = LearnedSelfAttentionLayer(nIn=64, nOut=64, nHeads=4,
                                      nQueries=16)
    layer.apply_defaults({})
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    params, _, _ = layer.initialize(jax.random.PRNGKey(1),
                                    InputType.recurrent(64, 384))
    x = jax.random.normal(kq, (2, 384, 64), jnp.float32)
    y, _ = layer.apply(params, {}, x, mask=kv_mask)
    jax.block_until_ready(y)
    _emit({"learned_self_attention_layer_ok":
           bool(jnp.isfinite(y).all()), "shape": list(y.shape)})


def mode_lstm():
    import jax

    from bench import _bench_char_lstm

    # the sweep owns batch explicitly; an inherited env override would
    # silently collapse all batch rows to one value
    os.environ.pop("BENCH_LSTM_BATCH", None)
    results = []
    combos = [(b, u, dt) for b in (64, 128, 256)
              for u in (1, 4, 8, 16)       # 4 is the round-4-plan ask
              for dt in ("float32", "bfloat16")]
    for batch, unroll, dtype in combos:
        os.environ["BENCH_LSTM_UNROLL"] = str(unroll)
        os.environ["BENCH_LSTM_DTYPE"] = dtype
        try:
            t0 = time.perf_counter()
            chars_s, dt_s, compile_s = _bench_char_lstm(
                batch=batch, steps=20, warmup=2, k_windows=1)
            row = {"batch": batch, "unroll": unroll, "dtype": dtype,
                   "chars_s": round(chars_s, 0),
                   "step_ms": round(dt_s * 1000, 1),
                   "compile_s": round(compile_s, 1),
                   "wall_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # noqa: BLE001
            row = {"batch": batch, "unroll": unroll, "dtype": dtype,
                   "error": str(e)[:160]}
        results.append(row)
        _emit(row)
    best = max((r for r in results if "chars_s" in r),
               key=lambda r: r["chars_s"], default=None)
    _emit({"best": best})
    if os.environ.get("EXP_TRACE") and best:
        # trace ONE step of the best config for the per-op table
        os.environ["BENCH_LSTM_UNROLL"] = str(best["unroll"])
        os.environ["BENCH_LSTM_DTYPE"] = best["dtype"]
        trace_dir = _fresh_dir(
            os.environ.get("EXP_TRACE_DIR", "/tmp/r4_lstm_trace"))
        with jax.profiler.trace(trace_dir):
            _bench_char_lstm(batch=best["batch"], steps=2, warmup=1,
                             k_windows=1)
        from deeplearning4j_tpu.optimize.xplane import op_breakdown
        for name, ms, n in op_breakdown(trace_dir)[:15]:
            _emit({"op": name[:70], "ms": round(ms, 3), "n": n})


def _measure_hbm_gbps():
    """Achievable HBM bandwidth on THIS chip: time a saxpy over a buffer
    far larger than VMEM (reads 2 arrays + writes 1 → 3x bytes moved).
    Gives the denominator for a measured — not quoted — roofline bound."""
    import jax
    import jax.numpy as jnp

    from jax import lax

    n = 64 * 1024 * 1024          # 256 MB per fp32 array, 768 MB moved
    a = jnp.ones((n,), jnp.float32)
    b = jnp.full((n,), 2.0, jnp.float32)
    # ALL reps inside one dispatch: a host-side python loop measures the
    # tunnel's per-call latency (~10 ms), not HBM — observed 67.9 "GB/s"
    # for an op whose own XStat rate is ~800 GB/s
    reps = 100                     # ~95 ms device time >> ~10 ms tunnel RTT

    @jax.jit
    def sweep(a, b):
        return lax.fori_loop(0, reps, lambda i, x: x * 1.5 + b, a)

    float(sweep(a, b)[0])          # compile + first run
    t0 = time.perf_counter()
    float(sweep(a, b)[0])
    dt = (time.perf_counter() - t0) / reps
    return 3 * 4 * n / dt / 1e9


def mode_resnet():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.nn.updaters import Nesterovs

    _emit({"hbm_gbps_measured": round(_measure_hbm_gbps(), 1)})

    batch = int(os.environ.get("EXP_BATCH", "256"))
    mdt = os.environ.get("EXP_MOMENTUM_DTYPE") or None
    model = ResNet50(numClasses=1000, dataType="bfloat16",
                     inputShape=(224, 224, 3),
                     updater=Nesterovs(0.1, 0.9, momentumDtype=mdt))
    net = model.init()
    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.uniform(kx, (batch, 224, 224, 3), jnp.float32)
    y = jax.nn.one_hot(jax.random.randint(ky, (batch,), 0, 1000), 1000,
                       dtype=jnp.float32)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    is_graph = isinstance(net, ComputationGraph)
    ins = {"input": x} if is_graph else x
    labs = [y] if is_graph else y
    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    rng = jax.random.PRNGKey(1)
    t0 = time.perf_counter()
    for i in range(3):
        params, opt, state, loss = step(params, opt, state, ins, labs,
                                        None, None,
                                        jax.random.fold_in(rng, i))
    float(loss)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    steps = 20
    for i in range(steps):
        params, opt, state, loss = step(params, opt, state, ins, labs,
                                        None, None,
                                        jax.random.fold_in(rng, 100 + i))
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    _emit({"resnet_img_s": round(batch / dt, 1),
           "step_ms": round(dt * 1000, 1),
           "compile_s": round(compile_s, 1)})
    if os.environ.get("EXP_TRACE"):
        trace_dir = _fresh_dir(
            os.environ.get("EXP_TRACE_DIR", "/tmp/r4_trace"))
        trace_steps = 3
        with jax.profiler.trace(trace_dir):
            for i in range(trace_steps):
                params, opt, state, loss = step(
                    params, opt, state, ins, labs, None, None,
                    jax.random.fold_in(rng, 200 + i))
            float(loss)
        from deeplearning4j_tpu.optimize.xplane import (memory_breakdown,
                                                        op_breakdown)
        for name, ms, n in op_breakdown(trace_dir)[:12]:
            _emit({"op": name[:70], "ms": round(ms, 3), "n": n})
        rows = memory_breakdown(trace_dir)
        for name, ms, b, gbps in rows[:12]:
            _emit({"op": name[:70], "ms": round(ms, 3), "bytes": b,
                   "GBps": round(gbps, 1)})
        # roofline: XLA bytes-accessed per step over MEASURED saxpy
        # bandwidth — both numbers from this chip, this session
        total_b = sum(r[2] for r in rows) / trace_steps
        _emit({"step_bytes_est": int(total_b),
               "roofline_note": "bound_ms = step_bytes_est / hbm_gbps_"
                                "measured; compare to step_ms above"})


def mode_spd():
    """stepsPerDispatch A/B on the real chip: per-batch wall time of
    fit(iterator) vs fit(iterator, stepsPerDispatch=8) for a small-step
    model (LeNet b256 — the dispatch-latency-bound bench row)."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.zoo import LeNet
    from deeplearning4j_tpu.nn.updaters import Nesterovs

    rng = np.random.default_rng(0)
    n_batches, b = 32, 256
    sets = [DataSet(rng.random((b, 28, 28, 1), dtype=np.float32),
                    np.eye(10, dtype=np.float32)[
                        rng.integers(10, size=b)])
            for _ in range(n_batches)]

    for k in (1, 8):
        model = LeNet(numClasses=10, dataType="bfloat16",
                      inputShape=(28, 28, 1), updater=Nesterovs(0.01, 0.9))
        net = model.init()
        it = ListDataSetIterator(sets, b)
        t0 = time.perf_counter()
        net.fit(it, stepsPerDispatch=k)          # includes compile
        compile_epoch_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        net.fit(it, epochs=2, stepsPerDispatch=k)
        dt = (time.perf_counter() - t0) / (2 * n_batches)
        _emit({"stepsPerDispatch": k, "ms_per_batch": round(dt * 1e3, 2),
               "img_s": round(b / dt, 0),
               "first_epoch_s": round(compile_epoch_s, 1)})


def mode_bert():
    """BERT-base fine-tune MFU vs batch at seq 128 (the baseline row is
    b32; larger batches fill the MXU rows better — informational)."""
    from bench import _bench_bert_finetune, bert_mfu_pct

    for batch in (32, 64, 128):
        try:
            steps_s, dt, compile_s, tokens = _bench_bert_finetune(
                batch=batch, steps=10, warmup=2)
            mfu = bert_mfu_pct(steps_s, tokens)
            _emit({"batch": batch, "steps_s": round(steps_s, 2),
                   "step_ms": round(dt * 1e3, 1),
                   "tokens_s": round(steps_s * tokens, 0),
                   "mfu_pct": round(mfu, 1),
                   "compile_s": round(compile_s, 1)})
        except Exception as e:  # noqa: BLE001
            _emit({"batch": batch, "error": str(e)[:200]})


def mode_longseq():
    """Long-context attention on chip: masked Pallas flash vs dense at
    growing sequence length (the seq-parallel/ring story's single-chip
    leg). Dense is expected to OOM/blow up first; flash should scale."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.kernels import flash_attention

    b, h, d = 4, 8, 64
    for seq in (2048, 4096, 8192, 16384):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seq), 3)
        q = jax.random.normal(kq, (b, h, seq, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, h, seq, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, h, seq, d), jnp.bfloat16)
        mask = (jnp.arange(seq)[None, :]
                < jnp.asarray([seq] * (b - 1) + [seq // 2])[:, None]
                ).astype(jnp.int32)
        row = {"seq": seq}

        def timed(fn, *args):
            def loss(*a):
                return jnp.sum(fn(*a).astype(jnp.float32) ** 2)
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            t0 = time.perf_counter()
            out = g(*args)
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                out = g(*args)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / reps * 1e3, compile_s

        try:
            ms, cs = timed(
                lambda q, k, v: flash_attention(q, k, v, mask=mask), q, k, v)
            row["flash_fwdbwd_ms"] = round(ms, 1)
            row["flash_compile_s"] = round(cs, 1)
        except Exception as e:  # noqa: BLE001
            row["flash_error"] = str(e)[:120]

        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / (d ** 0.5)
            s = jnp.where(mask[:, None, None, :] > 0, s, -1e30)
            return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                              v.astype(jnp.float32))

        if seq <= 8192:
            try:
                ms, cs = timed(dense, q, k, v)
                row["dense_fwdbwd_ms"] = round(ms, 1)
            except Exception as e:  # noqa: BLE001
                row["dense_error"] = str(e)[:120]
        else:
            row["dense_skipped"] = "O(seq^2) scores would exceed HBM"
        _emit(row)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "smoke"
    _arm_deadline(mode)
    # without this every exp run recompiles every kernel from scratch
    # (observed: back-to-back smoke runs paid identical compile time)
    from deeplearning4j_tpu.util.hostkey import enable_compile_cache
    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.perf_counter()
    try:
        {"probe": mode_probe, "smoke": mode_smoke, "lstm": mode_lstm,
         "resnet": mode_resnet, "spd": mode_spd,
         "longseq": mode_longseq, "bert": mode_bert,
         "clustering": mode_clustering}[mode]()
    except Exception as e:  # noqa: BLE001
        _emit({"mode": mode, "error": f"{type(e).__name__}: {e}"[:400]})
        os._exit(1)
    _emit({"mode": mode, "total_s": round(time.perf_counter() - t0, 1)})
    os._exit(0)




def mode_clustering():
    """Session-4 informational numbers: the new clustering stack ON CHIP.
    KMeans (one jitted Lloyd while_loop) and exact t-SNE at sizes where
    the reference's CPU implementations take minutes."""
    import numpy as np
    import time as _t

    from deeplearning4j_tpu.clustering import BarnesHutTsne, KMeansClustering
    from deeplearning4j_tpu.clustering.vptree import knn

    rng = np.random.RandomState(0)

    # KMeans: 200k points x 64 dims, k=100 — the (N, K) GEMM rides the MXU
    x = rng.randn(200_000, 64).astype(np.float32)
    kmc = KMeansClustering.setup(100, maxIterationCount=30)
    t0 = _t.perf_counter()
    cs = kmc.applyTo(x)
    t_km = _t.perf_counter() - t0
    _emit({"kmeans_points": 200_000, "dims": 64, "k": 100, "iters_max": 30,
           "wall_s": round(t_km, 2),
           "nonempty": sum(1 for c in cs.getClusters() if c.getPoints())})

    # batched exact kNN: 1k queries over 200k corpus
    t0 = _t.perf_counter()
    idx, dist = knn(x[:1000], x, 10)
    t_knn = _t.perf_counter() - t0
    _emit({"knn_queries": 1000, "corpus": 200_000, "k": 10,
           "wall_s": round(t_knn, 2), "self_hit": bool((idx[:, 0] ==
                                                        np.arange(1000)).all())})

    # exact t-SNE: 5k points (the Barnes-Hut regime) — one jitted descent
    xt = rng.randn(5000, 32).astype(np.float32)
    t0 = _t.perf_counter()
    emb = (BarnesHutTsne.Builder().setMaxIter(500).perplexity(30)
           .seed(0).build().fit(xt).getData())
    t_ts = _t.perf_counter() - t0
    _emit({"tsne_points": 5000, "dims": 32, "iters": 500,
           "wall_s": round(t_ts, 2),
           "finite": bool(np.isfinite(emb).all())})


if __name__ == "__main__":
    main()
