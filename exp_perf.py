"""Perf experiment matrix for the ResNet-50 bench step (dev tool).

Runs the bench core under several configurations and prints one line per
config: fused-BN default, batch sweep, XLA flag variants. Use when the
TPU is reachable:  python exp_perf.py [configs...]
"""
import os
import subprocess
import sys
import time

CONFIGS = {
    "base": {},
    "b128": {"BENCH_BATCH": "128"},
    "b384": {"BENCH_BATCH": "384"},
    "b512": {"BENCH_BATCH": "512"},
    "lhs": {"LIBTPU_INIT_ARGS": "--xla_tpu_enable_latency_hiding_scheduler=true"},
    "flags1": {"LIBTPU_INIT_ARGS":
               "--xla_tpu_aggressive_opt_barrier_removal=ENABLED"},
    # NOTE: --xla_tpu_scoped_vmem_limit_kib configs were removed: on this
    # environment's remote-compile service they hang the compiler past any
    # reasonable timeout (2026-07-30) — and the bench's own deadline is the
    # only thing standing between that hang and a wedged tunnel.
}


def run_one(name, env_extra):
    env = dict(os.environ)
    env.pop("BENCH_CHILD", None)  # an inherited '1' would re-enable the
    env.update(env_extra)         # in-process SIGKILL-wedge path
    # NEVER set BENCH_CHILD here: running the measurement in-process and
    # SIGKILLing it on timeout leaves the TPU tunnel's grant held and
    # wedges the chip for hours (observed 2026-07-30, vmem-flag sweep).
    # Go through bench.py's parent, which owns a kill-able child and a
    # HARD deadline shorter than our subprocess timeout, so the bench
    # process always exits cleanly on its own.
    env.setdefault("BENCH_STEPS", "20")
    env["BENCH_EXTRA"] = ""      # headline only
    # FORCE-set (not setdefault): an inherited larger deadline would let
    # the subprocess timeout fire first — the SIGKILL-mid-claim wedge
    env["BENCH_ATTEMPTS"] = "1"
    # stay ABOVE the remote compile service's ~500 s own timeout: a
    # killpg below it can land mid-compile-RPC and wedge the tunnel
    env["BENCH_ATTEMPT_TIMEOUT"] = "560"
    env["BENCH_DEADLINE"] = "580"
    t0 = time.time()
    bench = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench.py")
    p = subprocess.run([sys.executable, bench], capture_output=True,
                       text=True, timeout=700, env=env)
    line = next((l for l in p.stdout.splitlines() if l.startswith("{")), "")
    print(f"{name:8s} {line}  [{time.time()-t0:.0f}s]", flush=True)
    for l in p.stderr.splitlines():
        if l.startswith("#"):
            print(f"         {l}", flush=True)


if __name__ == "__main__":
    picks = sys.argv[1:] or list(CONFIGS)
    for n in picks:
        try:
            run_one(n, CONFIGS[n])
        except Exception as e:  # noqa: BLE001 — keep sweeping
            print(f"{n:8s} FAILED: {e}", flush=True)
