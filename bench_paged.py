#!/usr/bin/env python
"""CPU microbench: paged KV cache vs slot-contiguous serving capacity
at EQUAL HBM (generation/ — ISSUE 18), one JSON artifact.

The claim under measurement is the paged-attention capacity argument:
a slot-contiguous server must reserve `rung x slots` KV rows up front
(every slot pays for the longest supportable request), while the paged
server allocates fixed-size pages only for rows a sequence actually
uses — so on a ragged-length request mix the same HBM holds several
times more concurrent sequences. Both arms here get EXACTLY the same
KV HBM budget and the same max-length support (rung 64 = bert-tiny's
position ceiling):

- **dense arm** — 4 slots x rung 64 = 256 contiguous KV rows.
- **paged arm** — a 32-page pool of 8 rows each = 256 KV rows (one
  page is the NULL write-sink, so 248 are allocatable — the paged arm
  runs slightly UNDER the dense budget), 24 slots reading through the
  per-slot page table.

Workload: a ragged mix of 48 greedy requests sharing a 16-token system
prefix (2 full pages, deduped by the prefix registry) with 0-3
divergent tail tokens and 4-6 token budgets — every request needs
<= 24 KV rows, so a dense slot wastes >= 40 of its 64 reserved rows
while the paged arm pays ~1 private page past the shared prefix.

Methodology is bench.py's median-of->=5-windows + recorded-spread
(VERDICT r4: a point sample of a +-20%-noise distribution is not a
measurement); one window = serve the full 48-request mix, with a
watcher thread sampling the live slot occupancy for the peak.

Headline `value` = peak concurrent sequences (paged) / dense slots at
equal HBM — acceptance >= 4.0. The artifact also carries the
prefix-dedup bytes-saved ledger (pages_reused x cache_page_bytes, fp
AND int8 page costs — int8 pages halve again on top of paging) and the
cross-arm token-identity verdict: the paged streams must equal the
dense streams token for token (greedy streams are a pure function of
the prompt, so they must survive the layout change AND the different
slot count bit-exactly). `scripts/check_bench_regression.py` gates
successive BENCH_PAGED_* artifacts on the headline via its `paths`
knob (MULTIHOST_r01 precedent — a 6x capacity ratio must never
compete with img/s headlines in the default BENCH_* trajectory).

Run:  JAX_PLATFORMS=cpu python bench_paged.py
"""
import argparse
import json
import os
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np

# bench.py is import-safe (no device init at module scope) — share THE
# windowing helper instead of copying it, so the methodology cannot
# drift between benches
from bench import _median_of_windows

from deeplearning4j_tpu.generation import BertDecoder, GenerationServer
from deeplearning4j_tpu.models.bert import bert_tiny, init_bert_params
from deeplearning4j_tpu.quantize.kvcache import cache_page_bytes

RUNG = 64            # bert-tiny position ceiling: both arms support it
PBUCKET = 24
PAGE_SIZE = 8
DENSE_SLOTS = 4
POOL_PAGES = 32      # 32 pages x 8 rows == 4 slots x 64 rows
PAGED_SLOTS = 24
N_REQUESTS = 48
SYS_PREFIX = list(range(1, 17))   # 16 tokens = 2 full shared pages


def _request_mix():
    """48 ragged greedy requests over 6 prompt variants: the shared
    system prefix plus 0-3 divergent tail tokens, budgets 4-6, every
    request's prompt+generation <= 24 rows (3 pages)."""
    variants = [
        (SYS_PREFIX, 6),
        (SYS_PREFIX + [21], 5),
        (SYS_PREFIX + [22, 23], 6),
        (SYS_PREFIX + [24], 4),
        (SYS_PREFIX + [25, 26, 27], 5),
        (SYS_PREFIX + [28, 29], 4),
    ]
    mix = [variants[i % len(variants)] for i in range(N_REQUESTS)]
    assert all(len(p) + n <= PBUCKET for p, n in mix)
    return mix


def _serve_mix(srv, mix):
    """One timed window: submit the whole mix, sample live slot
    occupancy from a watcher thread, consume every stream. Returns
    (streams, tokens_per_sec, peak_concurrent)."""
    peak = [0]
    done = threading.Event()

    def watch():
        while not done.is_set():
            peak[0] = max(peak[0], len(srv._slot_req))
            time.sleep(0.001)

    w = threading.Thread(target=watch)
    w.start()
    t0 = time.perf_counter()
    reqs = [srv.submit(list(p), max_new_tokens=n) for p, n in mix]
    streams = [r.result(timeout=300) for r in reqs]
    dt = time.perf_counter() - t0
    done.set()
    w.join()
    toks = sum(len(s) for s in streams)
    return streams, toks / dt, peak[0]


def _run_arm(srv, mix, k_windows=5):
    """Median tokens/s over independent windows; window 0's streams
    and the max peak across windows ride along."""
    state = {"streams": None, "peak": 0}

    def window(i):
        streams, rate, peak = _serve_mix(srv, mix)
        if i == 0:
            state["streams"] = streams
        state["peak"] = max(state["peak"], peak)
        return rate

    rate, vals, spread = _median_of_windows(window, k=k_windows)
    return {"rate": rate, "windows": [round(v, 1) for v in vals],
            "spread_pct": round(spread * 100, 1),
            "streams": state["streams"], "peak": state["peak"]}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_PAGED_fresh.json")
    ap.add_argument("--windows", type=int, default=5)
    args = ap.parse_args(argv)

    cfg = bert_tiny()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    mix = _request_mix()
    row_bytes = 2 * cfg.num_layers * cfg.num_heads * cfg.head_dim * 4
    dense_bytes = DENSE_SLOTS * RUNG * row_bytes
    page_fp = cache_page_bytes(cfg.num_layers, cfg.num_heads, PAGE_SIZE,
                               cfg.head_dim)
    page_i8 = cache_page_bytes(cfg.num_layers, cfg.num_heads, PAGE_SIZE,
                               cfg.head_dim, kv_dtype="int8")
    paged_bytes = POOL_PAGES * page_fp
    assert paged_bytes == dense_bytes, (paged_bytes, dense_bytes)

    print(f"# dense arm: {DENSE_SLOTS} slots x rung {RUNG} "
          f"({dense_bytes} KV bytes)")
    dense_srv = GenerationServer(
        BertDecoder(cfg, params), slots=DENSE_SLOTS,
        cache_lengths=[RUNG], prompt_buckets=[PBUCKET],
        method="greedy", seed=0)
    dense_srv.warmup()
    try:
        dense = _run_arm(dense_srv, mix, k_windows=args.windows)
    finally:
        dense_srv.shutdown()
    print(f"# dense: {dense['rate']:.1f} tok/s, "
          f"peak {dense['peak']} concurrent")

    print(f"# paged arm: {PAGED_SLOTS} slots over a {POOL_PAGES}-page "
          f"pool ({paged_bytes} KV bytes)")
    paged_srv = GenerationServer(
        BertDecoder(cfg, params, page_size=PAGE_SIZE,
                    pool_pages=POOL_PAGES),
        slots=PAGED_SLOTS, cache_lengths=[RUNG],
        prompt_buckets=[PBUCKET], method="greedy", seed=0)
    paged_srv.warmup()
    try:
        paged = _run_arm(paged_srv, mix, k_windows=args.windows)
        pool = {**paged_srv._pages.occupancy(), **paged_srv._pages.stats}
    finally:
        paged_srv.shutdown()
    print(f"# paged: {paged['rate']:.1f} tok/s, "
          f"peak {paged['peak']} concurrent, "
          f"{pool['prefix_hits']} prefix hits")

    identical = dense["streams"] == paged["streams"]
    assert identical, "paged streams diverged from dense streams"
    value = round(paged["peak"] / DENSE_SLOTS, 2)

    doc = {
        "model": "bert_tiny",
        "rung": RUNG,
        "prompt_bucket": PBUCKET,
        "page_size": PAGE_SIZE,
        "requests": N_REQUESTS,
        "shared_prefix_tokens": len(SYS_PREFIX),
        "dense": {"slots": DENSE_SLOTS, "kv_bytes": dense_bytes,
                  "tok_per_s": round(dense["rate"], 1),
                  "windows": dense["windows"],
                  "spread_pct": dense["spread_pct"],
                  "peak_concurrent": dense["peak"]},
        "paged": {"slots": PAGED_SLOTS, "pool_pages": POOL_PAGES,
                  "kv_bytes": paged_bytes,
                  "tok_per_s": round(paged["rate"], 1),
                  "windows": paged["windows"],
                  "spread_pct": paged["spread_pct"],
                  "peak_concurrent": paged["peak"],
                  "pool": pool},
        "prefix_dedup": {
            "prefix_hits": pool["prefix_hits"],
            "pages_reused": pool["pages_reused"],
            "cow_copies": pool["cow_copies"],
            "page_bytes_fp": page_fp,
            "page_bytes_int8": page_i8,
            "bytes_saved": pool["pages_reused"] * page_fp,
        },
        "token_identity": {"requests": N_REQUESTS,
                           "identical": identical},
        "value": value,
        "metric": "paged_concurrent_seqs_vs_dense_equal_hbm",
        "unit": "x",
        "provenance": {"host": "cpu", "jax": jax.__version__,
                       "windows": args.windows},
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"# headline: {value}x concurrent sequences at equal HBM "
          f"-> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
