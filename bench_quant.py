"""Quantization + recompute microbench (CPU, synthetic): the
memory-traffic diet's acceptance numbers.

Two arms, one JSON line (same harness idiom as bench_serving.py /
bench_generation.py):

1. **int8 inference vs fp** on a pointwise-conv-heavy residual model
   (the shape ROADMAP item 3 targets: stacks of 1×1 conv + BN + relu
   with residual shortcuts — every conv is a GEMM, every byte between
   them is traffic). The fp arm is the repo's standard inference
   forward (lax.conv per layer, BN as its own layer) compiled to one
   executable; the int8 arm is `quantize_network`'s rewrite — int8
   weights/boundary activations, BN folded into GEMM epilogues, and
   the cache-resident chain executor. Target: >= 1.5x throughput.

2. **selective recompute** on the same ResNet-style blocks:
   rematPolicy("blocks") must cut the saved-for-backward activation
   bytes >= 30% (quantize/traffic.py ledger + the compiled step's own
   memory analysis where available) with gradients EQUAL to the
   un-rematted step.

Run:  JAX_PLATFORMS=cpu python bench_quant.py
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

# keep the bench honest on shared boxes: one process, default threads
os.environ.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS",
                                                      "cpu"))

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _build_pointwise_resnet(wide, narrow, blocks, hw, seed=0):
    """ResNet-style bottleneck bodies made of the ops this PR diets:
    1×1 conv (wide→narrow) + BN/relu, 1×1 conv (narrow→wide) + BN,
    residual add, relu — the exact shape of ResNet-50's res-stage 1×1
    pairs, which is where BENCH_r04 located the HBM-bound traffic."""
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                                   BatchNormalization,
                                                   ConvolutionLayer,
                                                   GlobalPoolingLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.updaters import Sgd

    def build(remat="none"):
        b = (NeuralNetConfiguration.Builder().seed(seed)
             .updater(Sgd(0.05)).weightInit("relu").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(hw, hw, wide)))
        if remat != "none":
            b.rematPolicy(remat)
        x = "input"
        for i in range(blocks):
            b.addLayer(f"r{i}_c1", ConvolutionLayer(
                kernelSize=(1, 1), nOut=narrow, convolutionMode="same",
                hasBias=False, activation="identity"), x)
            b.addLayer(f"r{i}_bn1",
                       BatchNormalization(activation="relu"), f"r{i}_c1")
            b.addLayer(f"r{i}_c2", ConvolutionLayer(
                kernelSize=(1, 1), nOut=wide, convolutionMode="same",
                hasBias=False, activation="identity"), f"r{i}_bn1")
            b.addLayer(f"r{i}_bn2",
                       BatchNormalization(activation="identity"),
                       f"r{i}_c2")
            b.addVertex(f"r{i}_add", ElementWiseVertex("add"),
                        f"r{i}_bn2", x)
            b.addLayer(f"r{i}_relu",
                       ActivationLayer(activation="relu"), f"r{i}_add")
            x = f"r{i}_relu"
        b.addLayer("pool", GlobalPoolingLayer(poolingType="avg"), x)
        b.addLayer("out", OutputLayer(lossFunction="mcxent", nOut=10,
                                      activation="softmax"), "pool")
        b.setOutputs("out")
        return ComputationGraph(b.build()).init()
    return build


def _interleaved_medians(run_a, run_b, k=7, steps=3):
    """Median seconds/dispatch for two arms, measured INTERLEAVED
    (a-window, b-window, a-window, ...) so shared-box load drift hits
    both arms equally — single-window numbers here swing ±20%."""
    va, vb = [], []
    for _ in range(k):
        for run, vals in ((run_a, va), (run_b, vb)):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = run()
            jax.block_until_ready(out)
            vals.append((time.perf_counter() - t0) / steps)
    return (statistics.median(va), [round(v * 1e3, 1) for v in va],
            statistics.median(vb), [round(v * 1e3, 1) for v in vb])


def bench_int8(wide=64, narrow=16, blocks=8, hw=28, batch=64):
    from deeplearning4j_tpu.quantize import quantize_network
    from deeplearning4j_tpu.runtime.executables import forward_fn

    build = _build_pointwise_resnet(wide, narrow, blocks, hw)
    net = build()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, hw, hw, wide)).astype(np.float32)
    xd = jnp.asarray(x)

    fp_fwd = jax.jit(forward_fn(net))
    fp_args = (net._params, net._state, xd)
    jax.block_until_ready(fp_fwd(*fp_args))

    qnet = quantize_network(net, data=[x])
    q_fwd = jax.jit(forward_fn(qnet))
    q_args = (qnet._params, qnet._state, xd)
    jax.block_until_ready(q_fwd(*q_args))

    fp_dt, fp_windows, q_dt, q_windows = _interleaved_medians(
        lambda: fp_fwd(*fp_args), lambda: q_fwd(*q_args))

    fp_out = np.asarray(fp_fwd(*fp_args)[0])
    q_out = np.asarray(q_fwd(*q_args)[0])
    agreement = float((fp_out.argmax(-1) == q_out.argmax(-1)).mean())

    return {
        "model": (f"bottleneck-resnet {wide}/{narrow} x{blocks}blocks "
                  f"{hw}x{hw} batch{batch}"),
        "fp_ms": round(fp_dt * 1e3, 1),
        "int8_ms": round(q_dt * 1e3, 1),
        "fp_windows_ms": fp_windows,
        "int8_windows_ms": q_windows,
        "int8_vs_fp_throughput": round(fp_dt / q_dt, 2),
        "fp_img_s": round(batch / fp_dt, 1),
        "int8_img_s": round(batch / q_dt, 1),
        "top1_agreement": agreement,
        "quant_stats": {k: v for k, v in qnet._quant_stats.items()
                        if k != "scales"},
    }


def bench_remat(wide=64, narrow=16, blocks=8, hw=28, batch=32):
    from deeplearning4j_tpu.quantize.traffic import activation_report

    build = _build_pointwise_resnet(wide, narrow, blocks, hw)
    plain = build("none")
    remat = build("blocks")
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((batch, hw, hw, wide)),
                    jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, batch)])
    ins = {"input": x}
    labels = [y]
    key = jax.random.PRNGKey(7)

    def grads(net):
        g, _ = jax.grad(
            lambda p: net._loss(p, net._state, ins, labels, None, None,
                                key), has_aux=True)(net._params)
        return g

    gp = grads(plain)
    gr = grads(remat)
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), gp, gr)
    max_grad_diff = max(jax.tree_util.tree_leaves(diffs) or [0.0])
    # "matching": recompute replays the same math but XLA may fuse the
    # replayed segment differently than the saved forward, so f32
    # reassociation jitter up to ~1e-4 is expected — allclose per leaf,
    # not bitwise (the tier-1 fixture pins a tighter bound on a small
    # block where fusion orders coincide)
    close = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.allclose(a, b, rtol=1e-3, atol=1e-4)),
        gp, gr)
    grads_match = all(jax.tree_util.tree_leaves(close))

    rep_plain = activation_report(plain, batch)
    rep_remat = activation_report(remat, batch)
    saved_plain = rep_plain["saved_bytes"]
    saved_remat = rep_remat["saved_bytes"]
    reduction = 1.0 - saved_remat / saved_plain if saved_plain else 0.0

    out = {
        "model": (f"bottleneck-resnet {wide}/{narrow} x{blocks}blocks "
                  f"{hw}x{hw} batch{batch}"),
        "saved_activation_bytes_plain": saved_plain,
        "saved_activation_bytes_remat": saved_remat,
        "saved_bytes_reduction_pct": round(reduction * 100, 1),
        "max_grad_diff": max_grad_diff,
        "grads_equal": grads_match,
    }
    # secondary evidence: the compiled backward's OWN temp-buffer peak
    # (XLA memory analysis; best-effort — not all backends report it)
    try:
        def step(net):
            return jax.jit(lambda p: jax.grad(
                lambda pp: net._loss(pp, net._state, ins, labels, None,
                                     None, key)[0])(p)) \
                .lower(net._params).compile()
        mp = step(plain).memory_analysis()
        mr = step(remat).memory_analysis()
        out["xla_temp_bytes_plain"] = int(mp.temp_size_in_bytes)
        out["xla_temp_bytes_remat"] = int(mr.temp_size_in_bytes)
        out["xla_temp_reduction_pct"] = round(
            (1 - mr.temp_size_in_bytes / mp.temp_size_in_bytes) * 100, 1)
        out["xla_note"] = (
            "XLA:CPU temp is total scratch under aggressive buffer "
            "reuse, not the saved-activation watermark — the "
            "policy-relative ledger above is the acceptance number; "
            "this field is advisory")
    except Exception as e:  # noqa: BLE001 — advisory field only
        out["xla_memory_analysis"] = f"unavailable: {str(e)[:120]}"
    return out


def main():
    t0 = time.perf_counter()
    result = {"metric": "quant_microbench", "unit": "ratio"}
    int8 = bench_int8()
    remat = bench_remat()
    result.update({
        "value": int8["int8_vs_fp_throughput"],
        "target": ">= 1.5x int8 throughput; >= 30% saved-bytes cut",
        "int8": int8,
        "remat": remat,
        "seconds": round(time.perf_counter() - t0, 1),
    })
    print(f"# int8 {int8['int8_vs_fp_throughput']}x "
          f"({int8['fp_ms']}ms -> {int8['int8_ms']}ms), "
          f"remat -{remat['saved_bytes_reduction_pct']}% saved bytes, "
          f"grads_equal={remat['grads_equal']}", file=sys.stderr,
          flush=True)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
