"""Experiment: measure step-time impact of a custom-VJP fused BN vs the
autodiff BN, and a conv-only (no-BN) ceiling. Dev tool, not shipped."""
import functools
import os

os.environ.setdefault("DL4J_TPU_WANT_TPU", "1")  # TPU dev tool: explicit chip opt-in
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.util.hostkey import cache_dir

jax.config.update("jax_compilation_cache_dir",
                  cache_dir(os.path.dirname(os.path.abspath(__file__))))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# ---- fused custom-VJP batch norm -----------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_train(x, gamma, beta, eps):
    y, _ = _bn_fwd(x, gamma, beta, eps)
    return y


def _stats(x):
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    s1 = jnp.mean(xf, axes)
    s2 = jnp.mean(xf * xf, axes)
    var = jnp.maximum(s2 - s1 * s1, 0.0)
    return s1, var


def _bn_fwd(x, gamma, beta, eps):
    mu, var = _stats(x)
    r = lax.rsqrt(var + eps)
    a = (gamma * r).astype(x.dtype)
    b = (beta - gamma * mu * r).astype(x.dtype)
    y = x * a + b
    return y, (x, mu, r, gamma)


def _bn_bwd(eps, res, dy):
    x, mu, r, gamma = res
    axes = tuple(range(x.ndim - 1))
    n = 1
    for d in axes:
        n *= x.shape[d]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mu) * r
    dbeta = jnp.sum(dyf, axes)
    dgamma = jnp.sum(dyf * xhat, axes)
    # dx = gamma*r*(dy - (xhat*dgamma + dbeta)/n)  — per-channel constants
    # folded so the elementwise pass reads only (x, dy) and writes dx
    k1 = (gamma * r).astype(x.dtype)
    k2 = (gamma * r * r * dgamma / n).astype(x.dtype)   # multiplies (x - mu)
    c = (gamma * r * (dbeta / n)).astype(x.dtype)
    mu_b = mu.astype(x.dtype)
    dx = k1 * dy - (x - mu_b) * k2 - c
    return dx, dgamma, dbeta


bn_train.defvjp(lambda x, g, b, eps: _bn_fwd(x, g, b, eps), _bn_bwd)


def run(mode, batch=256, steps=20):
    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.nn.updaters import Nesterovs
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.nn.activations import get_activation

    if mode == "fusedbn":
        def apply(self, params, state, x, train=False, rng=None, mask=None):
            if train:
                mu, var = _stats(x)
                new_state = {
                    "mean": self.decay * state["mean"] + (1 - self.decay) * mu,
                    "var": self.decay * state["var"] + (1 - self.decay) * var}
                g = params.get("gamma", jnp.ones_like(state["mean"]))
                b = params.get("beta", jnp.zeros_like(state["mean"]))
                y = bn_train(x, g, b, self.eps)
            else:
                mu, var = state["mean"], state["var"]
                new_state = state
                r = lax.rsqrt(var + self.eps)
                g = params.get("gamma", jnp.ones_like(mu))
                b = params.get("beta", jnp.zeros_like(mu))
                y = x * (g * r).astype(x.dtype) + (b - g * mu * r).astype(x.dtype)
            return get_activation(self.activation)(y), new_state
        L.BatchNormalization.apply = apply
    elif mode == "nobn":
        def apply(self, params, state, x, train=False, rng=None, mask=None):
            g = params.get("gamma", 1.0)
            b = params.get("beta", 0.0)
            y = x * jnp.asarray(g, x.dtype) + jnp.asarray(b, x.dtype)
            return get_activation(self.activation)(y), state
        L.BatchNormalization.apply = apply

    model = ResNet50(numClasses=1000, dataType="bfloat16",
                     inputShape=(224, 224, 3), updater=Nesterovs(0.1, 0.9))
    net = model.init()
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, 224, 224, 3), jnp.float32)
    y = jax.nn.one_hot(jax.random.randint(ky, (batch,), 0, 1000), 1000,
                       dtype=jnp.float32)
    ins = {"input": x}
    labs = [y]
    step = net._train_step
    params, opt, state = net._params, net._opt_state, net._state
    rng = jax.random.PRNGKey(1)
    for i in range(3):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, state, loss = step(params, opt, state, ins, labs, None,
                                        None, jax.random.fold_in(rng, 100 + i))
    fl = float(loss)
    dt = (time.perf_counter() - t0) / steps
    print(f"{mode}: step={dt*1000:.1f}ms {batch/dt:.1f} img/s loss={fl:.3f}")


if __name__ == "__main__":
    run(sys.argv[1] if len(sys.argv) > 1 else "baseline")
