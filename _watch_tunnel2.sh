#!/bin/bash
# Final round-5 window: probe until ~03:00 UTC only — a heal later than
# that is the DRIVER's bench to claim (never two TPU consumers).
cd /root/repo
for i in $(seq 1 5); do
  date -u +"probe2 %H:%M:%S"
  if timeout 130 python _probe.py 2>&1 | grep -q "PROBE devices"; then
    echo "TUNNEL HEALTHY at $(date -u) — launching campaign"
    exec /root/repo/_campaign.sh
  fi
  sleep 780
done
echo "final window closed; leaving the tunnel to the driver"
