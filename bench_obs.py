"""Observability disabled-path microbench (CPU): the ISSUE 15 guard,
re-armed for every observability PR since (the ISSUE 19 ops event
journal's emission hooks ride the same enabled-guard and the same two
workloads below).

Request tracing, the cluster metrics plane, and SLO tracking must be
FREE when off — every instrumentation point this PR adds is one
`timeline is None` / enabled-guard branch on the hot path. This bench
proves it empirically, the same way the fastpath lint proves it
structurally: an interleaved A/B between the current tree and a
baseline checkout WITHOUT the observability changes, monitoring
disabled in both arms, on the two hot paths the PR touches:

- **fit50** — the 50-step training fit (the PR 4 guard workload);
- **decode_k8** — steady-state greedy decode at superstep k=8
  (the generation hot path the request timelines ride).

Windows alternate base/head (base, head, base, head, ...) so
shared-box load drift hits both arms equally — single-window numbers
on this class of box swing ±20%. The verdict is "within noise": the
relative delta must not exceed the measured window spread.

Run:  JAX_PLATFORMS=cpu python bench_obs.py [--ref <git-ref>]

`--ref` (default `DL4J_OBS_BASE_REF` or HEAD) names the baseline
commit; with the PR uncommitted in the working tree, HEAD *is* the
pre-observability baseline. After it lands, pass the parent commit.
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.abspath(__file__))

WINDOWS = int(os.environ.get("DL4J_OBS_BENCH_WINDOWS", "5"))


# ===================== child workloads =================================
def _child_fit50():
    """Median seconds for 50 fit steps (tiny MLP), monitoring off."""
    import numpy as np
    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       NeuralNetConfiguration,
                                       OutputLayer, Sgd)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Sgd(0.1)).activation("relu")
            .list()
            .layer(DenseLayer.Builder().nOut(256).build())
            .layer(DenseLayer.Builder().nOut(256).build())
            .layer(OutputLayer.Builder("mcxent").nOut(4)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(64))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((128, 64)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 128)]
    ds = DataSet(x, y)
    for _ in range(5):                      # warmup: compile + caches
        net.fit(ds)
    vals = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(50):
            net.fit(ds)
        vals.append(time.perf_counter() - t0)
    return statistics.median(vals)


def _child_decode_k8():
    """Median seconds for a 192-token greedy decode at superstep k=8,
    monitoring off; executables come from a per-tree disk store so only
    the first window of each arm pays compiles."""
    from deeplearning4j_tpu.generation import GenerationServer
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.recurrent import (LSTM,
                                                      RnnOutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    V = 16
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
         .weightInit("xavier").list()
         .layer(LSTM(nOut=64, activation="tanh"))
         .layer(RnnOutputLayer(lossFunction="mcxent", nOut=V,
                               activation="softmax"))
         .setInputType(InputType.recurrent(V)).build())).init()
    srv = GenerationServer(net, slots=2, cache_lengths=[256],
                           prompt_buckets=[8], method="greedy", seed=11,
                           superstep=8,
                           exec_cache_dir=os.environ.get(
                               "DL4J_OBS_EXEC_CACHE"))
    try:
        srv.warmup()
        srv.generate([1, 4, 2], max_new_tokens=32, timeout=120)
        vals = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(8):
                toks = srv.generate([5, 6, 1], max_new_tokens=240,
                                    timeout=120)
                assert len(toks) == 240
            vals.append(time.perf_counter() - t0)
        return statistics.median(vals)
    finally:
        srv.shutdown()


CHILD_WORKLOADS = {"fit50": _child_fit50, "decode_k8": _child_decode_k8}


def _run_child(workload, tree, exec_cache):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = tree
    env["DL4J_OBS_EXEC_CACHE"] = exec_cache
    # share the persistent XLA compile cache across windows of one arm
    env.setdefault("DL4J_COMPILE_CACHE",
                   os.path.join(exec_cache, "xla"))
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", workload],
        env=env, cwd=tempfile.gettempdir(), capture_output=True,
        text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"child {workload} failed in {tree}:\n"
                           f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
    return float(out.stdout.strip().splitlines()[-1])


def _checkout_base(ref, dst):
    """Materialize the baseline package tree at `ref` into dst."""
    os.makedirs(dst, exist_ok=True)
    tar = subprocess.run(["git", "-C", REPO, "archive", ref,
                          "deeplearning4j_tpu"],
                         capture_output=True, timeout=120)
    if tar.returncode != 0:
        raise RuntimeError(tar.stderr.decode()[-500:])
    subprocess.run(["tar", "-x", "-C", dst], input=tar.stdout,
                   check=True, timeout=120)
    return dst


def _spread(vals):
    m = statistics.median(vals)
    return (max(vals) - min(vals)) / m if m else 0.0


def run(ref):
    results = {"metric": "observability disabled-path overhead",
               "base_ref": ref, "windows": WINDOWS}
    with tempfile.TemporaryDirectory(prefix="dl4j-obs-bench-") as tmp:
        base_tree = _checkout_base(ref, os.path.join(tmp, "base"))
        caches = {"base": os.path.join(tmp, "cache-base"),
                  "head": os.path.join(tmp, "cache-head")}
        for c in caches.values():
            os.makedirs(c, exist_ok=True)
        trees = {"base": base_tree, "head": REPO}
        for workload in ("fit50", "decode_k8"):
            vals = {"base": [], "head": []}
            for i in range(WINDOWS):
                # alternate which arm goes first so slow drift within
                # a round cancels too
                order = ("base", "head") if i % 2 == 0 \
                    else ("head", "base")
                for arm in order:
                    vals[arm].append(_run_child(workload, trees[arm],
                                                caches[arm]))
            base_med = statistics.median(vals["base"])
            head_med = statistics.median(vals["head"])
            delta = (head_med - base_med) / base_med
            noise = max(_spread(vals["base"]), _spread(vals["head"]),
                        0.02)
            results[workload] = {
                "base_s": round(base_med, 4),
                "head_s": round(head_med, 4),
                "base_windows_s": [round(v, 4) for v in vals["base"]],
                "head_windows_s": [round(v, 4) for v in vals["head"]],
                "delta": round(delta, 4),
                "window_spread": round(noise, 4),
                "within_noise": abs(delta) <= noise,
            }
    results["pass"] = all(results[w]["within_noise"]
                          for w in ("fit50", "decode_k8"))
    return results


def main(argv):
    if len(argv) >= 2 and argv[0] == "--child":
        fn = CHILD_WORKLOADS[argv[1]]
        print(fn())
        return 0
    ref = os.environ.get("DL4J_OBS_BASE_REF", "HEAD")
    if len(argv) >= 2 and argv[0] == "--ref":
        ref = argv[1]
    results = run(ref)
    print(json.dumps(results, indent=2))
    return 0 if results["pass"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
