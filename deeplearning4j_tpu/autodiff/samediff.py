"""SameDiff-equivalent autodiff graph engine (≡ nd4j-api ::
autodiff.samediff.SameDiff / SDVariable).

The reference builds an op graph, differentiates it symbolically, and
executes op-by-op on the CUDA executioner. Here the graph records ops as
composable pure functions; `output()`/`fit()` trace the WHOLE graph into a
single jitted XLA executable (the "compile SameDiff graphs whole into one
XLA executable" north-star line in BASELINE.json), and gradients come from
`jax.grad` of that executable rather than symbolic graph surgery.

Variable kinds mirror the reference: PLACEHOLDER (fed at exec), VARIABLE
(trainable), CONSTANT.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.updaters import Updater, build_optimizer
from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax


class VariableType:
    PLACEHOLDER = "placeholder"
    VARIABLE = "variable"
    CONSTANT = "constant"
    ARRAY = "array"  # op outputs


class SDVariable:
    def __init__(self, sd, name, vtype, shape=None, fn=None, inputs=()):
        self.sd = sd
        self.name = name
        self.vtype = vtype
        self.shape = shape
        self.fn = fn                    # for ARRAY nodes: f(*input_arrays)
        self.inputs = list(inputs)      # parent variable names

    # -- fluent math (mirrors SDVariable's operator surface) -------------
    def _bin(self, other, opname):
        other = self.sd._lift(other)
        return self.sd._op(opname, None, self, other, params={})

    def add(self, o):
        return self._bin(o, "add")

    def sub(self, o):
        return self._bin(o, "sub")

    def mul(self, o):
        return self._bin(o, "mul")

    def div(self, o):
        return self._bin(o, "div")

    def rsub(self, o):
        return self.sd._lift(o)._bin(self, "sub")

    def rdiv(self, o):
        return self.sd._lift(o)._bin(self, "div")

    def mmul(self, o):
        return self._bin(o, "mmul")

    def pow(self, p):
        return self.sd._op("pow", None, self, params={"p": float(p)})

    def neg(self):
        return self.sd._op("neg", None, self, params={})

    def transpose(self, *axes):
        ax = list(axes) if axes else None
        return self.sd._op("transpose", None, self, params={"axes": ax})

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", None, self,
                           params={"shape": [int(s) for s in shape]})

    def _reduce(self, opname, dims, keepdims):
        ax = None
        if dims:
            ax = int(dims[0]) if len(dims) == 1 else [int(d) for d in dims]
        return self.sd._op(opname, None, self,
                           params={"axis": ax, "keepdims": bool(keepdims)})

    def sum(self, *dims, keepdims=False):
        return self._reduce("sum", dims, keepdims)

    def mean(self, *dims, keepdims=False):
        return self._reduce("mean", dims, keepdims)

    def max(self, *dims, keepdims=False):
        return self._reduce("max", dims, keepdims)

    def min(self, *dims, keepdims=False):
        return self._reduce("min", dims, keepdims)

    def std(self, *dims, keepdims=False):
        return self._reduce("std", dims, keepdims)

    def argmax(self, dim=-1):
        return self.sd._op("argmax", None, self, params={"dim": int(dim)})

    # python operators
    __add__ = add
    __radd__ = add
    __sub__ = sub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __matmul__ = mmul

    def __rsub__(self, o):
        return self.rsub(o)

    def __rtruediv__(self, o):
        return self.rdiv(o)

    def __neg__(self):
        return self.neg()

    def __pow__(self, p):
        return self.pow(p)

    def rename(self, new_name):
        return self.sd.rename(self.name, new_name)

    def eval(self, placeholders=None):
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def getArr(self):
        if self.vtype in (VariableType.VARIABLE, VariableType.CONSTANT):
            return NDArray(self.sd._values[self.name])
        return self.eval()

    def setArray(self, arr):
        self.sd._values[self.name] = as_jax(arr)
        self.sd._invalidate()

    def __repr__(self):
        return f"SDVariable(name={self.name!r}, type={self.vtype})"


class _MathNamespace:
    def __init__(self, sd):
        self.sd = sd

    def _u(self, opname, x, params=None):
        return self.sd._op(opname, None, self.sd._lift(x),
                           params=params or {})

    def exp(self, x):
        return self._u("exp", x)

    def log(self, x):
        return self._u("log", x)

    def sqrt(self, x):
        return self._u("sqrt", x)

    def square(self, x):
        return self._u("square", x)

    def abs(self, x):
        return self._u("abs", x)

    def sin(self, x):
        return self._u("sin", x)

    def cos(self, x):
        return self._u("cos", x)

    def tanh(self, x):
        return self._u("tanh", x)

    def sigmoid(self, x):
        return self._u("sigmoid", x)

    def clip(self, x, lo, hi):
        # open bounds (None or ±inf) travel as null: the artifact is
        # strict JSON (allow_nan=False), so ±inf must not reach params
        return self._u("clip", x, {
            "lo": None if lo is None or lo == -np.inf else float(lo),
            "hi": None if hi is None or hi == np.inf else float(hi)})


class _NNNamespace:
    def __init__(self, sd):
        self.sd = sd

    def relu(self, x):
        return self.sd._op("relu", None, self.sd._lift(x), params={})

    def gelu(self, x):
        return self.sd._op("gelu", None, self.sd._lift(x), params={})

    def softmax(self, x, axis=-1):
        return self.sd._op("softmax", None, self.sd._lift(x),
                           params={"axis": int(axis)})

    def logSoftmax(self, x, axis=-1):
        return self.sd._op("log_softmax", None, self.sd._lift(x),
                           params={"axis": int(axis)})

    def tanh(self, x):
        return self.sd._op("tanh", None, self.sd._lift(x), params={})

    def sigmoid(self, x):
        return self.sd._op("sigmoid", None, self.sd._lift(x), params={})

    def dropout(self, x, keep_prob):
        # inference identity; train-time dropout arrives via fit rngs
        return self.sd._op("dropout_id", None, self.sd._lift(x), params={})

    def linear(self, input, weights, bias=None):
        if bias is None:
            return input.mmul(weights)
        return input.mmul(weights).add(bias)

    def layerNorm(self, x, gain, bias=None, eps=1e-5, axis=-1):
        x, gain = self.sd._lift(x), self.sd._lift(gain)
        ins = (x, gain) + ((self.sd._lift(bias),) if bias is not None else ())
        return self.sd._op("layer_norm", None, *ins,
                           params={"eps": float(eps), "axis": int(axis)})

    def batchNorm(self, x, mean, var, gamma, beta, eps=1e-5):
        return self.sd._op("batch_norm", None,
                           *(self.sd._lift(v) for v in
                             (x, mean, var, gamma, beta)),
                           params={"eps": float(eps)})


class _LossNamespace:
    def __init__(self, sd):
        self.sd = sd

    def softmaxCrossEntropy(self, name, labels, logits):
        labels, logits = self.sd._lift(labels), self.sd._lift(logits)
        return self.sd._op_named(name, "softmax_xent", None, labels, logits,
                                 params={})

    def sigmoidCrossEntropy(self, name, labels, logits):
        labels, logits = self.sd._lift(labels), self.sd._lift(logits)
        return self.sd._op_named(name, "sigmoid_xent", None, labels, logits,
                                 params={})

    def meanSquaredError(self, name, labels, predictions):
        labels, predictions = self.sd._lift(labels), self.sd._lift(predictions)
        return self.sd._op_named(name, "mse", None, labels, predictions,
                                 params={})

    def l2Loss(self, name, x):
        return self.sd._op_named(name, "l2", None, self.sd._lift(x),
                                 params={})


def _pair2(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


class _CNNNamespace:
    """≡ SameDiff.cnn() — conv/pool ops over NHWC (the reference is NCHW;
    layouts invert like the rest of the rebuild)."""

    def __init__(self, sd):
        self.sd = sd

    def conv2d(self, x, weights, bias=None, stride=(1, 1), padding="SAME",
               dilation=(1, 1)):
        """x (B,H,W,Cin), weights (kh,kw,Cin,Cout) HWIO."""
        x = self.sd._lift(x)
        weights = self.sd._lift(weights)
        params = {"stride": list(_pair2(stride)),
                  "padding": padding if isinstance(padding, str)
                  else [list(p) for p in padding],
                  "dilation": list(_pair2(dilation))}
        ins = (x, weights) if bias is None else (x, weights,
                                                self.sd._lift(bias))
        return self.sd._op("conv2d", None, *ins, params=params)

    def maxPooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
        return self.sd._op("maxpool2d", None, self.sd._lift(x),
                           params={"kernel": list(_pair2(kernel)),
                                   "stride": list(_pair2(stride)),
                                   "padding": padding})

    def avgPooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID"):
        # divides by the TRUE window population so SAME padding zeros
        # don't dilute edge averages (TF/Keras/reference semantics)
        return self.sd._op("avgpool2d", None, self.sd._lift(x),
                           params={"kernel": list(_pair2(kernel)),
                                   "stride": list(_pair2(stride)),
                                   "padding": padding})

    def upsampling2d(self, x, scale=2):
        return self.sd._op("upsampling2d", None, self.sd._lift(x),
                           params={"scale": int(scale)})


class _LinalgNamespace:
    """≡ SameDiff.linalg() — jnp.linalg-backed decompositions."""

    def __init__(self, sd):
        self.sd = sd

    def mmul(self, a, b):
        return self.sd._lift(a).mmul(self.sd._lift(b))

    def cholesky(self, x):
        return self.sd._op("cholesky", None, self.sd._lift(x), params={})

    def qr(self, x):
        return self.sd._op("qr", None, self.sd._lift(x), params={})

    def svd(self, x):
        """Singular values (the reference's Svd op surface)."""
        return self.sd._op("svd", None, self.sd._lift(x), params={})

    def solve(self, a, b):
        return self.sd._op("solve", None, self.sd._lift(a),
                           self.sd._lift(b), params={})


class _RandomNamespace:
    """≡ SameDiff.random() — sampling ops. FUNCTIONAL-JAX SEMANTICS: each
    op node draws from a key fixed at construction (seeded by the graph's
    deterministic RNG), so repeated eval() of the same node returns the
    SAME array — reproducible by design, unlike the reference's
    resample-per-execution ops. Create a new op (or a fresh graph seed)
    for a fresh draw; stochastic TRAINING noise belongs to the dropout
    machinery, which rekeys per step."""

    def __init__(self, sd):
        self.sd = sd

    def _draw(self, opname, shape, extra):
        seed = int(self.sd._rng.integers(0, 2 ** 31 - 1))
        params = {"seed": seed, "shape": [int(s) for s in shape], **extra}
        return self.sd._op(opname, None, params=params)

    def normal(self, mean, stddev, *shape):
        return self._draw("random_normal", shape,
                          {"mean": float(mean), "stddev": float(stddev)})

    def uniform(self, lo, hi, *shape):
        return self._draw("random_uniform", shape,
                          {"lo": float(lo), "hi": float(hi)})

    def bernoulli(self, p, *shape):
        return self._draw("random_bernoulli", shape, {"p": float(p)})


class TrainingConfig:
    """≡ org.nd4j.autodiff.samediff.TrainingConfig.Builder."""

    def __init__(self, updater=None, l1=0.0, l2=0.0,
                 dataSetFeatureMapping=None, dataSetLabelMapping=None):
        self.updater = updater
        self.l1 = float(l1)
        self.l2 = float(l2)
        self.dataSetFeatureMapping = dataSetFeatureMapping or []
        self.dataSetLabelMapping = dataSetLabelMapping or []

    class Builder:
        def __init__(self):
            self._kw = {}

        def updater(self, u):
            self._kw["updater"] = u
            return self

        def l1(self, v):
            self._kw["l1"] = v
            return self

        def l2(self, v):
            self._kw["l2"] = v
            return self

        def dataSetFeatureMapping(self, *names):
            self._kw["dataSetFeatureMapping"] = list(names)
            return self

        def dataSetLabelMapping(self, *names):
            self._kw["dataSetLabelMapping"] = list(names)
            return self

        def build(self):
            return TrainingConfig(**self._kw)


class SameDiff:
    def __init__(self):
        self._nodes = {}      # name -> SDVariable
        self._values = {}     # VARIABLE/CONSTANT name -> jnp array
        self._counter = 0
        self._loss_names = []
        self._training_config = None
        self._opt_state = None
        self._tx = None
        self._rng = np.random.default_rng(0)
        self._exec_cache = {}
        self.math = _MathNamespace(self)
        self.nn = _NNNamespace(self)
        self.loss = _LossNamespace(self)
        self.cnn = _CNNNamespace(self)
        self.linalg = _LinalgNamespace(self)
        self.random = _RandomNamespace(self)

    @staticmethod
    def create():
        return SameDiff()

    def summary(self):
        """≡ SameDiff.summary(): table of variables (name, kind, shape)
        and op nodes (name, op, inputs)."""
        lines = ["--- SameDiff summary ---",
                 f"{'Name':<24} {'Kind':<12} {'Shape/Op':<20} Inputs"]
        n_vars = n_ops = 0
        for name, v in self._nodes.items():
            if v.vtype == VariableType.ARRAY:
                n_ops += 1
                op = getattr(v, "opname", None) or (
                    v.fn.__name__ if v.fn is not None else "?")
                if op in ("<lambda>", "?"):
                    op = name.rsplit("_", 1)[0]  # node names carry the op
                lines.append(f"{name:<24} {'op':<12} {op:<20} "
                             f"{', '.join(v.inputs)}")
            else:
                n_vars += 1
                shape = tuple(v.shape) if v.shape is not None else "?"
                val = self._values.get(name)
                if val is not None:
                    shape = tuple(val.shape)
                lines.append(f"{name:<24} {v.vtype:<12} {str(shape):<20}")
        lines.append(f"--- {n_vars} variables, {n_ops} ops, "
                     f"losses: {self._loss_names or '[]'} ---")
        return "\n".join(lines)

    def _invalidate(self):
        self._exec_cache = {}

    # -- variable creation ----------------------------------------------
    def _fresh(self, base):
        self._counter += 1
        return f"{base}_{self._counter}"

    def placeHolder(self, name, *shape, dtype=None):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        v = SDVariable(self, name, VariableType.PLACEHOLDER, shape)
        self._nodes[name] = v
        return v

    def var(self, name, init=None, shape=None):
        """Trainable variable: init can be an array or a shape tuple (then
        xavier-initialized)."""
        if init is None and shape is not None:
            init = shape
        if isinstance(init, (tuple, list)) and all(
                isinstance(i, (int, np.integer)) for i in init):
            fan_in = init[0] if len(init) > 1 else 1
            arr = (self._rng.standard_normal(tuple(init))
                   * np.sqrt(1.0 / max(1, fan_in))).astype(np.float32)
        else:
            arr = np.asarray(as_jax(init))
        v = SDVariable(self, name, VariableType.VARIABLE,
                       tuple(arr.shape))
        self._nodes[name] = v
        self._values[name] = jnp.asarray(arr)
        self._invalidate()
        return v

    def constant(self, name, value=None):
        if value is None:
            name, value = self._fresh("const"), name
        arr = as_jax(value)
        v = SDVariable(self, name, VariableType.CONSTANT, tuple(arr.shape))
        self._nodes[name] = v
        self._values[name] = arr
        self._invalidate()
        return v

    def _lift(self, x):
        if isinstance(x, SDVariable):
            return x
        return self.constant(self._fresh("lit"), x)

    # -- op recording ----------------------------------------------------
    def _op(self, opname, fn, *inputs, params=None):
        return self._op_named(self._fresh(opname), opname, fn, *inputs,
                              params=params)

    def _op_named(self, name, opname, fn, *inputs, params=None):
        """Record one op node. fn=None (the serializable form) builds the
        fn from graph_serde.OP_BUILDERS[opname](**params) — opname+params
        then fully describe the node, and save() can persist it. An
        explicit fn (control flow, ad-hoc callables) executes fine but
        marks the node non-serializable."""
        serializable = fn is None
        if fn is None:
            from deeplearning4j_tpu.autodiff.graph_serde import build_fn
            fn = build_fn(opname, params)
        v = SDVariable(self, name, VariableType.ARRAY, None, fn,
                       [i.name for i in inputs])
        v.opname = opname
        v.params = params
        v.serializable = serializable
        self._nodes[name] = v
        self._invalidate()
        return v

    def rename(self, old, new):
        v = self._nodes.pop(old)
        v.name = new
        self._nodes[new] = v
        for node in self._nodes.values():
            node.inputs = [new if i == old else i for i in node.inputs]
        if old in self._values:
            self._values[new] = self._values.pop(old)
        if old in self._loss_names:
            self._loss_names = [new if n == old else n for n in self._loss_names]
        self._invalidate()
        return v

    def getVariable(self, name):
        return self._nodes[name]

    def variables(self):
        return [v for v in self._nodes.values()
                if v.vtype == VariableType.VARIABLE]

    # -- execution -------------------------------------------------------
    def _topo(self, targets):
        order, seen = [], set()

        def visit(name):
            if name in seen:
                return
            seen.add(name)
            for p in self._nodes[name].inputs:
                visit(p)
            order.append(name)

        for t in targets:
            visit(t)
        return order

    def _make_exec(self, out_names):
        """Build one pure function (values, placeholders) -> outputs dict,
        jit-compiled: the whole graph is a single XLA executable."""
        order = self._topo(out_names)
        nodes = {n: self._nodes[n] for n in order}

        def run(values, placeholders):
            env = {}
            for n in order:
                node = nodes[n]
                if node.vtype == VariableType.PLACEHOLDER:
                    env[n] = placeholders[n]
                elif node.vtype in (VariableType.VARIABLE, VariableType.CONSTANT):
                    env[n] = values[n]
                else:
                    env[n] = node.fn(*(env[i] for i in node.inputs))
            return {n: env[n] for n in out_names}

        return run

    def output(self, placeholders, outputs):
        """≡ SameDiff.output(Map, String...) — returns dict name->NDArray."""
        if isinstance(outputs, str):
            outputs = [outputs]
        key = tuple(outputs)
        if key not in self._exec_cache:
            self._exec_cache[key] = jax.jit(self._make_exec(key))
        phs = {k: as_jax(v) for k, v in (placeholders or {}).items()}
        res = self._exec_cache[key](self._values, phs)
        return {k: NDArray(v) for k, v in res.items()}

    def outputSingle(self, placeholders, output):
        return self.output(placeholders, [output])[output]

    def evaluate(self, iterator, outputVariable, evaluation=None,
                 labelIndex=None):
        """≡ SameDiff.evaluate(DataSetIterator, outputVariable,
        Evaluation): feed each DataSet through the TrainingConfig's
        dataSetFeatureMapping and accumulate predictions vs labels.

        Multi-output graphs (≡ SameDiff.evaluate(iterator,
        variableEvals, predictionLabelMapping)): pass a DICT
        {outputVariable: IEvaluation} — each variable scores against the
        label array at `labelIndex[var]` (defaults to the variable's
        position in the dict). All outputs come from ONE forward per
        batch. Returns the dict."""
        tc = self._training_config
        if tc is None or not getattr(tc, "dataSetFeatureMapping", None):
            raise ValueError(
                "evaluate() needs a TrainingConfig with "
                "dataSetFeatureMapping/dataSetLabelMapping (call "
                "setTrainingConfig first)")
        if isinstance(outputVariable, dict):
            var_evals = dict(outputVariable)
            label_idx = {v: (labelIndex or {}).get(v, i)
                         for i, v in enumerate(var_evals)}
        else:
            if evaluation is None:
                from deeplearning4j_tpu.eval.evaluation import Evaluation
                evaluation = Evaluation()
            var_evals = {outputVariable: evaluation}
            label_idx = {outputVariable: 0}
        if hasattr(iterator, "reset"):
            iterator.reset()
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        out_names = list(var_evals)
        for ds in iterator:
            feats = ds.features if isinstance(ds, MultiDataSet) \
                else [ds.features]
            labs = ds.labels if isinstance(ds, MultiDataSet) \
                else [ds.labels]
            if len(feats) != len(tc.dataSetFeatureMapping):
                raise ValueError(
                    f"evaluate(): {len(feats)} feature arrays vs "
                    f"{len(tc.dataSetFeatureMapping)} mapped placeholders")
            phs = dict(zip(tc.dataSetFeatureMapping, feats))
            preds = self.output(phs, out_names)
            masks = getattr(ds, "labelsMask",
                            getattr(ds, "labelsMasks", None))
            if not isinstance(masks, (list, tuple)):
                masks = [masks] * len(labs)
            for var, ev in var_evals.items():
                li = label_idx[var]
                if li >= len(labs):
                    raise ValueError(
                        f"evaluate(): output '{var}' maps to label index "
                        f"{li} but the DataSet has {len(labs)} label "
                        "arrays")
                ev.eval(labs[li], preds[var],
                        masks[li] if li < len(masks) else None)
        return (var_evals if isinstance(outputVariable, dict)
                else var_evals[outputVariable])

    def batchOutput(self):
        sd = self

        class _B:
            def __init__(self):
                self._phs, self._outs = {}, []

            def input(self, name, arr):
                self._phs[name] = arr
                return self

            def output(self, *names):
                self._outs.extend(names)
                return self

            def outputSingle(self):
                return sd.output(self._phs, self._outs)[self._outs[0]]

            def exec(self):
                return sd.output(self._phs, self._outs)

        return _B()

    # -- training --------------------------------------------------------
    def setLossVariables(self, *names):
        self._loss_names = [n.name if isinstance(n, SDVariable) else n
                            for n in names]

    def convertConstantsToVariables(self, *names):
        """≡ SameDiff.convertToVariables — promote imported constants to
        trainable variables (the imported-model fine-tune path)."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            node = self._nodes[n]
            if node.vtype == VariableType.CONSTANT:
                node.vtype = VariableType.VARIABLE
        self._tx = None  # optimizer state must re-init over the new set
        self._invalidate()
        return self

    def convertVariablesToConstants(self, *names):
        """≡ SameDiff.convertToConstants — freeze variables."""
        for n in names:
            n = n.name if isinstance(n, SDVariable) else n
            node = self._nodes[n]
            if node.vtype == VariableType.VARIABLE:
                node.vtype = VariableType.CONSTANT
        self._tx = None
        self._invalidate()
        return self

    def setTrainingConfig(self, tc):
        self._training_config = tc
        self._tx = None

    # -- control flow (≡ SameDiff control-flow ops: If/While/For — lowered
    # to lax.cond / lax.while_loop / lax.scan so the compiled graph stays
    # ONE XLA executable with structured control flow, no unrolling) -----
    def ifCond(self, name, pred, inputs, true_fn, false_fn):
        """pred: scalar SDVariable; true_fn/false_fn: plain jnp functions
        taking the input ARRAYS and returning one array. Lowered to
        lax.cond (both branches traced, compiler picks at runtime)."""
        inputs = [self._lift(v) for v in inputs]

        def f(p, *arrs):
            return jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                                lambda a: true_fn(*a),
                                lambda a: false_fn(*a), arrs)

        return self._op_named(name, "if", f, self._lift(pred), *inputs)

    def whileLoop(self, name, loop_vars, cond_fn, body_fn):
        """loop_vars: list of SDVariables (initial state). cond_fn/body_fn:
        jnp functions over the state arrays; body returns the new state
        tuple. Returns one SDVariable per state slot (final values)."""
        loop_vars = [self._lift(v) for v in loop_vars]
        n = len(loop_vars)

        def f(*arrs):
            return jax.lax.while_loop(lambda vs: cond_fn(*vs),
                                      lambda vs: tuple(body_fn(*vs)),
                                      tuple(arrs))

        tup = self._op_named(f"{name}/state", "while", f, *loop_vars)
        return [self._op_named(f"{name}/out{i}", "tuple_get",
                               (lambda i_: lambda t: t[i_])(i), tup)
                for i in range(n)]

    def scanLoop(self, name, init, xs, body_fn):
        """lax.scan surface: body_fn(carry, x) -> (carry, y). Returns
        (final_carry, stacked_ys) SDVariables."""
        init = self._lift(init)
        xs = self._lift(xs)

        def f(c0, xs_arr):
            return jax.lax.scan(body_fn, c0, xs_arr)

        tup = self._op_named(f"{name}/state", "scan", f, init, xs)
        carry = self._op_named(f"{name}/carry", "tuple_get",
                               lambda t: t[0], tup)
        ys = self._op_named(f"{name}/ys", "tuple_get", lambda t: t[1], tup)
        return carry, ys

    def forLoop(self, name, n_iters, loop_vars, body_fn):
        """Fixed-trip-count loop via lax.fori_loop."""
        loop_vars = [self._lift(v) for v in loop_vars]
        n = len(loop_vars)

        def f(*arrs):
            return jax.lax.fori_loop(
                0, int(n_iters),
                lambda i, vs: tuple(body_fn(i, *vs)), tuple(arrs))

        tup = self._op_named(f"{name}/state", "for", f, *loop_vars)
        return [self._op_named(f"{name}/out{i}", "tuple_get",
                               (lambda i_: lambda t: t[i_])(i), tup)
                for i in range(n)]

    # -- SERIALIZABLE control flow (round-5, ≡ the reference FlatBuffers
    # form: If/While bodies persist as nested sub-graphs). Branch/body
    # logic is expressed as SameDiff GRAPHS whose placeholders are fed by
    # this graph's tensors — the whole thing saves/loads like any other
    # op because the sub-graphs travel inline in the node's params. The
    # plain-callable forms above stay for ad-hoc use (documented
    # non-serializable). ---------------------------------------------
    def _graph_params(self, sub):
        from deeplearning4j_tpu.autodiff.graph_serde import graph_doc
        bad = [n for n, v in sub._nodes.items()
               if v.vtype == VariableType.ARRAY
               and not getattr(v, "serializable", False)]
        if bad:
            raise ValueError(
                f"control-flow sub-graph contains non-serializable ops "
                f"{bad[:5]} — sub-graphs must use registry ops only "
                "(the point of the *Graph control-flow forms)")
        return graph_doc(sub, inline_values=True)

    def ifCondGraph(self, name, pred, inputs, input_names, true_sd,
                    false_sd, output):
        """lax.cond with SameDiff sub-graph branches: `inputs` (this
        graph's SDVariables) feed both branches' placeholders
        `input_names`; each branch computes node `output`."""
        inputs = [self._lift(v) for v in inputs]
        return self._op_named(name, "samediff.if", None, self._lift(pred),
                              *inputs, params={
                                  "true_graph": self._graph_params(true_sd),
                                  "false_graph":
                                      self._graph_params(false_sd),
                                  "input_names": list(input_names),
                                  "output": output})

    def whileLoopGraph(self, name, loop_vars, state_names, cond_sd,
                       cond_out, body_sd, body_outs):
        """lax.while_loop with sub-graph condition/body: state slots
        `state_names` feed both graphs' placeholders; cond computes the
        scalar `cond_out`, body computes one node per slot (`body_outs`).
        Returns one SDVariable per final state slot."""
        loop_vars = [self._lift(v) for v in loop_vars]
        tup = self._op_named(f"{name}/state", "samediff.while", None,
                             *loop_vars, params={
                                 "cond_graph": self._graph_params(cond_sd),
                                 "body_graph": self._graph_params(body_sd),
                                 "state_names": list(state_names),
                                 "cond_out": cond_out,
                                 "body_outs": list(body_outs)})
        return [self._op_named(f"{name}/out{i}", "tuple_get", None, tup,
                               params={"i": i})
                for i in range(len(loop_vars))]

    def scanLoopGraph(self, name, init, xs, body_sd, carry_name, x_name,
                      carry_out, y_out):
        """lax.scan with a sub-graph body mapping placeholders
        (carry_name, x_name) to nodes (carry_out, y_out). Returns
        (final_carry, stacked_ys)."""
        init, xs = self._lift(init), self._lift(xs)
        tup = self._op_named(f"{name}/state", "samediff.scan", None, init,
                             xs, params={
                                 "body_graph": self._graph_params(body_sd),
                                 "carry_name": carry_name,
                                 "x_name": x_name,
                                 "carry_out": carry_out, "y_out": y_out})
        carry = self._op_named(f"{name}/carry", "tuple_get", None, tup,
                               params={"i": 0})
        ys = self._op_named(f"{name}/ys", "tuple_get", None, tup,
                            params={"i": 1})
        return carry, ys

    def forLoopGraph(self, name, n_iters, loop_vars, state_names, body_sd,
                     body_outs, index_name="i"):
        """lax.fori_loop with a sub-graph body; the iteration index rides
        in as placeholder `index_name` (int32 scalar)."""
        loop_vars = [self._lift(v) for v in loop_vars]
        tup = self._op_named(f"{name}/state", "samediff.for", None,
                             *loop_vars, params={
                                 "body_graph": self._graph_params(body_sd),
                                 "n_iters": int(n_iters),
                                 "index_name": index_name,
                                 "state_names": list(state_names),
                                 "body_outs": list(body_outs)})
        return [self._op_named(f"{name}/out{i}", "tuple_get", None, tup,
                               params={"i": i})
                for i in range(len(loop_vars))]


    def _total_loss(self, values, placeholders):
        runner = self._make_exec(tuple(self._loss_names))
        outs = runner(values, placeholders)
        total = 0.0
        for n in self._loss_names:
            total = total + jnp.sum(outs[n])
        tc = self._training_config
        if tc is not None and (tc.l1 or tc.l2):
            for v in self.variables():
                arr = values[v.name]
                if tc.l1:
                    total = total + tc.l1 * jnp.sum(jnp.abs(arr))
                if tc.l2:
                    total = total + 0.5 * tc.l2 * jnp.sum(arr * arr)
        return total

    def _ensure_optimizer(self):
        if self._tx is None:
            tc = self._training_config
            if tc is None or tc.updater is None:
                raise ValueError("setTrainingConfig with an updater before fit()")
            self._tx = (tc.updater.to_optax()
                        if isinstance(tc.updater, Updater) else tc.updater)
            var_names = [v.name for v in self.variables()]
            self._opt_state = self._tx.init(
                {n: self._values[n] for n in var_names})
            pending = getattr(self, "_pending_opt_leaves", None)
            if pending is not None:
                # save(save_updater=True) artifact: splice the persisted
                # optimizer-state leaves into the freshly built structure
                treedef = jax.tree_util.tree_structure(self._opt_state)
                if treedef.num_leaves != len(pending):
                    raise ValueError(
                        f"updater state in artifact has {len(pending)} "
                        f"leaves but this optimizer has "
                        f"{treedef.num_leaves} — was the training config "
                        "changed after load?")
                self._opt_state = jax.tree_util.tree_unflatten(
                    treedef, pending)
                self._pending_opt_leaves = None

    @functools.cached_property
    def _fit_step(self):
        tx_holder = self

        @jax.jit
        def step(var_values, const_values, opt_state, placeholders):
            values = {**const_values, **var_values}
            loss, grads = jax.value_and_grad(
                lambda vv: tx_holder._total_loss({**const_values, **vv},
                                                 placeholders))(var_values)
            updates, opt_state = tx_holder._tx.update(grads, opt_state,
                                                      var_values)
            var_values = optax.apply_updates(var_values, updates)
            return var_values, opt_state, loss

        return step

    def fit(self, dataset=None, labels=None, placeholders=None, epochs=1):
        """fit(DataSet) using TrainingConfig mappings, fit(features,
        labels) arrays through the same mappings, fit(placeholders=dict)
        feeding everything directly, or — ≡ SameDiff.fit(DataSetIterator,
        numEpochs) — fit(iterator, epochs=N): trains every batch of the
        iterator per epoch and returns the per-batch loss history (a
        plain list, ≡ the reference's History losscurve)."""
        if hasattr(dataset, "hasNext") and hasattr(dataset, "next"):
            history = []
            for _ in range(int(epochs)):
                dataset.reset()
                while dataset.hasNext():
                    history.append(self.fit(dataset.next()))
            return history
        self._ensure_optimizer()
        tc = self._training_config
        if isinstance(labels, dict):
            # fit(dataset, placeholders_dict) callers from the old
            # (dataset, placeholders) signature: a dict is never a labels
            # array — route it to placeholders.
            if placeholders is not None:
                raise TypeError(
                    "fit(): got a dict for `labels` AND `placeholders`; "
                    "pass placeholders once, as placeholders=")
            labels, placeholders = None, labels
        if labels is not None:
            from deeplearning4j_tpu.datasets.dataset import DataSet
            dataset = DataSet(dataset, labels)
        if placeholders is None:
            from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
            if isinstance(dataset, DataSet):
                feats, labs = [dataset.features], [dataset.labels]
            elif isinstance(dataset, MultiDataSet):
                feats, labs = dataset.features, dataset.labels
            else:
                raise TypeError(f"Cannot fit on {type(dataset)}")
            placeholders = {}
            for name, arr in zip(tc.dataSetFeatureMapping, feats):
                placeholders[name] = arr
            for name, arr in zip(tc.dataSetLabelMapping, labs):
                placeholders[name] = arr
        phs = {k: as_jax(v) for k, v in placeholders.items()}
        var_names = [v.name for v in self.variables()]
        var_values = {n: self._values[n] for n in var_names}
        const_values = {k: v for k, v in self._values.items()
                        if k not in var_values}
        var_values, self._opt_state, loss = self._fit_step(
            var_values, const_values, self._opt_state, phs)
        self._values.update(var_values)
        return float(loss)

    def calculateGradients(self, placeholders, *wrt):
        """≡ SameDiff.calculateGradients — gradients of the loss wrt the
        given variable names."""
        if not self._loss_names:
            raise ValueError("setLossVariables(...) first")
        wrt = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        phs = {k: as_jax(v) for k, v in (placeholders or {}).items()}
        var_values = {n: self._values[n] for n in
                      [v.name for v in self.variables()]}
        const_values = {k: v for k, v in self._values.items()
                        if k not in var_values}
        grads = jax.grad(
            lambda vv: self._total_loss({**const_values, **vv}, phs))(var_values)
        return {n: NDArray(grads[n]) for n in wrt}

    def grad(self, name):
        raise RuntimeError("Use calculateGradients(placeholders, names...)")

    # -- persistence (≡ SameDiff.save/load: the WHOLE graph — ops, shapes,
    # values — restores with no defining source; see graph_serde) --------
    def save(self, path, save_updater=False, values_only=False):
        """Write the self-contained zip artifact (samediff.json +
        values.npz). save_updater=True (≡ SameDiff.save's
        saveUpdaterState) also persists the optimizer-state leaves, so a
        loaded graph's fit() resumes mid-momentum bit-exactly.

        values_only=True writes just the values.npz leg — the persistence
        path for graphs containing non-serializable nodes (ad-hoc
        callables): re-build the graph in code and load_values()."""
        from deeplearning4j_tpu.autodiff.graph_serde import save_samediff
        save_samediff(self, path, values_only=values_only,
                      save_updater=save_updater)

    @staticmethod
    def load(path):
        """Rebuild the full graph from a save() artifact in a fresh
        process — no defining Python needed (op fns come from the
        graph_serde builder registry)."""
        from deeplearning4j_tpu.autodiff.graph_serde import load_samediff
        return load_samediff(path)

    def load_values(self, path):
        """Load ONLY the values from a save() artifact into THIS graph
        (the old partial-restore surface, kept for API compatibility;
        also reads values_only=True artifacts and legacy pre-r5 pickle
        checkpoints written by this module's old save()).

        Artifacts written with save_updater=True carry `__updater__N`
        optimizer-state leaves; those are restored too — spliced straight
        into a live optimizer, or parked in `_pending_opt_leaves` for
        `_ensure_optimizer` to consume on the first fit() — so a
        values-only checkpoint resumes mid-momentum instead of silently
        dropping the updater state (ADVICE r5, graph_serde.py:425)."""
        import io
        import zipfile

        if zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                vals = np.load(io.BytesIO(zf.read("values.npz")))
                values = {k: vals[k] for k in vals.files}
        else:
            with open(path, "rb") as f:
                magic = f.read(2)
            if not magic.startswith(b"\x80"):
                raise ValueError(
                    f"{path!r} is neither a samediff zip artifact nor a "
                    "legacy pickle checkpoint")
            # one-time migration path for checkpoints written by the
            # pre-round-5 pickle save(); new artifacts are pickle-free
            import pickle
            with open(path, "rb") as f:
                values = pickle.load(f)["values"]
        upd_prefix = "__updater__"
        upd_keys = sorted((k for k in values if k.startswith(upd_prefix)),
                          key=lambda k: int(k[len(upd_prefix):]))
        treedef = None
        if upd_keys and self._opt_state is not None:
            # validate BEFORE mutating anything: a mismatch must leave
            # the graph exactly as it was (values, caches, optimizer)
            treedef = jax.tree_util.tree_structure(self._opt_state)
            if treedef.num_leaves != len(upd_keys):
                raise ValueError(
                    f"updater state in artifact has {len(upd_keys)} "
                    f"leaves but this optimizer has "
                    f"{treedef.num_leaves} — was the training config "
                    "changed since the checkpoint?")
        for k, v in values.items():
            if k in self._values:
                self._values[k] = jnp.asarray(v)
        if upd_keys:
            leaves = [jnp.asarray(values[k]) for k in upd_keys]
            if treedef is not None:
                self._opt_state = jax.tree_util.tree_unflatten(treedef,
                                                               leaves)
            else:
                self._pending_opt_leaves = leaves
        self._invalidate()
        return self
