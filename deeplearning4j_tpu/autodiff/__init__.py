from deeplearning4j_tpu.autodiff.samediff import (SameDiff, SDVariable,
                                                  TrainingConfig,
                                                  VariableType)

__all__ = ["SameDiff", "SDVariable", "TrainingConfig", "VariableType"]
