from deeplearning4j_tpu.autodiff import tf_import  # registers importFrozenTF
from deeplearning4j_tpu.autodiff.tf_import import TFGraphMapper, importFrozenTF
from deeplearning4j_tpu.autodiff.onnx_import import (OnnxGraphMapper,
                                                     importOnnx)
from deeplearning4j_tpu.autodiff.samediff import (SameDiff, SDVariable,
                                                  TrainingConfig,
                                                  VariableType)

__all__ = ["SameDiff", "SDVariable", "TrainingConfig", "VariableType",
           "TFGraphMapper", "importFrozenTF", "OnnxGraphMapper",
           "importOnnx"]
