"""SameDiff FULL-GRAPH serialization (≡ nd4j-api ::
autodiff.samediff.SameDiff.save/load, which persists the whole graph —
ops, shapes, values — as FlatBuffers with no defining source required).

TPU-native form: every graph op is (opname, params) where `params` is a
plain-JSON dict, and this module's OP_BUILDERS registry maps opname ->
builder(**params) -> pure jax fn. A graph then serializes as a zip of

  samediff.json   — node table: {name, vtype, shape, opname, params,
                    inputs}, plus loss names / name counter / training
                    config (updater via util.serde's @class encoding)
  values.npz      — every VARIABLE/CONSTANT array, keyed by node name

and loads in a FRESH process with no user Python: builders are module
code, params are data. Pickle-free by construction (the reference's
FlatBuffers property). Custom user ops register a builder via
registerSerializableOp(opname, builder) — the same contract the
reference applies to custom-op import (builder must be registered in the
loading process too).

Control flow serializes through the *Graph forms (SameDiff.ifCondGraph /
whileLoopGraph / scanLoopGraph / forLoopGraph): branch/body logic is a
SameDiff SUB-graph whose doc travels inline in the node's params — the
same nested encoding the reference's FlatBuffers uses. The plain-callable
forms (ifCond/whileLoop/...) capture arbitrary USER Python and stay
documented non-serializable; save() raises an actionable error naming
them and pointing at the *Graph forms.
"""
from __future__ import annotations

import io
import json
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

GRAPH_JSON = "samediff.json"
VALUES_NPZ = "values.npz"
FORMAT_VERSION = 1

OP_BUILDERS = {}


def op_builder(opname):
    def deco(fn):
        OP_BUILDERS[opname] = fn
        return fn
    return deco


def registerSerializableOp(opname, builder):
    """Register a custom op builder: builder(**params) -> f(*input_arrays).
    Must run in the loading process too (module-level registration is the
    usual place) — params must be plain JSON values."""
    OP_BUILDERS[str(opname)] = builder


def build_fn(opname, params):
    b = OP_BUILDERS.get(opname)
    if b is None and opname.split(".")[0] in ("onnx", "tf"):
        # importer builders register at module import; pull the provider
        # in on demand (covers nested control-flow sub-graphs too)
        import importlib
        importlib.import_module(
            "deeplearning4j_tpu.autodiff."
            + {"onnx": "onnx_import", "tf": "tf_import"}[
                opname.split(".")[0]])
        b = OP_BUILDERS.get(opname)
    if b is None:
        raise KeyError(
            f"no builder registered for op {opname!r} — "
            "registerSerializableOp(opname, builder) first")
    return b(**(params or {}))


def _t(v):
    """JSON round-trips tuples as lists; jax APIs want tuples back."""
    return tuple(v) if isinstance(v, (list, tuple)) else v


def _pairs(p):
    """Padding: string ('SAME'/'VALID') or [[lo, hi], ...] pairs."""
    if isinstance(p, str):
        return p
    return [tuple(q) for q in p]


# -- elementwise / binary -------------------------------------------------
for _name, _fn in [
        ("add", jnp.add), ("sub", jnp.subtract), ("mul", jnp.multiply),
        ("div", jnp.divide), ("mmul", jnp.matmul), ("neg", jnp.negative),
        ("exp", jnp.exp), ("log", jnp.log), ("sqrt", jnp.sqrt),
        ("square", jnp.square), ("abs", jnp.abs), ("sin", jnp.sin),
        ("cos", jnp.cos), ("tanh", jnp.tanh), ("sigmoid", jax.nn.sigmoid),
        ("relu", jax.nn.relu), ("gelu", jax.nn.gelu),
        ("dropout_id", lambda a: a),
        ("cholesky", jnp.linalg.cholesky),
        ("qr", lambda a: jnp.linalg.qr(a)[0]),
        ("svd", lambda a: jnp.linalg.svd(a, compute_uv=False)),
        ("solve", jnp.linalg.solve)]:
    OP_BUILDERS[_name] = (lambda f: lambda: f)(_fn)


@op_builder("pow")
def _b_pow(p):
    return lambda a: jnp.power(a, p)


@op_builder("transpose")
def _b_transpose(axes=None):
    ax = _t(axes) if axes is not None else None
    return lambda a: jnp.transpose(a, ax)


@op_builder("reshape")
def _b_reshape(shape):
    return lambda a: jnp.reshape(a, _t(shape))


def _reduce_builder(fn):
    def build(axis=None, keepdims=False):
        ax = _t(axis) if isinstance(axis, (list, tuple)) else axis
        return lambda a: fn(a, axis=ax, keepdims=keepdims)
    return build


for _name, _fn in [("sum", jnp.sum), ("mean", jnp.mean), ("max", jnp.max),
                   ("min", jnp.min), ("std", jnp.std)]:
    OP_BUILDERS[_name] = _reduce_builder(_fn)


@op_builder("argmax")
def _b_argmax(dim=-1):
    return lambda a: jnp.argmax(a, axis=dim)


@op_builder("clip")
def _b_clip(lo, hi):
    l = -jnp.inf if lo is None else lo
    h = jnp.inf if hi is None else hi
    return lambda a: jnp.clip(a, l, h)


@op_builder("softmax")
def _b_softmax(axis=-1):
    return lambda a: jax.nn.softmax(a, axis=axis)


@op_builder("log_softmax")
def _b_log_softmax(axis=-1):
    return lambda a: jax.nn.log_softmax(a, axis=axis)


@op_builder("layer_norm")
def _b_layer_norm(eps=1e-5, axis=-1):
    def f(a, g, *b):
        mu = jnp.mean(a, axis=axis, keepdims=True)
        var = jnp.var(a, axis=axis, keepdims=True)
        y = (a - mu) * jax.lax.rsqrt(var + eps) * g
        return y + b[0] if b else y
    return f


@op_builder("batch_norm")
def _b_batch_norm(eps=1e-5):
    def f(a, m, v, g, b):
        return (a - m) * jax.lax.rsqrt(v + eps) * g + b
    return f


# -- losses ---------------------------------------------------------------
@op_builder("softmax_xent")
def _b_softmax_xent():
    def f(y, z):
        return -jnp.mean(jnp.sum(y * jax.nn.log_softmax(z, -1), -1))
    return f


@op_builder("sigmoid_xent")
def _b_sigmoid_xent():
    def f(y, z):
        per = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        return jnp.mean(jnp.sum(per, -1))
    return f


@op_builder("mse")
def _b_mse():
    def f(y, p):
        return jnp.mean((y - p) ** 2)
    return f


@op_builder("l2")
def _b_l2():
    return lambda a: 0.5 * jnp.sum(a * a)


# -- cnn ------------------------------------------------------------------
@op_builder("conv2d")
def _b_conv2d(stride=(1, 1), padding="SAME", dilation=(1, 1)):
    s, d, p = _t(stride), _t(dilation), _pairs(padding)

    def f(a, w, *b):
        y = jax.lax.conv_general_dilated(
            a, w, s, p, rhs_dilation=d,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + b[0] if b else y
    return f


@op_builder("maxpool2d")
def _b_maxpool2d(kernel=(2, 2), stride=(2, 2), padding="VALID"):
    k, s, p = _t(kernel), _t(stride), _pairs(padding)
    return lambda a: jax.lax.reduce_window(
        a, -jnp.inf, jax.lax.max, (1,) + k + (1,), (1,) + s + (1,), p)


@op_builder("avgpool2d")
def _b_avgpool2d(kernel=(2, 2), stride=(2, 2), padding="VALID"):
    k, s, p = _t(kernel), _t(stride), _pairs(padding)

    def f(a):
        dims, strides = (1,) + k + (1,), (1,) + s + (1,)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, p)
        counts = jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add,
                                       dims, strides, p)
        return summed / counts
    return f


@op_builder("upsampling2d")
def _b_upsampling2d(scale=2):
    s = int(scale)
    return lambda a: jnp.repeat(jnp.repeat(a, s, axis=1), s, axis=2)


# -- random (seed is a param: the draw stays reproducible across save/load)
@op_builder("random_normal")
def _b_random_normal(seed, shape, mean=0.0, stddev=1.0):
    return lambda: mean + stddev * jax.random.normal(
        jax.random.PRNGKey(seed), _t(shape))


@op_builder("random_uniform")
def _b_random_uniform(seed, shape, lo=0.0, hi=1.0):
    return lambda: jax.random.uniform(jax.random.PRNGKey(seed), _t(shape),
                                      minval=lo, maxval=hi)


@op_builder("random_bernoulli")
def _b_random_bernoulli(seed, shape, p=0.5):
    return lambda: jax.random.bernoulli(
        jax.random.PRNGKey(seed), p, _t(shape)).astype(jnp.float32)


# -- nested graph docs (serializable control flow rides on these) ---------
def graph_doc(sd, inline_values=False):
    """The JSON node table for a graph. inline_values=True embeds every
    VARIABLE/CONSTANT array as base64 (for SUB-graphs nested inside a
    control-flow node's params — the top-level artifact keeps values in
    the npz leg instead)."""
    import base64

    nodes = []
    for name, v in sd._nodes.items():
        nodes.append({
            "name": name,
            "vtype": v.vtype,
            "shape": list(v.shape) if v.shape is not None else None,
            "opname": getattr(v, "opname", None),
            "params": getattr(v, "params", None),
            "inputs": list(v.inputs),
        })
    doc = {"counter": sd._counter, "loss_names": list(sd._loss_names),
           "nodes": nodes}
    if inline_values:
        vals = {}
        for k, arr in sd._values.items():
            a = np.asarray(arr)
            # dtype.str keeps byte order ('<f4'): the inline leg must
            # stay endian-safe like the npz leg
            vals[k] = {"dtype": a.dtype.str, "shape": list(a.shape),
                       "b64": base64.b64encode(a.tobytes()).decode()}
        doc["values"] = vals
    return doc


def graph_from_doc(doc):
    """Rebuild a SameDiff from a graph_doc (values from the inline base64
    leg when present)."""
    import base64

    from deeplearning4j_tpu.autodiff.samediff import (SameDiff, SDVariable,
                                                      VariableType)

    sd = SameDiff()
    sd._counter = int(doc.get("counter", 0))
    sd._loss_names = list(doc.get("loss_names", []))
    for nd in doc["nodes"]:
        name, vtype = nd["name"], nd["vtype"]
        shape = tuple(nd["shape"]) if nd["shape"] is not None else None
        if vtype == VariableType.ARRAY:
            fn = build_fn(nd["opname"], nd.get("params"))
            v = SDVariable(sd, name, vtype, shape, fn, nd["inputs"])
            v.opname = nd["opname"]
            v.params = nd.get("params")
            v.serializable = True
        else:
            v = SDVariable(sd, name, vtype, shape)
        sd._nodes[name] = v
    for k, spec in (doc.get("values") or {}).items():
        arr = np.frombuffer(base64.b64decode(spec["b64"]),
                            np.dtype(spec["dtype"])).reshape(spec["shape"])
        sd._values[k] = jnp.asarray(arr)
    return sd


def _subgraph_runner(doc, out_names):
    """Compile a nested graph doc to fn(input_dict) -> {out: array}."""
    sub = graph_from_doc(doc)
    run = sub._make_exec(tuple(out_names))
    values = sub._values

    def call(placeholders):
        return run(values, placeholders)
    return call


# -- serializable control flow (≡ the reference's FlatBuffers form, where
# If/While bodies persist as nested sub-graphs). The *Graph control-flow
# API on SameDiff passes its branch/body GRAPHS here as inline docs.
@op_builder("samediff.if")
def _b_if(true_graph, false_graph, input_names, output):
    t = _subgraph_runner(true_graph, [output])
    f = _subgraph_runner(false_graph, [output])

    def fn(pred, *arrs):
        env = dict(zip(input_names, arrs))
        return jax.lax.cond(jnp.reshape(pred, ()).astype(bool),
                            lambda e: t(e)[output],
                            lambda e: f(e)[output], env)
    return fn


@op_builder("samediff.while")
def _b_while(cond_graph, body_graph, state_names, cond_out, body_outs):
    c = _subgraph_runner(cond_graph, [cond_out])
    b = _subgraph_runner(body_graph, list(body_outs))

    def fn(*arrs):
        def cond(vs):
            env = dict(zip(state_names, vs))
            return jnp.reshape(c(env)[cond_out], ()).astype(bool)

        def body(vs):
            env = dict(zip(state_names, vs))
            outs = b(env)
            return tuple(outs[o] for o in body_outs)
        return jax.lax.while_loop(cond, body, tuple(arrs))
    return fn


@op_builder("samediff.scan")
def _b_scan(body_graph, carry_name, x_name, carry_out, y_out):
    b = _subgraph_runner(body_graph, [carry_out, y_out])

    def fn(c0, xs):
        def body(c, x):
            outs = b({carry_name: c, x_name: x})
            return outs[carry_out], outs[y_out]
        return jax.lax.scan(body, c0, xs)
    return fn


@op_builder("samediff.for")
def _b_for(body_graph, n_iters, index_name, state_names, body_outs):
    b = _subgraph_runner(body_graph, list(body_outs))

    def fn(*arrs):
        def body(i, vs):
            env = dict(zip(state_names, vs))
            env[index_name] = jnp.asarray(i, jnp.int32)
            outs = b(env)
            return tuple(outs[o] for o in body_outs)
        return jax.lax.fori_loop(0, int(n_iters), body, tuple(arrs))
    return fn


@op_builder("tuple_get")
def _b_tuple_get(i):
    return lambda t: t[i]


@op_builder("slice_axis")
def _b_slice_axis(axis, start, size):
    """Shared slice-by-axis (ONNX Split / TF SplitV lower onto this);
    lax.slice_in_dim canonicalizes negative axes itself."""
    return lambda x, *_r: jax.lax.slice_in_dim(x, start, start + size,
                                               axis=axis)


# -- persistence ----------------------------------------------------------
def _opt_leaves(sd):
    """Optimizer-state leaves in tree_flatten order — live state if the
    optimizer ran, else the still-pending leaves a load() carried (so a
    load -> re-save repack keeps the momenta)."""
    if sd._opt_state is not None:
        return jax.tree_util.tree_leaves(sd._opt_state)
    return getattr(sd, "_pending_opt_leaves", None)


def save_samediff(sd, path, values_only=False, save_updater=False):
    """Write the zip artifact. Raises on non-serializable nodes (control
    flow, unregistered custom fns) with the node list in the message;
    values_only=True skips the graph leg entirely (checkpointing for
    graphs with such nodes — re-build in code, then load_values);
    save_updater=True (≡ the reference's saveUpdaterState flag) also
    persists the optimizer-state leaves so fit() resumes mid-momentum —
    in BOTH artifact forms: load_samediff restores them via
    doc["updater_state_leaves"], and SameDiff.load_values restores the
    `__updater__N` arrays from values-only checkpoints too."""
    from deeplearning4j_tpu.autodiff.samediff import VariableType
    from deeplearning4j_tpu.util.serde import encode

    if values_only:
        arrays = {k: np.asarray(v) for k, v in sd._values.items()}
        if save_updater:
            for i, leaf in enumerate(_opt_leaves(sd) or []):
                arrays[f"__updater__{i}"] = np.asarray(leaf)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(VALUES_NPZ, buf.getvalue())
        return

    bad = [(n, getattr(v, "opname", None)) for n, v in sd._nodes.items()
           if v.vtype == VariableType.ARRAY and not getattr(
               v, "serializable", False)]
    if bad:
        raise ValueError(
            "SameDiff.save: graph contains ops with no registered "
            f"builder: {bad[:8]}{'...' if len(bad) > 8 else ''} — "
            "ad-hoc callables (including the plain ifCond/whileLoop/"
            "scanLoop/forLoop forms) are not serializable. Options: "
            "rebuild control flow with the *Graph forms (ifCondGraph/"
            "whileLoopGraph/scanLoopGraph/forLoopGraph — sub-graphs "
            "serialize inline), registerSerializableOp(opname, builder) "
            "for custom ops (in both the saving and loading process), or "
            "checkpoint the weights alone with "
            "save(path, values_only=True)")

    tc = sd._training_config
    doc = {
        "format": FORMAT_VERSION,
        **graph_doc(sd),
        "training_config": None if tc is None else {
            "updater": encode(tc.updater) if tc.updater is not None else None,
            "l1": tc.l1, "l2": tc.l2,
            "dataSetFeatureMapping": list(tc.dataSetFeatureMapping),
            "dataSetLabelMapping": list(tc.dataSetLabelMapping),
        },
    }
    arrays = {k: np.asarray(v) for k, v in sd._values.items()}
    if save_updater:
        leaves = _opt_leaves(sd)
        if leaves is not None:
            doc["updater_state_leaves"] = len(leaves)
            for i, leaf in enumerate(leaves):
                arrays[f"__updater__{i}"] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        # allow_nan=False: the artifact must stay strict RFC-8259 JSON
        # (readable by jq / other languages) — open bounds etc. must be
        # encoded as null by the op mappers, never as Infinity/NaN
        zf.writestr(GRAPH_JSON, json.dumps(doc, indent=1, allow_nan=False))
        zf.writestr(VALUES_NPZ, buf.getvalue())


def load_samediff(path):
    """Rebuild a SameDiff from the zip artifact in a fresh process: nodes
    from the table (op fns from OP_BUILDERS), values from the npz."""
    from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
    from deeplearning4j_tpu.util.serde import decode

    with zipfile.ZipFile(path) as zf:
        doc = json.loads(zf.read(GRAPH_JSON))
        vals = np.load(io.BytesIO(zf.read(VALUES_NPZ)))
        values = {k: vals[k] for k in vals.files}
    if doc.get("format", 0) > FORMAT_VERSION:
        raise ValueError(f"samediff artifact format {doc['format']} is "
                         f"newer than this build ({FORMAT_VERSION})")
    sd = graph_from_doc(doc)
    for name in sd._nodes:
        if name in values:
            sd._values[name] = jnp.asarray(values[name])
    n_opt = doc.get("updater_state_leaves")
    if n_opt:
        # consumed by _ensure_optimizer once the optax structure exists
        sd._pending_opt_leaves = [
            jnp.asarray(values[f"__updater__{i}"]) for i in range(n_opt)]
    tc = doc.get("training_config")
    if tc is not None:
        sd._training_config = TrainingConfig(
            updater=decode(tc["updater"]) if tc["updater"] else None,
            l1=tc["l1"], l2=tc["l2"],
            dataSetFeatureMapping=tc["dataSetFeatureMapping"],
            dataSetLabelMapping=tc["dataSetLabelMapping"])
    return sd
