"""TF frozen-graph → SameDiff import (≡ nd4j-api ::
imports.graphmapper.tf.TFGraphMapper / SameDiff.importFrozenTF — the
path the reference's BERT examples use).

Maps a GraphDef (parsed by the dependency-free tfproto codec) onto the
SameDiff graph: Const → constants, Placeholder → placeholders, compute
ops → jnp-backed ARRAY nodes, so the imported model compiles to ONE XLA
executable exactly like natively-built graphs. The op set covers the
frozen-BERT surface: MatMul/BatchMatMul, BiasAdd, layernorm fragments
(Mean, SquaredDifference, Rsqrt), erf-based GELU, Softmax, embedding
GatherV2, shape ops, and elementwise arithmetic.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, VariableType
from deeplearning4j_tpu.autodiff import tfproto


def _clean_ref(ref):
    """Strip a ':0' output index; KEEP ':N' for N > 0 (multi-output ops —
    Split/SplitV/Unpack register one node per output under 'name:N');
    None for '^control' deps."""
    if ref.startswith("^"):
        return None
    base, _, idx = ref.partition(":")
    return base if idx in ("", "0") else ref


class UnsupportedTFOpError(ValueError):
    pass


def _axis_from(const_inputs, idx, default=None):
    v = const_inputs[idx]
    if v is None:
        return default
    a = np.asarray(v).reshape(-1)
    return int(a[0]) if a.size == 1 else tuple(int(x) for x in a)


# each entry: fn(attrs) -> jnp function over input arrays
_ELEMENTWISE = {
    "Add": jnp.add, "AddV2": jnp.add, "BiasAdd": lambda x, b: x + b,
    "Sub": jnp.subtract, "Mul": jnp.multiply, "RealDiv": jnp.divide,
    "Div": jnp.divide, "Maximum": jnp.maximum, "Minimum": jnp.minimum,
    "Pow": jnp.power, "SquaredDifference": lambda a, b: (a - b) ** 2,
    "Relu": jax.nn.relu, "Relu6": lambda x: jnp.clip(x, 0, 6),
    "Elu": jax.nn.elu, "Selu": jax.nn.selu, "Tanh": jnp.tanh,
    "Sigmoid": jax.nn.sigmoid, "Erf": jax.lax.erf, "Exp": jnp.exp,
    "Log": jnp.log, "Sqrt": jnp.sqrt, "Rsqrt": jax.lax.rsqrt,
    "Square": jnp.square, "Abs": jnp.abs, "Neg": jnp.negative,
    "Identity": lambda x: x, "StopGradient": jax.lax.stop_gradient,
    "Floor": jnp.floor, "Sign": jnp.sign,
}

# -- serializable op builders ("tf." namespace in the graph_serde
# registry): imported nodes carry (opname, JSON params) so a frozen-graph
# import saved via SameDiff.save restores with no .pb and no user code --
from deeplearning4j_tpu.autodiff.graph_serde import op_builder  # noqa: E402

for _opn, _fn in _ELEMENTWISE.items():
    op_builder("tf." + _opn.lower())((lambda f: lambda: f)(_fn))
op_builder("tf.softmax")(lambda: lambda x: jax.nn.softmax(x, axis=-1))
op_builder("tf.softplus")(lambda: jax.nn.softplus)
op_builder("tf.addn")(lambda: lambda *xs: sum(xs[1:], xs[0]))


@op_builder("tf.leaky_relu")
def _b_leaky_relu(alpha=0.2):
    return lambda x: jnp.where(x > 0, x, alpha * x)


@op_builder("tf.split_axis")
def _b_split_axis(axis, index, num):
    # equal split: the slice size resolves from the STATIC shape at
    # trace time (TF Split carries only num_split)
    def f(x, *_r):
        ax = axis if axis >= 0 else x.ndim + axis
        if x.shape[ax] % num:
            raise ValueError(
                f"Split: dim {ax} ({x.shape[ax]}) not divisible by "
                f"num_split={num}")
        size = x.shape[ax] // num
        return jax.lax.slice_in_dim(x, index * size, (index + 1) * size,
                                    axis=ax)
    return f


@op_builder("tf.unstack_idx")
def _b_unstack_idx(axis, index, num):
    def f(x):
        ax = axis if axis >= 0 else x.ndim + axis
        if x.shape[ax] != num:
            raise ValueError(
                f"Unpack: num={num} but dim {ax} is {x.shape[ax]}")
        return jnp.squeeze(
            jax.lax.slice_in_dim(x, index, index + 1, axis=ax), axis=ax)
    return f
op_builder("tf.shape")(lambda: lambda x: jnp.asarray(x.shape, jnp.int32))
op_builder("tf.rsqrt")(lambda: jax.lax.rsqrt)


@op_builder("tf.matmul")
def _b_matmul(ta=False, tb=False):
    def mm(a, b):
        a = a.T if ta else a
        b = b.T if tb else b
        return a @ b
    return mm


@op_builder("tf.batch_matmul")
def _b_batch_matmul(ta=False, tb=False):
    def bmm(a, b):
        a = jnp.swapaxes(a, -1, -2) if ta else a
        b = jnp.swapaxes(b, -1, -2) if tb else b
        return a @ b
    return bmm


def _tf_reduce_builder(fn):
    def build(axis=None, keep=False):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return lambda x, _a: fn(x, axis=ax, keepdims=keep)
    return build


for _opn, _fn in [("mean", jnp.mean), ("sum", jnp.sum), ("max", jnp.max),
                  ("min", jnp.min)]:
    op_builder("tf." + _opn)(_tf_reduce_builder(_fn))


@op_builder("tf.reshape")
def _b_reshape(shape):
    return lambda x, _s: jnp.reshape(x, tuple(shape))


@op_builder("tf.transpose")
def _b_transpose(perm):
    return lambda x, _p: jnp.transpose(x, tuple(perm))


@op_builder("tf.expand_dims")
def _b_expand_dims(axis=0):
    return lambda x, _a: jnp.expand_dims(x, axis)


@op_builder("tf.squeeze")
def _b_squeeze(dims=None):
    return lambda x: jnp.squeeze(x, None if not dims else tuple(dims))


@op_builder("tf.concat")
def _b_concat(axis=0):
    return lambda *xs: jnp.concatenate(xs, axis)


@op_builder("tf.gather")
def _b_gather(axis=0):
    return lambda p, i, *rest: jnp.take(p, i.astype(jnp.int32), axis=axis)


@op_builder("tf.cast")
def _b_cast(dtype="float32"):
    np_dt = np.dtype(dtype)
    return lambda x: x.astype(np_dt)


@op_builder("tf.stack")
def _b_stack(axis=0):
    return lambda *xs: jnp.stack(xs, axis=axis)


@op_builder("tf.tile")
def _b_tile(reps):
    return lambda x, _r: jnp.tile(x, tuple(reps))


@op_builder("tf.strided_slice")
def _b_strided_slice(sl):
    # JSON form: int = rank-reducing index; [lo, hi, step] = slice
    # (None encoded as JSON null)
    slt = tuple(s if isinstance(s, int) else slice(*s) for s in sl)
    return lambda x, *_r: x[slt]


@op_builder("tf.one_hot")
def _b_one_hot(depth):
    return lambda i, *_r: jax.nn.one_hot(i.astype(jnp.int32), depth)


@op_builder("tf.conv2d")
def _b_conv2d(strides, dil, padding, depthwise=False):
    st, dl = tuple(strides), tuple(dil)
    pd = padding if isinstance(padding, str) else [tuple(p)
                                                  for p in padding]

    def conv(x, w):
        # TF weights are HWIO; depthwise weights (H, W, C, M) run as a
        # grouped conv with feature_group_count = C
        groups = 1
        if depthwise:
            h_, w_, cin, mult = w.shape
            w = w.reshape(h_, w_, 1, cin * mult)
            groups = cin
        return jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), window_strides=st, padding=pd,
            rhs_dilation=dl, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return conv


@op_builder("tf.maxpool")
def _b_maxpool(ksize, strides, padding):
    k, s = tuple(ksize), tuple(strides)
    return lambda x: jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, k, s,
                                           padding)


@op_builder("tf.avgpool")
def _b_avgpool(ksize, strides, padding):
    k, s = tuple(ksize), tuple(strides)

    def avg(x):
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, k, s, padding)
        n = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, k, s,
                                  padding)
        return summed / n
    return avg


@op_builder("tf.fused_batch_norm")
def _b_fused_batch_norm(eps=1e-4):
    def fbn(x, gamma, beta, mean, var):
        return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return fbn


@op_builder("tf.pad")
def _b_pad(width, cval=0.0):
    w = [tuple(row) for row in width]
    return lambda x, *_r: jnp.pad(x, w, constant_values=cval)


class TFGraphMapper:
    @staticmethod
    def importGraph(path_or_bytes, sd=None):
        data = path_or_bytes
        if not isinstance(data, (bytes, bytearray)):
            with open(data, "rb") as f:
                data = f.read()
        nodes = tfproto.parse_graphdef(bytes(data))
        sd = sd or SameDiff.create()
        consts = {}     # name -> np value (for shape/axis arguments)

        for node in nodes:
            TFGraphMapper._map_node(sd, node, consts)
        return sd

    @staticmethod
    def _map_node(sd, node, consts):
        op, name = node.op, node.name
        in_refs = [r for r in (_clean_ref(i) for i in node.inputs)
                   if r is not None]

        def const_val(i):
            return consts.get(in_refs[i])

        if op == "Const":
            value = node.attrs.get("value")
            consts[name] = np.asarray(value)
            sd.constant(name, np.asarray(value))
            return
        if op in ("Identity", "StopGradient") and in_refs \
                and in_refs[0] in consts:
            # frozen graphs routinely wrap constants in Identity; keep the
            # alias visible so axis/shape arguments still resolve
            consts[name] = consts[in_refs[0]]
        if op == "Placeholder":
            shape = node.attrs.get("shape")
            dims = shape[1] if isinstance(shape, tuple) else []
            sd.placeHolder(name, *[d if d > 0 else None for d in dims])
            return

        ins = [sd.getVariable(r) for r in in_refs]

        if op in _ELEMENTWISE:
            sd._op_named(name, "tf." + op.lower(), None, *ins, params={})
        elif op == "MatMul":
            sd._op_named(name, "tf.matmul", None, *ins, params={
                "ta": bool(node.attrs.get("transpose_a", False)),
                "tb": bool(node.attrs.get("transpose_b", False))})
        elif op in ("BatchMatMul", "BatchMatMulV2"):
            sd._op_named(name, "tf.batch_matmul", None, *ins, params={
                "ta": bool(node.attrs.get("adj_x", False)),
                "tb": bool(node.attrs.get("adj_y", False))})
        elif op == "Softmax":
            sd._op_named(name, "tf.softmax", None, *ins, params={})
        elif op in ("Mean", "Sum", "Max", "Min"):
            if const_val(1) is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic {op} axes unsupported (axis input "
                    "must trace to a Const)")
            axis = _axis_from([const_val(1)], 0)
            sd._op_named(name, "tf." + op.lower(), None, *ins, params={
                "axis": list(axis) if isinstance(axis, tuple) else axis,
                "keep": bool(node.attrs.get("keep_dims", False))})
        elif op == "Reshape":
            shp = const_val(1)
            if shp is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Reshape target shape unsupported")
            sd._op_named(name, "tf.reshape", None, *ins, params={
                "shape": [int(s) for s in np.asarray(shp).reshape(-1)]})
        elif op == "Transpose":
            if const_val(1) is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Transpose perm unsupported")
            sd._op_named(name, "tf.transpose", None, *ins, params={
                "perm": [int(p)
                         for p in np.asarray(const_val(1)).reshape(-1)]})
        elif op == "ExpandDims":
            sd._op_named(name, "tf.expand_dims", None, *ins, params={
                "axis": _axis_from([const_val(1)], 0, 0)})
        elif op == "Squeeze":
            dims = node.attrs.get("squeeze_dims") or None
            sd._op_named(name, "tf.squeeze", None, *ins, params={
                "dims": None if not dims else [int(d) for d in dims]})
        elif op in ("ConcatV2", "Concat"):
            # ConcatV2: axis is the LAST input; v1 Concat: the FIRST
            axis_idx = len(in_refs) - 1 if op == "ConcatV2" else 0
            av = const_val(axis_idx)
            if av is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Concat axis unsupported")
            data_ins = (ins[:-1] if op == "ConcatV2" else ins[1:])
            sd._op_named(name, "tf.concat", None, *data_ins, params={
                "axis": int(np.asarray(av).reshape(()))})
        elif op in ("GatherV2", "Gather"):
            axis = 0
            if op == "GatherV2" and len(ins) > 2:
                axis = _axis_from([const_val(2)], 0, 0)
            sd._op_named(name, "tf.gather", None, *ins,
                         params={"axis": axis})
        elif op == "Cast":
            dst = node.attrs.get("DstT")
            np_dt = tfproto._DTYPES.get(
                dst[1] if isinstance(dst, tuple) else dst, np.float32)
            sd._op_named(name, "tf.cast", None, *ins,
                         params={"dtype": np.dtype(np_dt).name})
        elif op == "Pack":
            sd._op_named(name, "tf.stack", None, *ins, params={
                "axis": int(node.attrs.get("axis", 0) or 0)})
        elif op == "Shape":
            sd._op_named(name, "tf.shape", None, *ins, params={})
        elif op == "Rsqrt":
            sd._op_named(name, "tf.rsqrt", None, *ins, params={})
        elif op == "Softplus":
            sd._op_named(name, "tf.softplus", None, *ins, params={})
        elif op == "LeakyRelu":
            a = node.attrs.get("alpha")
            sd._op_named(name, "tf.leaky_relu", None, *ins, params={
                "alpha": 0.2 if a is None else float(a)})
        elif op == "AddN":
            sd._op_named(name, "tf.addn", None, *ins, params={})
        elif op == "Split":
            # TF v1 Split: inputs [split_dim, value], attr num_split;
            # equal split — sizes resolve from the static shape at trace
            av = const_val(0)
            if av is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Split axis unsupported")
            axis = int(np.asarray(av).reshape(()))
            num = int(node.attrs.get("num_split", 0) or 0)
            if num <= 0:
                raise UnsupportedTFOpError(
                    f"{name}: Split needs the num_split attribute")
            for i in range(num):
                out_name = name if i == 0 else f"{name}:{i}"
                # the VALUE is input[1] (input[0] is the axis const)
                sd._op_named(out_name, "tf.split_axis", None, ins[1],
                             params={"axis": axis, "index": i,
                                     "num": num})
        elif op == "SplitV":
            # inputs [value, size_splits, axis]
            sizes = const_val(1)
            ax_v = const_val(2)
            if sizes is None or ax_v is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic SplitV sizes/axis unsupported")
            axis = int(np.asarray(ax_v).reshape(()))
            sizes = [int(v) for v in np.asarray(sizes).reshape(-1)]
            if any(v < 0 for v in sizes):
                raise UnsupportedTFOpError(
                    f"{name}: SplitV -1 (inferred) size unsupported")
            off = 0
            for i, sz in enumerate(sizes):
                out_name = name if i == 0 else f"{name}:{i}"
                sd._op_named(out_name, "slice_axis", None, ins[0],
                             params={"axis": axis, "start": off,
                                     "size": sz})
                off += sz
        elif op == "Unpack":
            axis = int(node.attrs.get("axis", 0) or 0)
            num = int(node.attrs.get("num", 1) or 1)
            for i in range(num):
                out_name = name if i == 0 else f"{name}:{i}"
                sd._op_named(out_name, "tf.unstack_idx", None, *ins,
                             params={"axis": axis, "index": i,
                                     "num": num})
        elif op == "Tile":
            reps = const_val(1)
            sd._op_named(name, "tf.tile", None, *ins, params={
                "reps": [int(r) for r in np.asarray(reps).reshape(-1)]})
        elif op == "StridedSlice":
            b = const_val(1)
            e = const_val(2)
            s = const_val(3)
            if b is None or e is None or s is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic StridedSlice unsupported")
            begin_mask = int(node.attrs.get("begin_mask", 0) or 0)
            end_mask = int(node.attrs.get("end_mask", 0) or 0)
            shrink = int(node.attrs.get("shrink_axis_mask", 0) or 0)
            if node.attrs.get("ellipsis_mask") or \
                    node.attrs.get("new_axis_mask"):
                raise UnsupportedTFOpError(
                    f"{name}: StridedSlice ellipsis/new_axis masks "
                    "unsupported")
            sl = []
            for d, (bi, ei, si) in enumerate(zip(
                    np.asarray(b).reshape(-1), np.asarray(e).reshape(-1),
                    np.asarray(s).reshape(-1))):
                if shrink & (1 << d):
                    sl.append(int(bi))          # rank-reducing index
                    continue
                lo = None if begin_mask & (1 << d) else int(bi)
                hi = None if end_mask & (1 << d) else int(ei)
                sl.append([lo, hi, int(si)])    # JSON slice triple
            sd._op_named(name, "tf.strided_slice", None, *ins,
                         params={"sl": sl})
        elif op == "OneHot":
            sd._op_named(name, "tf.one_hot", None, *ins, params={
                "depth": int(np.asarray(const_val(1)).reshape(()))})
        elif op in ("Conv2D", "DepthwiseConv2dNative"):
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")
            strides = tuple(node.attrs.get("strides") or (1, 1, 1, 1))[1:3]
            dil = tuple(node.attrs.get("dilations") or (1, 1, 1, 1))[1:3]
            padding = node.attrs.get("padding", "VALID")
            if padding == "EXPLICIT":
                ep = node.attrs.get("explicit_paddings") or []
                if len(ep) != 8:
                    raise UnsupportedTFOpError(
                        f"{name}: padding=EXPLICIT needs 8 "
                        f"explicit_paddings values, got {len(ep)}")
                if any(int(v) for v in (*ep[:2], *ep[6:])):
                    raise UnsupportedTFOpError(
                        f"{name}: EXPLICIT padding on batch/channel "
                        f"dims unsupported ({list(ep)})")
                # NHWC order: take the H and W begin/end pairs
                padding = [(int(ep[2]), int(ep[3])),
                           (int(ep[4]), int(ep[5]))]
            sd._op_named(name, "tf.conv2d", None, *ins, params={
                "strides": [int(s) for s in strides],
                "dil": [int(d) for d in dil],
                "padding": padding if isinstance(padding, str)
                else [list(p) for p in padding],
                "depthwise": op == "DepthwiseConv2dNative"})
        elif op in ("MaxPool", "AvgPool"):
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")
            ksize = tuple(node.attrs.get("ksize") or (1, 2, 2, 1))
            strides = tuple(node.attrs.get("strides") or ksize)
            padding = node.attrs.get("padding", "VALID")
            if padding not in ("SAME", "VALID"):
                raise UnsupportedTFOpError(
                    f"{name}: pool padding {padding!r} unsupported")
            params = {"ksize": [int(k) for k in ksize],
                      "strides": [int(s) for s in strides],
                      "padding": padding}
            sd._op_named(name, "tf.maxpool" if op == "MaxPool"
                         else "tf.avgpool", None, *ins, params=params)
        elif op in ("FusedBatchNorm", "FusedBatchNormV2",
                    "FusedBatchNormV3"):
            # frozen-graph inference form: inputs x, gamma, beta, mean, var
            if node.attrs.get("is_training"):
                raise UnsupportedTFOpError(
                    f"{name}: FusedBatchNorm with is_training=True "
                    f"unsupported (freeze the graph for inference)")
            # TF OpDef default is 1e-4 — a frozen graph stripped of
            # default-valued attrs must not import with a 10x epsilon
            eps = float(node.attrs.get("epsilon", 1e-4))
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")

            sd._op_named(name, "tf.fused_batch_norm", None, *ins,
                         params={"eps": eps})
        elif op in ("Pad", "PadV2"):
            pv = const_val(1)
            if pv is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Pad unsupported")
            width = [[int(v) for v in row]
                     for row in np.asarray(pv).reshape(-1, 2)]
            cval = 0.0
            if op == "PadV2" and len(in_refs) > 2:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedTFOpError(
                        f"{name}: non-constant PadV2 value unsupported")
                cval = float(np.asarray(cv).reshape(()))
            sd._op_named(name, "tf.pad", None, *ins,
                         params={"width": width, "cval": cval})
        else:
            raise UnsupportedTFOpError(
                f"TF op '{op}' (node '{name}') is not in the import op set")


def importFrozenTF(path_or_bytes):
    """≡ SameDiff.importFrozenTF(File)."""
    return TFGraphMapper.importGraph(path_or_bytes)


SameDiff.importFrozenTF = staticmethod(importFrozenTF)
