"""TF frozen-graph → SameDiff import (≡ nd4j-api ::
imports.graphmapper.tf.TFGraphMapper / SameDiff.importFrozenTF — the
path the reference's BERT examples use).

Maps a GraphDef (parsed by the dependency-free tfproto codec) onto the
SameDiff graph: Const → constants, Placeholder → placeholders, compute
ops → jnp-backed ARRAY nodes, so the imported model compiles to ONE XLA
executable exactly like natively-built graphs. The op set covers the
frozen-BERT surface: MatMul/BatchMatMul, BiasAdd, layernorm fragments
(Mean, SquaredDifference, Rsqrt), erf-based GELU, Softmax, embedding
GatherV2, shape ops, and elementwise arithmetic.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, VariableType
from deeplearning4j_tpu.autodiff import tfproto


def _clean_ref(ref):
    """strip ':0' output index; None for '^control' deps."""
    if ref.startswith("^"):
        return None
    return ref.split(":")[0]


class UnsupportedTFOpError(ValueError):
    pass


def _axis_from(const_inputs, idx, default=None):
    v = const_inputs[idx]
    if v is None:
        return default
    a = np.asarray(v).reshape(-1)
    return int(a[0]) if a.size == 1 else tuple(int(x) for x in a)


# each entry: fn(attrs) -> jnp function over input arrays
_ELEMENTWISE = {
    "Add": jnp.add, "AddV2": jnp.add, "BiasAdd": lambda x, b: x + b,
    "Sub": jnp.subtract, "Mul": jnp.multiply, "RealDiv": jnp.divide,
    "Div": jnp.divide, "Maximum": jnp.maximum, "Minimum": jnp.minimum,
    "Pow": jnp.power, "SquaredDifference": lambda a, b: (a - b) ** 2,
    "Relu": jax.nn.relu, "Relu6": lambda x: jnp.clip(x, 0, 6),
    "Elu": jax.nn.elu, "Selu": jax.nn.selu, "Tanh": jnp.tanh,
    "Sigmoid": jax.nn.sigmoid, "Erf": jax.lax.erf, "Exp": jnp.exp,
    "Log": jnp.log, "Sqrt": jnp.sqrt, "Rsqrt": jax.lax.rsqrt,
    "Square": jnp.square, "Abs": jnp.abs, "Neg": jnp.negative,
    "Identity": lambda x: x, "StopGradient": jax.lax.stop_gradient,
    "Floor": jnp.floor, "Sign": jnp.sign,
}


class TFGraphMapper:
    @staticmethod
    def importGraph(path_or_bytes, sd=None):
        data = path_or_bytes
        if not isinstance(data, (bytes, bytearray)):
            with open(data, "rb") as f:
                data = f.read()
        nodes = tfproto.parse_graphdef(bytes(data))
        sd = sd or SameDiff.create()
        consts = {}     # name -> np value (for shape/axis arguments)

        for node in nodes:
            TFGraphMapper._map_node(sd, node, consts)
        return sd

    @staticmethod
    def _map_node(sd, node, consts):
        op, name = node.op, node.name
        in_refs = [r for r in (_clean_ref(i) for i in node.inputs)
                   if r is not None]

        def const_val(i):
            return consts.get(in_refs[i])

        if op == "Const":
            value = node.attrs.get("value")
            consts[name] = np.asarray(value)
            sd.constant(name, np.asarray(value))
            return
        if op in ("Identity", "StopGradient") and in_refs \
                and in_refs[0] in consts:
            # frozen graphs routinely wrap constants in Identity; keep the
            # alias visible so axis/shape arguments still resolve
            consts[name] = consts[in_refs[0]]
        if op == "Placeholder":
            shape = node.attrs.get("shape")
            dims = shape[1] if isinstance(shape, tuple) else []
            sd.placeHolder(name, *[d if d > 0 else None for d in dims])
            return

        ins = [sd.getVariable(r) for r in in_refs]

        if op in _ELEMENTWISE:
            fn = _ELEMENTWISE[op]
            sd._op_named(name, op.lower(), fn, *ins)
        elif op == "MatMul":
            ta = bool(node.attrs.get("transpose_a", False))
            tb = bool(node.attrs.get("transpose_b", False))

            def mm(a, b, ta=ta, tb=tb):
                a = a.T if ta else a
                b = b.T if tb else b
                return a @ b
            sd._op_named(name, "matmul", mm, *ins)
        elif op in ("BatchMatMul", "BatchMatMulV2"):
            ta = bool(node.attrs.get("adj_x", False))
            tb = bool(node.attrs.get("adj_y", False))

            def bmm(a, b, ta=ta, tb=tb):
                a = jnp.swapaxes(a, -1, -2) if ta else a
                b = jnp.swapaxes(b, -1, -2) if tb else b
                return a @ b
            sd._op_named(name, "batch_matmul", bmm, *ins)
        elif op == "Softmax":
            sd._op_named(name, "softmax",
                         lambda x: jax.nn.softmax(x, axis=-1), *ins)
        elif op in ("Mean", "Sum", "Max", "Min"):
            red = {"Mean": jnp.mean, "Sum": jnp.sum, "Max": jnp.max,
                   "Min": jnp.min}[op]
            if const_val(1) is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic {op} axes unsupported (axis input "
                    "must trace to a Const)")
            axis = _axis_from([const_val(1)], 0)
            keep = bool(node.attrs.get("keep_dims", False))
            sd._op_named(name, op.lower(),
                         lambda x, _a, red=red, axis=axis, keep=keep:
                         red(x, axis=axis, keepdims=keep), *ins)
        elif op == "Reshape":
            shp = const_val(1)
            if shp is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Reshape target shape unsupported")
            shp = tuple(int(s) for s in np.asarray(shp).reshape(-1))
            sd._op_named(name, "reshape",
                         lambda x, _s, shp=shp: jnp.reshape(x, shp), *ins)
        elif op == "Transpose":
            if const_val(1) is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Transpose perm unsupported")
            perm = tuple(int(p)
                         for p in np.asarray(const_val(1)).reshape(-1))
            sd._op_named(name, "transpose",
                         lambda x, _p, perm=perm: jnp.transpose(x, perm),
                         *ins)
        elif op == "ExpandDims":
            axis = _axis_from([const_val(1)], 0, 0)
            sd._op_named(name, "expand_dims",
                         lambda x, _a, axis=axis: jnp.expand_dims(x, axis),
                         *ins)
        elif op == "Squeeze":
            dims = node.attrs.get("squeeze_dims") or None
            sd._op_named(name, "squeeze",
                         lambda x, dims=dims: jnp.squeeze(
                             x, None if not dims else tuple(dims)), *ins)
        elif op in ("ConcatV2", "Concat"):
            # ConcatV2: axis is the LAST input; v1 Concat: the FIRST
            axis_idx = len(in_refs) - 1 if op == "ConcatV2" else 0
            av = const_val(axis_idx)
            if av is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Concat axis unsupported")
            axis = int(np.asarray(av).reshape(()))
            data_ins = (ins[:-1] if op == "ConcatV2" else ins[1:])
            sd._op_named(name, "concat",
                         lambda *xs, axis=axis: jnp.concatenate(xs, axis),
                         *data_ins)
        elif op in ("GatherV2", "Gather"):
            axis = 0
            if op == "GatherV2" and len(ins) > 2:
                axis = _axis_from([const_val(2)], 0, 0)
            sd._op_named(name, "gather",
                         lambda p, i, *rest, axis=axis: jnp.take(
                             p, i.astype(jnp.int32), axis=axis), *ins)
        elif op == "Cast":
            dst = node.attrs.get("DstT")
            np_dt = tfproto._DTYPES.get(
                dst[1] if isinstance(dst, tuple) else dst, np.float32)
            sd._op_named(name, "cast",
                         lambda x, np_dt=np_dt: x.astype(np_dt), *ins)
        elif op == "Pack":
            axis = int(node.attrs.get("axis", 0) or 0)
            sd._op_named(name, "stack",
                         lambda *xs, axis=axis: jnp.stack(xs, axis=axis),
                         *ins)
        elif op == "Shape":
            sd._op_named(name, "shape",
                         lambda x: jnp.asarray(x.shape, jnp.int32), *ins)
        elif op == "Rsqrt":
            sd._op_named(name, "rsqrt", jax.lax.rsqrt, *ins)
        elif op == "Tile":
            reps = const_val(1)
            reps = tuple(int(r) for r in np.asarray(reps).reshape(-1))
            sd._op_named(name, "tile",
                         lambda x, _r, reps=reps: jnp.tile(x, reps), *ins)
        elif op == "StridedSlice":
            b = const_val(1)
            e = const_val(2)
            s = const_val(3)
            if b is None or e is None or s is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic StridedSlice unsupported")
            begin_mask = int(node.attrs.get("begin_mask", 0) or 0)
            end_mask = int(node.attrs.get("end_mask", 0) or 0)
            shrink = int(node.attrs.get("shrink_axis_mask", 0) or 0)
            if node.attrs.get("ellipsis_mask") or \
                    node.attrs.get("new_axis_mask"):
                raise UnsupportedTFOpError(
                    f"{name}: StridedSlice ellipsis/new_axis masks "
                    "unsupported")
            sl = []
            for d, (bi, ei, si) in enumerate(zip(
                    np.asarray(b).reshape(-1), np.asarray(e).reshape(-1),
                    np.asarray(s).reshape(-1))):
                if shrink & (1 << d):
                    sl.append(int(bi))          # rank-reducing index
                    continue
                lo = None if begin_mask & (1 << d) else int(bi)
                hi = None if end_mask & (1 << d) else int(ei)
                sl.append(slice(lo, hi, int(si)))
            sl = tuple(sl)
            sd._op_named(name, "strided_slice",
                         lambda x, *_r, sl=sl: x[sl], *ins)
        elif op == "OneHot":
            depth = int(np.asarray(const_val(1)).reshape(()))
            sd._op_named(name, "one_hot",
                         lambda i, *_r, depth=depth: jax.nn.one_hot(
                             i.astype(jnp.int32), depth), *ins)
        elif op in ("Conv2D", "DepthwiseConv2dNative"):
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")
            strides = tuple(node.attrs.get("strides") or (1, 1, 1, 1))[1:3]
            dil = tuple(node.attrs.get("dilations") or (1, 1, 1, 1))[1:3]
            padding = node.attrs.get("padding", "VALID")
            if padding == "EXPLICIT":
                ep = node.attrs.get("explicit_paddings") or []
                if len(ep) != 8:
                    raise UnsupportedTFOpError(
                        f"{name}: padding=EXPLICIT needs 8 "
                        f"explicit_paddings values, got {len(ep)}")
                if any(int(v) for v in (*ep[:2], *ep[6:])):
                    raise UnsupportedTFOpError(
                        f"{name}: EXPLICIT padding on batch/channel "
                        f"dims unsupported ({list(ep)})")
                # NHWC order: take the H and W begin/end pairs
                padding = [(int(ep[2]), int(ep[3])),
                           (int(ep[4]), int(ep[5]))]
            depthwise = op == "DepthwiseConv2dNative"

            def conv(x, w, strides=strides, dil=dil, padding=padding,
                     depthwise=depthwise):
                # TF weights are HWIO; depthwise weights (H, W, C, M) run
                # as a grouped conv with feature_group_count = C
                groups = 1
                if depthwise:
                    h_, w_, cin, mult = w.shape
                    w = w.reshape(h_, w_, 1, cin * mult)
                    groups = cin
                return jax.lax.conv_general_dilated(
                    x, w.astype(x.dtype), window_strides=strides,
                    padding=padding, rhs_dilation=dil,
                    feature_group_count=groups,
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
            sd._op_named(name, "conv2d", conv, *ins)
        elif op in ("MaxPool", "AvgPool"):
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")
            ksize = tuple(node.attrs.get("ksize") or (1, 2, 2, 1))
            strides = tuple(node.attrs.get("strides") or ksize)
            padding = node.attrs.get("padding", "VALID")
            if padding not in ("SAME", "VALID"):
                raise UnsupportedTFOpError(
                    f"{name}: pool padding {padding!r} unsupported")
            if op == "MaxPool":
                sd._op_named(name, "maxpool",
                             lambda x, ksize=ksize, strides=strides,
                             padding=padding: jax.lax.reduce_window(
                                 x, -jnp.inf, jax.lax.max, ksize, strides,
                                 padding), *ins)
            else:
                def avg(x, ksize=ksize, strides=strides, padding=padding):
                    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, ksize,
                                              strides, padding)
                    n = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                              jax.lax.add, ksize, strides,
                                              padding)
                    return s / n
                sd._op_named(name, "avgpool", avg, *ins)
        elif op in ("FusedBatchNorm", "FusedBatchNormV2",
                    "FusedBatchNormV3"):
            # frozen-graph inference form: inputs x, gamma, beta, mean, var
            if node.attrs.get("is_training"):
                raise UnsupportedTFOpError(
                    f"{name}: FusedBatchNorm with is_training=True "
                    f"unsupported (freeze the graph for inference)")
            # TF OpDef default is 1e-4 — a frozen graph stripped of
            # default-valued attrs must not import with a 10x epsilon
            eps = float(node.attrs.get("epsilon", 1e-4))
            fmt = node.attrs.get("data_format", "NHWC")
            if fmt != "NHWC":
                raise UnsupportedTFOpError(
                    f"{name}: data_format {fmt!r} unsupported (NHWC only)")

            def fbn(x, gamma, beta, mean, var, eps=eps):
                return ((x - mean) * jax.lax.rsqrt(var + eps)
                        * gamma + beta)
            sd._op_named(name, "fused_batch_norm", fbn, *ins)
        elif op in ("Pad", "PadV2"):
            pv = const_val(1)
            if pv is None:
                raise UnsupportedTFOpError(
                    f"{name}: dynamic Pad unsupported")
            width = [tuple(int(v) for v in row)
                     for row in np.asarray(pv).reshape(-1, 2)]
            cval = 0.0
            if op == "PadV2" and len(in_refs) > 2:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedTFOpError(
                        f"{name}: non-constant PadV2 value unsupported")
                cval = float(np.asarray(cv).reshape(()))
            sd._op_named(name, "pad",
                         lambda x, *_r, width=width, cval=cval: jnp.pad(
                             x, width, constant_values=cval), *ins)
        else:
            raise UnsupportedTFOpError(
                f"TF op '{op}' (node '{name}') is not in the import op set")


def importFrozenTF(path_or_bytes):
    """≡ SameDiff.importFrozenTF(File)."""
    return TFGraphMapper.importGraph(path_or_bytes)


SameDiff.importFrozenTF = staticmethod(importFrozenTF)
