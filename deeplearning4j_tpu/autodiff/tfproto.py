"""Minimal protobuf wire-format codec for TensorFlow GraphDef files
(≡ the protobuf layer under nd4j's TFGraphMapper import path).

No tensorflow/protobuf dependency: the wire format is five primitive
shapes (varint, fixed32/64, length-delimited), and GraphDef only needs a
handful of message types (NodeDef, AttrValue, TensorProto,
TensorShapeProto). Field numbers follow tensorflow/core/framework/*.proto.
The writer exists so tests (and users without TF) can author frozen
graphs; the reader backs SameDiff.importFrozenTF.
"""
from __future__ import annotations

import struct

import numpy as np

# TF DataType enum (framework/types.proto)
DT_FLOAT, DT_DOUBLE, DT_INT32, DT_UINT8 = 1, 2, 3, 4
DT_INT16, DT_INT8, DT_STRING, DT_COMPLEX64, DT_INT64, DT_BOOL = \
    5, 6, 7, 8, 9, 10

_DTYPES = {DT_FLOAT: np.float32, DT_DOUBLE: np.float64,
           DT_INT32: np.int32, DT_INT64: np.int64, DT_BOOL: np.bool_,
           DT_UINT8: np.uint8, DT_INT16: np.int16, DT_INT8: np.int8}
_DTYPES_INV = {np.dtype(v): k for k, v in _DTYPES.items()}


# -- wire primitives -----------------------------------------------------
def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out, value):
    value &= (1 << 64) - 1  # negatives encode as 10-byte two's complement
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def parse_fields(buf):
    """bytes -> {field_number: [raw values]} (varint ints / bytes)."""
    fields = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        fields.setdefault(fnum, []).append(val)
    return fields


def _signed(v):
    """varint int64: values ≥ 2^63 are negative two's complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


# -- GraphDef reading ----------------------------------------------------
def parse_tensor(buf):
    """TensorProto -> numpy array."""
    f = parse_fields(buf)
    dtype = _DTYPES[f[1][0]] if 1 in f else np.float32
    shape = []
    if 2 in f:
        for dim in parse_fields(f[2][0]).get(2, []):
            shape.append(_signed(parse_fields(dim).get(1, [0])[0]))
    if 4 in f and f[4][0]:                       # tensor_content bytes
        arr = np.frombuffer(f[4][0], dtype=dtype)
    elif 5 in f:                                 # float_val (packed or not)
        raw = b"".join(v if isinstance(v, bytes) else b"" for v in f[5])
        arr = np.frombuffer(raw, np.float32) if raw else np.asarray(
            [v for v in f[5] if not isinstance(v, bytes)], np.float32)
        arr = arr.astype(dtype)
    elif 6 in f:                                 # double_val (packed f64)
        raw = b"".join(v for v in f[6] if isinstance(v, bytes))
        arr = np.frombuffer(raw, "<f8").astype(dtype) if raw else \
            np.asarray([v for v in f[6] if not isinstance(v, bytes)],
                       np.float64).astype(dtype)
    elif 7 in f:                                 # int_val
        arr = _packed_ints(f[7], np.int32).astype(dtype)
    elif 10 in f:                                # int64_val
        arr = _packed_ints(f[10], np.int64).astype(dtype)
    elif 11 in f:                                # bool_val
        arr = _packed_ints(f[11], np.bool_)
    elif 8 in f or 13 in f:                      # string_val / half_val
        raise ValueError(
            "TensorProto string/half content is not supported")
    else:
        # no content fields at all is valid protobuf: an all-zeros tensor
        arr = np.zeros(shape or (), dtype)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:                  # splat-encoded constant
        arr = np.full(n, arr.reshape(-1)[0], dtype)
    return arr.reshape(shape) if shape else arr.reshape(())


def _packed_ints(vals, dtype):
    out = []
    for v in vals:
        if isinstance(v, bytes):                 # packed repeated
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed(x))
        else:
            out.append(_signed(v))
    return np.asarray(out, dtype)


def parse_attr(buf):
    """AttrValue -> python value."""
    f = parse_fields(buf)
    if 2 in f:
        return f[2][0].decode("utf-8", "replace")      # s
    if 3 in f:
        return _signed(f[3][0])                        # i
    if 4 in f:
        return struct.unpack("<f", f[4][0])[0]         # f
    if 5 in f:
        return bool(f[5][0])                           # b
    if 6 in f:
        return ("dtype", f[6][0])                      # type
    if 8 in f:
        return parse_tensor(f[8][0])                   # tensor
    if 7 in f:                                         # shape
        dims = [_signed(parse_fields(d).get(1, [0])[0])
                for d in parse_fields(f[7][0]).get(2, [])]
        return ("shape", dims)
    if 1 in f:                                         # list
        lf = parse_fields(f[1][0])
        if 3 in lf:
            return _packed_ints(lf[3], np.int64).tolist()
        if 4 in lf:
            raw = b"".join(v for v in lf[4] if isinstance(v, bytes))
            return np.frombuffer(raw, "<f4").tolist()
        if 2 in lf:
            return [s.decode() for s in lf[2]]
    return None


class TFNode:
    def __init__(self, name, op, inputs, attrs):
        self.name = name
        self.op = op
        self.inputs = inputs       # raw refs (may carry ':0' / '^ctrl')
        self.attrs = attrs

    def __repr__(self):
        return f"TFNode({self.op} {self.name} <- {self.inputs})"


def parse_graphdef(data):
    """GraphDef bytes -> list[TFNode]."""
    nodes = []
    for nd in parse_fields(data).get(1, []):
        f = parse_fields(nd)
        name = f.get(1, [b""])[0].decode()
        op = f.get(2, [b""])[0].decode()
        inputs = [i.decode() for i in f.get(3, [])]
        attrs = {}
        for kv in f.get(5, []):
            kvf = parse_fields(kv)
            key = kvf.get(1, [b""])[0].decode()
            attrs[key] = parse_attr(kvf.get(2, [b""])[0])
        nodes.append(TFNode(name, op, inputs, attrs))
    return nodes


# -- GraphDef writing (for tests / TF-less authoring) --------------------
def _field(out, fnum, wtype):
    _write_varint(out, (fnum << 3) | wtype)


def _put_bytes(out, fnum, data):
    _field(out, fnum, 2)
    _write_varint(out, len(data))
    out.extend(data)


def _put_varint(out, fnum, value):
    _field(out, fnum, 0)
    _write_varint(out, value)


def encode_tensor(arr):
    arr = np.asarray(arr)
    out = bytearray()
    _put_varint(out, 1, _DTYPES_INV[arr.dtype])
    shape = bytearray()
    for d in arr.shape:
        dim = bytearray()
        _put_varint(dim, 1, d)
        _put_bytes(shape, 2, dim)
    _put_bytes(out, 2, shape)
    _put_bytes(out, 4, arr.tobytes())
    return bytes(out)


def encode_attr(value):
    out = bytearray()
    if isinstance(value, np.generic):   # 0-d numpy scalar → tensor attr
        value = np.asarray(value)
    if isinstance(value, str):
        _put_bytes(out, 2, value.encode())
    elif isinstance(value, bool):
        _put_varint(out, 5, int(value))
    elif isinstance(value, int):
        _put_varint(out, 3, value)
    elif isinstance(value, float):
        _field(out, 4, 5)
        out.extend(struct.pack("<f", value))
    elif isinstance(value, tuple) and value[0] == "dtype":
        _put_varint(out, 6, value[1])
    elif isinstance(value, (list,)):
        lst = bytearray()
        for v in value:
            _put_varint(lst, 3, int(v))
        _put_bytes(out, 1, bytes(lst))
    elif isinstance(value, np.ndarray):
        _put_bytes(out, 8, encode_tensor(value))
    else:
        raise ValueError(f"cannot encode attr {value!r}")
    return bytes(out)


def encode_graphdef(nodes):
    """nodes: list of (name, op, inputs, attrs-dict) or TFNode."""
    out = bytearray()
    for n in nodes:
        if isinstance(n, TFNode):
            name, op, inputs, attrs = n.name, n.op, n.inputs, n.attrs
        else:
            name, op, inputs, attrs = n
        nd = bytearray()
        _put_bytes(nd, 1, name.encode())
        _put_bytes(nd, 2, op.encode())
        for i in inputs:
            _put_bytes(nd, 3, i.encode())
        for k, v in attrs.items():
            kv = bytearray()
            _put_bytes(kv, 1, k.encode())
            _put_bytes(kv, 2, encode_attr(v))
            _put_bytes(nd, 5, bytes(kv))
        _put_bytes(out, 1, bytes(nd))
    return bytes(out)
