"""ONNX model import → SameDiff (≡ the reference's planned
nd4j onnx-import module; same role as tf_import for the ONNX ecosystem).

Reuses the dependency-free protobuf wire codec from tfproto — ONNX
ModelProto/GraphProto/NodeProto/TensorProto are just different field
numbers over the same wire format (onnx/onnx.proto). Initializers become
SameDiff constants, graph inputs placeholders, nodes jnp-backed ops; the
imported model compiles to one XLA executable and can be fine-tuned
after convertConstantsToVariables.

Conv/pooling note: ONNX is NCHW; ops run natively NCHW via
lax.conv_general_dilated dimension numbers (XLA lays out for the MXU
either way) — no transpose insertion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.tfproto import (_read_varint, _signed,
                                                 parse_fields)

# ONNX TensorProto.DataType
_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16,
                6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
                11: np.float64}


class UnsupportedOnnxOpError(ValueError):
    pass


def _packed_int64s(vals):
    """repeated int64, packed (proto3 default: one length-delimited blob)
    or unpacked varints."""
    out = []
    for v in vals:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed(x))
        else:
            out.append(_signed(v))
    return out


def parse_onnx_tensor(buf):
    f = parse_fields(buf)
    dims = _packed_int64s(f.get(1, []))
    dtype = _ONNX_DTYPES.get(f.get(2, [1])[0], np.float32)
    if 9 in f and f[9][0]:                       # raw_data
        arr = np.frombuffer(f[9][0], dtype=dtype)
    elif 4 in f:                                 # float_data (packed f32)
        raw = b"".join(v for v in f[4] if isinstance(v, bytes))
        arr = np.frombuffer(raw, "<f4").astype(dtype) if raw else \
            np.asarray([], dtype)
    elif 7 in f:                                 # int64_data
        arr = np.asarray(_packed_int64s(f[7]), dtype)
    else:
        arr = np.zeros(dims or (), dtype)
    name = f.get(8, [b""])[0].decode()
    return name, (arr.reshape(dims) if dims else arr.reshape(()))


def _parse_attr(buf):
    f = parse_fields(buf)
    name = f.get(1, [b""])[0].decode()
    if 2 in f:
        import struct
        return name, struct.unpack("<f", f[2][0])[0]
    if 3 in f:
        return name, _signed(f[3][0])
    if 4 in f:
        return name, f[4][0].decode("utf-8", "replace")
    if 5 in f:
        return name, parse_onnx_tensor(f[5][0])[1]
    if 8 in f:                                   # ints
        return name, _packed_int64s(f[8])
    if 7 in f:                                   # floats (opset-7 Upsample
        import struct                            # scales live here)
        # parse_fields stores every wire-type-5 value as 4-byte chunks and
        # packed lists as one blob — both land here as bytes
        out = []
        for v in f[7]:
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
        return name, out
    return name, None


def _attr(node, name, default):
    v = node.attrs.get(name)
    return default if v is None else v


def _resolve_pads(node, k, s, d, spatial):
    """Effective ((lo, hi), ...) spatial padding for Conv/pools, honoring
    `auto_pad` (SAME_UPPER/SAME_LOWER/VALID) over the explicit `pads`
    attribute — older exporters still emit auto_pad, and ignoring it
    silently imported zero padding (round-1 ADVICE).  `spatial` is the
    static input spatial shape (known at trace time)."""
    auto = _attr(node, "auto_pad", "NOTSET")
    if isinstance(auto, bytes):
        auto = auto.decode()
    if auto in ("NOTSET", ""):
        pads = node.attrs.get("pads") or [0] * (2 * len(spatial))
        n = len(spatial)
        return [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    if auto == "VALID":
        return [(0, 0)] * len(spatial)
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        out = []
        for i, size in enumerate(spatial):
            eff = (int(k[i]) - 1) * int(d[i]) + 1
            o = -(-int(size) // int(s[i]))
            total = max((o - 1) * int(s[i]) + eff - int(size), 0)
            lo = total // 2
            hi = total - lo
            out.append((hi, lo) if auto == "SAME_LOWER" else (lo, hi))
        return out
    raise UnsupportedOnnxOpError(
        f"{node.name}: unsupported auto_pad value {auto!r}")


class OnnxNode:
    def __init__(self, name, op, inputs, outputs, attrs):
        self.name, self.op = name, op
        self.inputs, self.outputs = inputs, outputs
        self.attrs = attrs


def parse_onnx_model(data):
    """ModelProto bytes -> (nodes, initializers{name: arr},
    input_infos{name: dims}, output_names)."""
    model = parse_fields(data)
    graph = parse_fields(model[7][0])            # ModelProto.graph = 7
    inits = {}
    for t in graph.get(5, []):                   # initializer = 5
        name, arr = parse_onnx_tensor(t)
        inits[name] = arr
    nodes = []
    for nb in graph.get(1, []):                  # node = 1
        f = parse_fields(nb)
        attrs = dict(_parse_attr(a) for a in f.get(5, []))
        nodes.append(OnnxNode(
            f.get(3, [b""])[0].decode(),
            f.get(4, [b""])[0].decode(),
            [i.decode() for i in f.get(1, [])],
            [o.decode() for o in f.get(2, [])],
            attrs))
    inputs = {}
    for vi in graph.get(11, []):                 # input = 11
        f = parse_fields(vi)
        nm = f.get(1, [b""])[0].decode()
        dims = []
        if 2 in f:
            tt = parse_fields(f[2][0])
            if 1 in tt:
                shp = parse_fields(tt[1][0])
                if 2 in shp:
                    for d in parse_fields(shp[2][0]).get(1, []):
                        df = parse_fields(d)
                        dims.append(_signed(df[1][0]) if 1 in df else -1)
        inputs[nm] = dims
    outputs = [parse_fields(vi).get(1, [b""])[0].decode()
               for vi in graph.get(12, [])]      # output = 12
    opset = 13                                   # modern default
    for oi in model.get(8, []):                  # opset_import = 8
        f = parse_fields(oi)
        domain = f.get(1, [b""])[0]
        if domain in (b"", b"ai.onnx"):
            opset = _signed(f.get(2, [13])[0])
    return nodes, inits, inputs, outputs, opset


_ONNX_ELEMENTWISE = {
    "Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
    "Div": jnp.divide, "Pow": jnp.power, "Sqrt": jnp.sqrt,
    "Exp": jnp.exp, "Log": jnp.log, "Abs": jnp.abs, "Neg": jnp.negative,
    "Relu": jax.nn.relu, "Sigmoid": jax.nn.sigmoid, "Tanh": jnp.tanh,
    "Erf": jax.lax.erf, "Identity": lambda x: x,
    "Reciprocal": lambda x: 1.0 / x, "Floor": jnp.floor,
    "Ceil": jnp.ceil, "Sign": jnp.sign,
}


class OnnxGraphMapper:
    @staticmethod
    def importModel(path_or_bytes, sd=None):
        data = path_or_bytes
        if not isinstance(data, (bytes, bytearray)):
            with open(data, "rb") as f:
                data = f.read()
        nodes, inits, inputs, outputs, opset = parse_onnx_model(bytes(data))
        sd = sd or SameDiff.create()
        consts = {}
        for name, arr in inits.items():
            sd.constant(name, arr)
            consts[name] = arr
        for name, dims in inputs.items():
            if name in inits:
                continue
            sd.placeHolder(name, *[d if d > 0 else None for d in dims])
        for node in nodes:
            OnnxGraphMapper._map_node(sd, node, consts, opset)
        sd._onnx_outputs = outputs
        return sd

    @staticmethod
    def _map_node(sd, node, consts, opset=13):
        op = node.op
        out = node.outputs[0]
        ins = [sd.getVariable(r) for r in node.inputs if r]

        def const_val(i):
            return consts.get(node.inputs[i])

        if op == "Constant":
            val = node.attrs.get("value")
            consts[out] = np.asarray(val)
            sd.constant(out, np.asarray(val))
            return
        if op in _ONNX_ELEMENTWISE:
            sd._op_named(out, op.lower(), _ONNX_ELEMENTWISE[op], *ins)
        elif op == "MatMul":
            sd._op_named(out, "matmul", jnp.matmul, *ins)
        elif op == "Gemm":
            alpha = float(_attr(node, "alpha", 1.0))
            beta = float(_attr(node, "beta", 1.0))
            ta = int(_attr(node, "transA", 0))
            tb = int(_attr(node, "transB", 0))

            def gemm(a, b, *c, alpha=alpha, beta=beta, ta=ta, tb=tb):
                a = a.T if ta else a
                b = b.T if tb else b
                y = alpha * (a @ b)
                return y + beta * c[0] if c else y
            sd._op_named(out, "gemm", gemm, *ins)
        elif op == "Softmax":
            if opset < 13:
                # opset <13: default axis=1 with coerce-to-2D semantics —
                # softmax over ALL dims from `axis` on, flattened together.
                axis = int(_attr(node, "axis", 1))

                def softmax_2d(x, axis=axis):
                    ax = axis if axis >= 0 else x.ndim + axis
                    lead = int(np.prod(x.shape[:ax])) if ax else 1
                    y = jax.nn.softmax(x.reshape(lead, -1), axis=-1)
                    return y.reshape(x.shape)
                sd._op_named(out, "softmax", softmax_2d, *ins)
            else:
                axis = int(_attr(node, "axis", -1))
                sd._op_named(out, "softmax",
                             lambda x, axis=axis: jax.nn.softmax(
                                 x, axis=axis), *ins)
        elif op == "Reshape":
            shp = const_val(1)
            if shp is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: dynamic Reshape unsupported")
            shp = tuple(int(s) for s in np.asarray(shp).reshape(-1))
            sd._op_named(out, "reshape",
                         lambda x, _s, shp=shp: jnp.reshape(x, shp), *ins)
        elif op == "Transpose":
            perm = node.attrs.get("perm")
            perm = None if perm is None else tuple(int(p) for p in perm)
            sd._op_named(out, "transpose",
                         lambda x, perm=perm: jnp.transpose(x, perm), *ins)
        elif op == "Concat":
            axis = int(_attr(node, "axis", 0))
            sd._op_named(out, "concat",
                         lambda *xs, axis=axis: jnp.concatenate(xs, axis),
                         *ins)
        elif op == "Gather":
            axis = int(_attr(node, "axis", 0))
            sd._op_named(out, "gather",
                         lambda p, i, axis=axis: jnp.take(
                             p, i.astype(jnp.int32), axis=axis), *ins)
        elif op == "Flatten":
            axis = int(_attr(node, "axis", 1))
            sd._op_named(out, "flatten",
                         lambda x, axis=axis: x.reshape(
                             (int(np.prod(x.shape[:axis])), -1)), *ins)
        elif op in ("Squeeze", "Unsqueeze"):
            axes = node.attrs.get("axes")
            if axes is None and len(node.inputs) > 1:
                av = const_val(1)
                axes = None if av is None else np.asarray(
                    av).reshape(-1).tolist()
            axes = tuple(int(a) for a in (axes or []))
            if op == "Squeeze":
                sd._op_named(out, "squeeze",
                             lambda x, *_r, axes=axes: jnp.squeeze(
                                 x, axes or None), *ins)
            else:
                def unsq(x, *_r, axes=axes):
                    for a in sorted(axes):
                        x = jnp.expand_dims(x, a)
                    return x
                sd._op_named(out, "unsqueeze", unsq, *ins)
        elif op == "ReduceMean":
            axes = node.attrs.get("axes")
            if axes is None and len(node.inputs) > 1:   # opset-18: input
                av = const_val(1)
                if av is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic ReduceMean axes unsupported")
                axes = np.asarray(av).reshape(-1).tolist()
            axes = tuple(int(a) for a in (axes or []))
            keep = int(_attr(node, "keepdims", 1))
            sd._op_named(out, "reduce_mean",
                         lambda x, *_r, axes=axes, keep=keep: jnp.mean(
                             x, axis=axes or None, keepdims=bool(keep)),
                         *ins)
        elif op == "Conv":
            strides = tuple(node.attrs.get("strides") or (1, 1))
            dil = tuple(node.attrs.get("dilations") or (1, 1))
            groups = int(_attr(node, "group", 1))

            def conv(x, w, *b, strides=strides, dil=dil, groups=groups,
                     node=node):
                # pads resolved at trace time: auto_pad=SAME_* depends on
                # the (static) input spatial shape
                pad_arg = _resolve_pads(node, w.shape[2:], strides, dil,
                                        x.shape[2:])
                y = jax.lax.conv_general_dilated(
                    x, w.astype(x.dtype), window_strides=strides,
                    padding=pad_arg, rhs_dilation=dil,
                    feature_group_count=groups,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                return y + b[0].reshape(1, -1, 1, 1) if b else y
            sd._op_named(out, "conv", conv, *ins)
        elif op in ("MaxPool", "AveragePool"):
            ksize = tuple(node.attrs.get("kernel_shape") or (2, 2))
            strides = tuple(node.attrs.get("strides") or ksize)
            window = (1, 1) + ksize
            strd = (1, 1) + strides
            ones = (1,) * len(ksize)
            # Module convention: silently-wrong output is worse than a
            # loud unsupported error (ADVICE r4). ceil_mode=1 (common in
            # torch exports) changes output SHAPES; pool dilations change
            # the window footprint — neither maps onto this lowering.
            if int(_attr(node, "ceil_mode", 0)) != 0:
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} ceil_mode=1 unsupported (re-export with "
                    "ceil_mode=0 / torch.onnx ceil_mode=False)")
            pdil = tuple(node.attrs.get("dilations") or ones)
            if any(d != 1 for d in pdil):
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} dilations={pdil} unsupported")
            # count_include_pad=1: divide by the FULL kernel size
            # everywhere (padded zeros count); default 0 divides by the
            # number of real elements under each window.
            include_pad = int(_attr(node, "count_include_pad", 0)) != 0

            def pool_pads(x, node=node, ksize=ksize, strides=strides,
                          ones=ones):
                return [(0, 0), (0, 0)] + _resolve_pads(
                    node, ksize, strides, ones, x.shape[2:])
            if op == "MaxPool":
                sd._op_named(out, "maxpool",
                             lambda x, window=window, strd=strd,
                             pool_pads=pool_pads: jax.lax.reduce_window(
                                 x, -jnp.inf, jax.lax.max, window, strd,
                                 pool_pads(x)), *ins)
            else:
                def avg(x, window=window, strd=strd, pool_pads=pool_pads,
                        include_pad=include_pad, ksize=ksize):
                    pad_arg = pool_pads(x)
                    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window,
                                              strd, pad_arg)
                    if include_pad:
                        return s / float(np.prod(ksize))
                    n = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                              jax.lax.add, window, strd,
                                              pad_arg)
                    return s / n
                sd._op_named(out, "avgpool", avg, *ins)
        elif op == "GlobalAveragePool":
            sd._op_named(out, "gap",
                         lambda x: jnp.mean(x, axis=(2, 3), keepdims=True),
                         *ins)
        elif op == "BatchNormalization":
            eps = float(_attr(node, "epsilon", 1e-5))

            def bn(x, gamma, beta, mean, var, eps=eps):
                shape = (1, -1) + (1,) * (x.ndim - 2)
                return ((x - mean.reshape(shape))
                        * jax.lax.rsqrt(var.reshape(shape) + eps)
                        * gamma.reshape(shape) + beta.reshape(shape))
            sd._op_named(out, "batchnorm", bn, *ins)
        elif op == "Cast":
            to = int(_attr(node, "to", 1))
            np_dt = _ONNX_DTYPES.get(to, np.float32)
            sd._op_named(out, "cast",
                         lambda x, np_dt=np_dt: x.astype(np_dt), *ins)
        elif op == "Clip":
            lo = _attr(node, "min", None)
            hi = _attr(node, "max", None)
            if lo is None and len(node.inputs) > 1 and node.inputs[1]:
                cv = const_val(1)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Clip min unsupported")
                lo = float(np.asarray(cv).reshape(()))
            if hi is None and len(node.inputs) > 2 and node.inputs[2]:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Clip max unsupported")
                hi = float(np.asarray(cv).reshape(()))
            lo = -np.inf if lo is None else float(lo)
            hi = np.inf if hi is None else float(hi)
            sd._op_named(out, "clip",
                         lambda x, *_r, lo=lo, hi=hi: jnp.clip(x, lo, hi),
                         *ins)
        elif op == "LeakyRelu":
            alpha = float(_attr(node, "alpha", 0.01))
            sd._op_named(out, "leakyrelu",
                         lambda x, alpha=alpha: jnp.where(x > 0, x,
                                                          alpha * x), *ins)
        elif op == "Elu":
            alpha = float(_attr(node, "alpha", 1.0))
            sd._op_named(out, "elu",
                         lambda x, alpha=alpha: jnp.where(
                             x > 0, x, alpha * (jnp.exp(x) - 1.0)), *ins)
        elif op == "Softplus":
            sd._op_named(out, "softplus", jax.nn.softplus, *ins)
        elif op == "HardSigmoid":
            alpha = float(_attr(node, "alpha", 0.2))
            beta = float(_attr(node, "beta", 0.5))
            sd._op_named(out, "hardsigmoid",
                         lambda x, a=alpha, b=beta: jnp.clip(
                             a * x + b, 0.0, 1.0), *ins)
        elif op == "ConvTranspose":
            strides = tuple(node.attrs.get("strides") or (1, 1))
            dil = tuple(node.attrs.get("dilations") or (1, 1))
            groups = int(_attr(node, "group", 1))
            out_pad = tuple(node.attrs.get("output_padding") or (0, 0))
            if groups != 1:
                raise UnsupportedOnnxOpError(
                    f"{out}: grouped ConvTranspose unsupported")
            auto_pad = node.attrs.get("auto_pad", b"NOTSET")
            auto_pad = (auto_pad.decode() if isinstance(
                auto_pad, (bytes, bytearray)) else str(auto_pad))
            if auto_pad not in ("NOTSET", ""):
                raise UnsupportedOnnxOpError(
                    f"{out}: ConvTranspose auto_pad={auto_pad!r} "
                    f"unsupported (export with explicit pads)")
            if node.attrs.get("output_shape") is not None:
                raise UnsupportedOnnxOpError(
                    f"{out}: ConvTranspose output_shape unsupported "
                    f"(export with explicit pads)")
            pads = node.attrs.get("pads")

            def convt(x, w, *b, strides=strides, dil=dil, pads=pads,
                      out_pad=out_pad):
                # ONNX weights are (Cin, Cout, kH, kW); the fractionally-
                # strided equivalent conv wants (Cout, Cin, kH, kW) with
                # spatially flipped taps and lhs_dilation = stride
                wf = jnp.flip(w, axis=(-2, -1)).swapaxes(0, 1)
                kh = (w.shape[2] - 1) * dil[0] + 1
                kw = (w.shape[3] - 1) * dil[1] + 1
                p = pads or (0, 0, 0, 0)   # (top, left, bottom, right)
                pad_arg = [(kh - 1 - p[0], kh - 1 - p[2] + out_pad[0]),
                           (kw - 1 - p[1], kw - 1 - p[3] + out_pad[1])]
                y = jax.lax.conv_general_dilated(
                    x, wf.astype(x.dtype), window_strides=(1, 1),
                    padding=pad_arg, lhs_dilation=strides,
                    rhs_dilation=dil,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                return y + b[0].reshape(1, -1, 1, 1) if b else y
            sd._op_named(out, "conv_transpose", convt, *ins)
        elif op == "Pad":
            mode = node.attrs.get("mode", b"constant")
            mode = (mode.decode() if isinstance(mode, (bytes, bytearray))
                    else str(mode))
            pads = node.attrs.get("pads")
            if pads is None:          # opset-11+: pads as input[1]
                pv = const_val(1)
                if pv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Pad unsupported")
                pads = np.asarray(pv).reshape(-1).tolist()
            if len(node.inputs) > 3 and node.inputs[3]:
                raise UnsupportedOnnxOpError(
                    f"{out}: opset-18 Pad axes input unsupported "
                    f"(pads must cover every dimension)")
            cval = 0.0
            if len(node.inputs) > 2 and node.inputs[2]:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant Pad value unsupported")
                cval = float(np.asarray(cv).reshape(()))
            pads = [int(p) for p in pads]
            jmode = {"constant": "constant", "reflect": "reflect",
                     "edge": "edge"}.get(mode)
            if jmode is None:
                raise UnsupportedOnnxOpError(f"{out}: Pad mode {mode!r}")

            def pad(x, *_r, pads=pads, jmode=jmode, cval=cval, name=out):
                n = x.ndim
                if len(pads) != 2 * n:
                    raise UnsupportedOnnxOpError(
                        f"{name}: Pad expects {2 * n} widths for rank-{n} "
                        f"input, got {len(pads)}")
                width = [(pads[i], pads[i + n]) for i in range(n)]
                if jmode == "constant":
                    return jnp.pad(x, width, constant_values=cval)
                return jnp.pad(x, width, mode=jmode)
            sd._op_named(out, "pad", pad, *ins)
        elif op in ("Resize", "Upsample"):
            mode = node.attrs.get("mode", b"nearest")
            mode = (mode.decode() if isinstance(mode, (bytes, bytearray))
                    else str(mode))
            if mode != "nearest":
                raise UnsupportedOnnxOpError(
                    f"{out}: Resize mode {mode!r} unsupported (nearest "
                    f"only)")
            # input layouts differ: Upsample = [X, scales] (or a scales
            # attr at opset 7); Resize = [X, roi, scales, sizes], where
            # scales may be an EMPTY name with sizes given instead —
            # never guess by tensor size, index by position
            scales = node.attrs.get("scales")
            sizes = None
            # opset-10 Resize is [X, scales]; opset-11+ adds roi at idx 1
            scales_idx = (1 if op == "Upsample" or len(node.inputs) == 2
                          else 2)
            if scales is None and len(node.inputs) > scales_idx \
                    and node.inputs[scales_idx]:
                cv = const_val(scales_idx)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant {op} scales unsupported")
                scales = np.asarray(cv).reshape(-1).tolist()
            if scales is None and op == "Resize" and \
                    len(node.inputs) > 3 and node.inputs[3]:
                cv = const_val(3)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant Resize sizes unsupported")
                sizes = [int(s) for s in np.asarray(cv).reshape(-1)]
            if scales is None and sizes is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} needs constant NCHW scales or sizes")
            if scales is not None:
                if float(scales[0]) != 1.0 or float(scales[1]) != 1.0:
                    raise UnsupportedOnnxOpError(
                        f"{out}: {op} batch/channel scales must be 1, "
                        f"got {scales[:2]}")
                sh, sw = float(scales[2]), float(scales[3])
                if sh != int(sh) or sw != int(sw) or sh < 1 or sw < 1:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-integer upsample scales ({sh}, {sw})")

            def resize(x, *_r, scales=scales, sizes=sizes, name=out):
                if scales is not None:
                    sh, sw = int(scales[2]), int(scales[3])
                else:
                    if sizes[0] != x.shape[0] or sizes[1] != x.shape[1]:
                        raise UnsupportedOnnxOpError(
                            f"{name}: Resize sizes may not change "
                            f"batch/channel dims")
                    if sizes[2] % x.shape[2] or sizes[3] % x.shape[3]:
                        raise UnsupportedOnnxOpError(
                            f"{name}: Resize sizes {sizes[2:]} are not "
                            f"integer multiples of input "
                            f"{x.shape[2:]}")
                    sh = sizes[2] // x.shape[2]
                    sw = sizes[3] // x.shape[3]
                return jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)
            sd._op_named(out, "resize", resize, *ins)
        else:
            raise UnsupportedOnnxOpError(
                f"ONNX op '{op}' (node '{out}') is not in the import set")


def importOnnx(path_or_bytes):
    return OnnxGraphMapper.importModel(path_or_bytes)


SameDiff.importOnnx = staticmethod(importOnnx)
