"""ONNX model import → SameDiff (≡ the reference's planned
nd4j onnx-import module; same role as tf_import for the ONNX ecosystem).

Reuses the dependency-free protobuf wire codec from tfproto — ONNX
ModelProto/GraphProto/NodeProto/TensorProto are just different field
numbers over the same wire format (onnx/onnx.proto). Initializers become
SameDiff constants, graph inputs placeholders, nodes jnp-backed ops; the
imported model compiles to one XLA executable and can be fine-tuned
after convertConstantsToVariables.

Conv/pooling note: ONNX is NCHW; ops run natively NCHW via
lax.conv_general_dilated dimension numbers (XLA lays out for the MXU
either way) — no transpose insertion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.tfproto import (_read_varint, _signed,
                                                 parse_fields)

# ONNX TensorProto.DataType
_ONNX_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16,
                6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
                11: np.float64}


class UnsupportedOnnxOpError(ValueError):
    pass


def _packed_int64s(vals):
    """repeated int64, packed (proto3 default: one length-delimited blob)
    or unpacked varints."""
    out = []
    for v in vals:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed(x))
        else:
            out.append(_signed(v))
    return out


def parse_onnx_tensor(buf):
    f = parse_fields(buf)
    dims = _packed_int64s(f.get(1, []))
    dtype = _ONNX_DTYPES.get(f.get(2, [1])[0], np.float32)
    if 9 in f and f[9][0]:                       # raw_data
        arr = np.frombuffer(f[9][0], dtype=dtype)
    elif 4 in f:                                 # float_data (packed f32)
        raw = b"".join(v for v in f[4] if isinstance(v, bytes))
        arr = np.frombuffer(raw, "<f4").astype(dtype) if raw else \
            np.asarray([], dtype)
    elif 7 in f:                                 # int64_data
        arr = np.asarray(_packed_int64s(f[7]), dtype)
    else:
        arr = np.zeros(dims or (), dtype)
    name = f.get(8, [b""])[0].decode()
    return name, (arr.reshape(dims) if dims else arr.reshape(()))


def _parse_attr(buf):
    f = parse_fields(buf)
    name = f.get(1, [b""])[0].decode()
    if 2 in f:
        import struct
        return name, struct.unpack("<f", f[2][0])[0]
    if 3 in f:
        return name, _signed(f[3][0])
    if 4 in f:
        return name, f[4][0].decode("utf-8", "replace")
    if 5 in f:
        return name, parse_onnx_tensor(f[5][0])[1]
    if 8 in f:                                   # ints
        return name, _packed_int64s(f[8])
    if 7 in f:                                   # floats (opset-7 Upsample
        import struct                            # scales live here)
        # parse_fields stores every wire-type-5 value as 4-byte chunks and
        # packed lists as one blob — both land here as bytes
        out = []
        for v in f[7]:
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
        return name, out
    return name, None


def _attr(node, name, default):
    v = node.attrs.get(name)
    return default if v is None else v


def _pads_params(node):
    """The (auto_pad, pads) attribute pair as plain JSON values — what a
    serialized conv/pool node needs to re-resolve its padding at trace
    time (graph_serde: params must be data, not objects)."""
    auto = _attr(node, "auto_pad", "NOTSET")
    if isinstance(auto, bytes):
        auto = auto.decode()
    pads = node.attrs.get("pads")
    return auto, (None if pads is None else [int(p) for p in pads])


def _resolve_pads(auto, pads, k, s, d, spatial, name=""):
    """Effective ((lo, hi), ...) spatial padding for Conv/pools, honoring
    `auto_pad` (SAME_UPPER/SAME_LOWER/VALID) over the explicit `pads`
    attribute — older exporters still emit auto_pad, and ignoring it
    silently imported zero padding (round-1 ADVICE).  `spatial` is the
    static input spatial shape (known at trace time)."""
    if auto in ("NOTSET", ""):
        pads = pads or [0] * (2 * len(spatial))
        n = len(spatial)
        return [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    if auto == "VALID":
        return [(0, 0)] * len(spatial)
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        out = []
        for i, size in enumerate(spatial):
            eff = (int(k[i]) - 1) * int(d[i]) + 1
            o = -(-int(size) // int(s[i]))
            total = max((o - 1) * int(s[i]) + eff - int(size), 0)
            lo = total // 2
            hi = total - lo
            out.append((hi, lo) if auto == "SAME_LOWER" else (lo, hi))
        return out
    raise UnsupportedOnnxOpError(
        f"{name}: unsupported auto_pad value {auto!r}")


class OnnxNode:
    def __init__(self, name, op, inputs, outputs, attrs):
        self.name, self.op = name, op
        self.inputs, self.outputs = inputs, outputs
        self.attrs = attrs


def parse_onnx_model(data):
    """ModelProto bytes -> (nodes, initializers{name: arr},
    input_infos{name: dims}, output_names)."""
    model = parse_fields(data)
    graph = parse_fields(model[7][0])            # ModelProto.graph = 7
    inits = {}
    for t in graph.get(5, []):                   # initializer = 5
        name, arr = parse_onnx_tensor(t)
        inits[name] = arr
    nodes = []
    for nb in graph.get(1, []):                  # node = 1
        f = parse_fields(nb)
        attrs = dict(_parse_attr(a) for a in f.get(5, []))
        nodes.append(OnnxNode(
            f.get(3, [b""])[0].decode(),
            f.get(4, [b""])[0].decode(),
            [i.decode() for i in f.get(1, [])],
            [o.decode() for o in f.get(2, [])],
            attrs))
    inputs = {}
    for vi in graph.get(11, []):                 # input = 11
        f = parse_fields(vi)
        nm = f.get(1, [b""])[0].decode()
        dims = []
        if 2 in f:
            tt = parse_fields(f[2][0])
            if 1 in tt:
                shp = parse_fields(tt[1][0])
                if 2 in shp:
                    for d in parse_fields(shp[2][0]).get(1, []):
                        df = parse_fields(d)
                        dims.append(_signed(df[1][0]) if 1 in df else -1)
        inputs[nm] = dims
    outputs = [parse_fields(vi).get(1, [b""])[0].decode()
               for vi in graph.get(12, [])]      # output = 12
    opset = 13                                   # modern default
    for oi in model.get(8, []):                  # opset_import = 8
        f = parse_fields(oi)
        domain = f.get(1, [b""])[0]
        if domain in (b"", b"ai.onnx"):
            opset = _signed(f.get(2, [13])[0])
    return nodes, inits, inputs, outputs, opset


_ONNX_ELEMENTWISE = {
    "Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
    "Div": jnp.divide, "Pow": jnp.power, "Sqrt": jnp.sqrt,
    "Exp": jnp.exp, "Log": jnp.log, "Abs": jnp.abs, "Neg": jnp.negative,
    "Relu": jax.nn.relu, "Sigmoid": jax.nn.sigmoid, "Tanh": jnp.tanh,
    "Erf": jax.lax.erf, "Identity": lambda x: x,
    "Reciprocal": lambda x: 1.0 / x, "Floor": jnp.floor,
    "Ceil": jnp.ceil, "Sign": jnp.sign,
}

# -- serializable op builders (graph_serde registry, "onnx." namespace) --
# Every imported node lowers to (opname, params) with params plain JSON, so
# an imported-then-saved graph restores with no ONNX file and no user code
# (VERDICT r4 #3: the import paths must be durable).
from deeplearning4j_tpu.autodiff.graph_serde import op_builder  # noqa: E402

for _opn, _fn in _ONNX_ELEMENTWISE.items():
    op_builder("onnx." + _opn.lower())((lambda f: lambda: f)(_fn))
op_builder("onnx.matmul")(lambda: jnp.matmul)
op_builder("onnx.softplus")(lambda: jax.nn.softplus)
op_builder("onnx.gap")(
    lambda: lambda x: jnp.mean(x, axis=tuple(range(2, x.ndim)),
                               keepdims=True))


@op_builder("onnx.gemm")
def _b_gemm(alpha=1.0, beta=1.0, ta=0, tb=0):
    def gemm(a, b, *c):
        a = a.T if ta else a
        b = b.T if tb else b
        y = alpha * (a @ b)
        return y + beta * c[0] if c else y
    return gemm


@op_builder("onnx.softmax")
def _b_softmax(axis=-1):
    return lambda x: jax.nn.softmax(x, axis=axis)


@op_builder("onnx.softmax_2d")
def _b_softmax_2d(axis=1):
    # opset <13 coerce-to-2D semantics: softmax over ALL dims from `axis`
    # on, flattened together
    def softmax_2d(x):
        ax = axis if axis >= 0 else x.ndim + axis
        lead = int(np.prod(x.shape[:ax])) if ax else 1
        y = jax.nn.softmax(x.reshape(lead, -1), axis=-1)
        return y.reshape(x.shape)
    return softmax_2d


@op_builder("onnx.reshape")
def _b_reshape(shape):
    return lambda x, *_r: jnp.reshape(x, tuple(shape))


@op_builder("onnx.transpose")
def _b_transpose(perm=None):
    p = None if perm is None else tuple(perm)
    return lambda x: jnp.transpose(x, p)


@op_builder("onnx.concat")
def _b_concat(axis=0):
    return lambda *xs: jnp.concatenate(xs, axis)


@op_builder("onnx.gather")
def _b_gather(axis=0):
    return lambda p, i: jnp.take(p, i.astype(jnp.int32), axis=axis)


@op_builder("onnx.flatten")
def _b_flatten(axis=1):
    return lambda x: x.reshape((int(np.prod(x.shape[:axis])), -1))


@op_builder("onnx.squeeze")
def _b_squeeze(axes=()):
    ax = tuple(axes)
    return lambda x, *_r: jnp.squeeze(x, ax or None)


@op_builder("onnx.unsqueeze")
def _b_unsqueeze(axes=()):
    def unsq(x, *_r):
        for a in sorted(axes):
            x = jnp.expand_dims(x, a)
        return x
    return unsq


def _onnx_reduce_builder(fn):
    def build(axes=(), keep=1):
        ax = tuple(axes)
        return lambda x, *_r: fn(x, axis=ax or None, keepdims=bool(keep))
    return build


for _rop, _rfn in [("reduce_mean", jnp.mean), ("reduce_sum", jnp.sum),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min)]:
    op_builder("onnx." + _rop)(_onnx_reduce_builder(_rfn))
# global pools reduce every spatial dim (ONNX defines them for rank >= 3)
op_builder("onnx.gmp")(
    lambda: lambda x: jnp.max(x, axis=tuple(range(2, x.ndim)),
                              keepdims=True))


@op_builder("onnx.slice")
def _b_slice(axes, starts, ends, steps):
    def f(x, *_r):
        sl = [slice(None)] * x.ndim
        for a, st, en, sp in zip(axes, starts, ends, steps):
            sl[a if a >= 0 else x.ndim + a] = slice(st, en, sp)
        return x[tuple(sl)]
    return f





@op_builder("onnx.conv")
def _b_conv(strides=(1, 1), dil=(1, 1), groups=1, auto_pad="NOTSET",
            pads=None, name=""):
    st, dl = tuple(strides), tuple(dil)

    def conv(x, w, *b):
        # pads resolved at trace time: auto_pad=SAME_* depends on the
        # (static) input spatial shape
        pad_arg = _resolve_pads(auto_pad, pads, w.shape[2:], st, dl,
                                x.shape[2:], name)
        y = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), window_strides=st,
            padding=pad_arg, rhs_dilation=dl,
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y + b[0].reshape(1, -1, 1, 1) if b else y
    return conv


@op_builder("onnx.maxpool")
def _b_maxpool(ksize, strides, auto_pad="NOTSET", pads=None, name=""):
    k, s = tuple(ksize), tuple(strides)
    window, strd = (1, 1) + k, (1, 1) + s
    ones = (1,) * len(k)

    def f(x):
        pad_arg = [(0, 0), (0, 0)] + _resolve_pads(auto_pad, pads, k, s,
                                                   ones, x.shape[2:], name)
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                     strd, pad_arg)
    return f


@op_builder("onnx.avgpool")
def _b_avgpool(ksize, strides, auto_pad="NOTSET", pads=None,
               include_pad=False, name=""):
    k, s = tuple(ksize), tuple(strides)
    window, strd = (1, 1) + k, (1, 1) + s
    ones = (1,) * len(k)

    def avg(x):
        pad_arg = [(0, 0), (0, 0)] + _resolve_pads(auto_pad, pads, k, s,
                                                   ones, x.shape[2:], name)
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strd,
                                       pad_arg)
        if include_pad:
            # padded zeros COUNT: divide by the full kernel size
            return summed / float(np.prod(k))
        n = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                  window, strd, pad_arg)
        return summed / n
    return avg


@op_builder("onnx.batchnorm")
def _b_batchnorm(eps=1e-5):
    def bn(x, gamma, beta, mean, var):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mean.reshape(shape))
                * jax.lax.rsqrt(var.reshape(shape) + eps)
                * gamma.reshape(shape) + beta.reshape(shape))
    return bn


@op_builder("onnx.cast")
def _b_cast(to=1):
    np_dt = _ONNX_DTYPES.get(int(to), np.float32)
    return lambda x: x.astype(np_dt)


@op_builder("onnx.clip")
def _b_clip(lo, hi):
    # open bounds travel as null (strict-JSON artifact), not Infinity
    l = -np.inf if lo is None else lo
    h = np.inf if hi is None else hi
    return lambda x, *_r: jnp.clip(x, l, h)


@op_builder("onnx.leakyrelu")
def _b_leakyrelu(alpha=0.01):
    return lambda x: jnp.where(x > 0, x, alpha * x)


@op_builder("onnx.elu")
def _b_elu(alpha=1.0):
    return lambda x: jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))


@op_builder("onnx.hardsigmoid")
def _b_hardsigmoid(alpha=0.2, beta=0.5):
    return lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0)


@op_builder("onnx.conv_transpose")
def _b_conv_transpose(strides=(1, 1), dil=(1, 1), pads=None,
                      out_pad=(0, 0)):
    st, dl, op_ = tuple(strides), tuple(dil), tuple(out_pad)

    def convt(x, w, *b):
        # ONNX weights are (Cin, Cout, kH, kW); the fractionally-strided
        # equivalent conv wants (Cout, Cin, kH, kW) with spatially flipped
        # taps and lhs_dilation = stride
        wf = jnp.flip(w, axis=(-2, -1)).swapaxes(0, 1)
        kh = (w.shape[2] - 1) * dl[0] + 1
        kw = (w.shape[3] - 1) * dl[1] + 1
        p = pads or (0, 0, 0, 0)   # (top, left, bottom, right)
        pad_arg = [(kh - 1 - p[0], kh - 1 - p[2] + op_[0]),
                   (kw - 1 - p[1], kw - 1 - p[3] + op_[1])]
        y = jax.lax.conv_general_dilated(
            x, wf.astype(x.dtype), window_strides=(1, 1),
            padding=pad_arg, lhs_dilation=st, rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y + b[0].reshape(1, -1, 1, 1) if b else y
    return convt


@op_builder("onnx.pad")
def _b_pad(pads, jmode="constant", cval=0.0, name=""):
    def pad(x, *_r):
        n = x.ndim
        if len(pads) != 2 * n:
            raise UnsupportedOnnxOpError(
                f"{name}: Pad expects {2 * n} widths for rank-{n} "
                f"input, got {len(pads)}")
        width = [(pads[i], pads[i + n]) for i in range(n)]
        if jmode == "constant":
            return jnp.pad(x, width, constant_values=cval)
        return jnp.pad(x, width, mode=jmode)
    return pad


@op_builder("onnx.resize")
def _b_resize(scales=None, sizes=None, name=""):
    def resize(x, *_r):
        if scales is not None:
            sh, sw = int(scales[2]), int(scales[3])
        else:
            if sizes[0] != x.shape[0] or sizes[1] != x.shape[1]:
                raise UnsupportedOnnxOpError(
                    f"{name}: Resize sizes may not change "
                    f"batch/channel dims")
            if sizes[2] % x.shape[2] or sizes[3] % x.shape[3]:
                raise UnsupportedOnnxOpError(
                    f"{name}: Resize sizes {sizes[2:]} are not "
                    f"integer multiples of input {x.shape[2:]}")
            sh = sizes[2] // x.shape[2]
            sw = sizes[3] // x.shape[3]
        return jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)
    return resize


class OnnxGraphMapper:
    @staticmethod
    def importModel(path_or_bytes, sd=None):
        data = path_or_bytes
        if not isinstance(data, (bytes, bytearray)):
            with open(data, "rb") as f:
                data = f.read()
        nodes, inits, inputs, outputs, opset = parse_onnx_model(bytes(data))
        sd = sd or SameDiff.create()
        consts = {}
        for name, arr in inits.items():
            sd.constant(name, arr)
            consts[name] = arr
        for name, dims in inputs.items():
            if name in inits:
                continue
            sd.placeHolder(name, *[d if d > 0 else None for d in dims])
        for node in nodes:
            OnnxGraphMapper._map_node(sd, node, consts, opset)
        sd._onnx_outputs = outputs
        return sd

    @staticmethod
    def _map_node(sd, node, consts, opset=13):
        op = node.op
        out = node.outputs[0]
        ins = [sd.getVariable(r) for r in node.inputs if r]

        def const_val(i):
            return consts.get(node.inputs[i])

        if op == "Constant":
            val = node.attrs.get("value")
            consts[out] = np.asarray(val)
            sd.constant(out, np.asarray(val))
            return
        if op in _ONNX_ELEMENTWISE:
            sd._op_named(out, "onnx." + op.lower(), None, *ins, params={})
        elif op == "MatMul":
            sd._op_named(out, "onnx.matmul", None, *ins, params={})
        elif op == "Gemm":
            sd._op_named(out, "onnx.gemm", None, *ins, params={
                "alpha": float(_attr(node, "alpha", 1.0)),
                "beta": float(_attr(node, "beta", 1.0)),
                "ta": int(_attr(node, "transA", 0)),
                "tb": int(_attr(node, "transB", 0))})
        elif op == "Softmax":
            if opset < 13:
                sd._op_named(out, "onnx.softmax_2d", None, *ins,
                             params={"axis": int(_attr(node, "axis", 1))})
            else:
                sd._op_named(out, "onnx.softmax", None, *ins,
                             params={"axis": int(_attr(node, "axis", -1))})
        elif op == "Reshape":
            shp = const_val(1)
            if shp is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: dynamic Reshape unsupported")
            shp = [int(s) for s in np.asarray(shp).reshape(-1)]
            sd._op_named(out, "onnx.reshape", None, *ins,
                         params={"shape": shp})
        elif op == "Transpose":
            perm = node.attrs.get("perm")
            perm = None if perm is None else [int(p) for p in perm]
            sd._op_named(out, "onnx.transpose", None, *ins,
                         params={"perm": perm})
        elif op == "Concat":
            sd._op_named(out, "onnx.concat", None, *ins,
                         params={"axis": int(_attr(node, "axis", 0))})
        elif op == "Gather":
            sd._op_named(out, "onnx.gather", None, *ins,
                         params={"axis": int(_attr(node, "axis", 0))})
        elif op == "Flatten":
            sd._op_named(out, "onnx.flatten", None, *ins,
                         params={"axis": int(_attr(node, "axis", 1))})
        elif op in ("Squeeze", "Unsqueeze"):
            axes = node.attrs.get("axes")
            if axes is None and len(node.inputs) > 1:
                av = const_val(1)
                axes = None if av is None else np.asarray(
                    av).reshape(-1).tolist()
            axes = [int(a) for a in (axes or [])]
            sd._op_named(out, "onnx." + op.lower(), None, *ins,
                         params={"axes": axes})
        elif op in ("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin"):
            axes = node.attrs.get("axes")
            if axes is None and len(node.inputs) > 1 and node.inputs[1]:
                av = const_val(1)   # opset-13/18+: axes as input
                if av is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic {op} axes unsupported")
                axes = np.asarray(av).reshape(-1).tolist()
            if not axes and int(_attr(node, "noop_with_empty_axes", 0)):
                # spec: empty axes + the flag == identity, NOT reduce-all
                sd._op_named(out, "onnx.identity", None, ins[0], params={})
            else:
                sd._op_named(out, "onnx.reduce_" + op[6:].lower(), None,
                             *ins, params={
                                 "axes": [int(a) for a in (axes or [])],
                                 "keep": int(_attr(node, "keepdims", 1))})
        elif op == "GlobalMaxPool":
            sd._op_named(out, "onnx.gmp", None, *ins, params={})
        elif op == "Slice":
            starts = node.attrs.get("starts")
            ends = node.attrs.get("ends")
            axes = node.attrs.get("axes")
            steps = None
            if starts is None:        # opset-10+: inputs 1..4
                def _slice_cv(i):
                    if len(node.inputs) > i and node.inputs[i]:
                        av = const_val(i)
                        if av is None:
                            raise UnsupportedOnnxOpError(
                                f"{out}: dynamic Slice unsupported")
                        return np.asarray(av).reshape(-1).tolist()
                    return None
                starts, ends = _slice_cv(1), _slice_cv(2)
                axes, steps = _slice_cv(3), _slice_cv(4)
            if starts is None or ends is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: Slice needs constant starts/ends")
            n_ = len(starts)
            axes = (list(range(n_)) if axes is None
                    else [int(a) for a in axes])
            steps = ([1] * n_ if steps is None
                     else [int(x_) for x_ in steps])
            # clamp ONNX's INT64_MAX "to the end" sentinels into python
            # slice range
            big = 2 ** 31
            sd._op_named(out, "onnx.slice", None, *ins, params={
                "axes": axes,
                "starts": [int(max(-big, min(big, v))) for v in starts],
                "ends": [int(max(-big, min(big, v))) for v in ends],
                "steps": steps})
        elif op == "Split":
            axis = int(_attr(node, "axis", 0))
            sizes = node.attrs.get("split")
            if sizes is None and len(node.inputs) > 1 and node.inputs[1]:
                av = const_val(1)   # opset-13+: split sizes as input
                if av is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Split sizes unsupported")
                sizes = np.asarray(av).reshape(-1).tolist()
            if sizes is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: Split without explicit sizes unsupported "
                    "(equal split needs a static dim — export with the "
                    "'split' attribute/input)")
            off = 0
            for i, o_name in enumerate(node.outputs):
                sd._op_named(o_name, "slice_axis", None, *ins,
                             params={"axis": axis, "start": off,
                                     "size": int(sizes[i])})
                off += int(sizes[i])
        elif op == "Conv":
            auto, pads = _pads_params(node)
            sd._op_named(out, "onnx.conv", None, *ins, params={
                "strides": [int(s) for s in
                            (node.attrs.get("strides") or (1, 1))],
                "dil": [int(d) for d in
                        (node.attrs.get("dilations") or (1, 1))],
                "groups": int(_attr(node, "group", 1)),
                "auto_pad": auto, "pads": pads, "name": out})
        elif op in ("MaxPool", "AveragePool"):
            ksize = [int(k) for k in
                     (node.attrs.get("kernel_shape") or (2, 2))]
            strides = [int(s) for s in
                       (node.attrs.get("strides") or ksize)]
            # Module convention: silently-wrong output is worse than a
            # loud unsupported error (ADVICE r4). ceil_mode=1 (common in
            # torch exports) changes output SHAPES; pool dilations change
            # the window footprint — neither maps onto this lowering.
            if int(_attr(node, "ceil_mode", 0)) != 0:
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} ceil_mode=1 unsupported (re-export with "
                    "ceil_mode=0 / torch.onnx ceil_mode=False)")
            pdil = tuple(node.attrs.get("dilations") or (1,) * len(ksize))
            if any(d != 1 for d in pdil):
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} dilations={pdil} unsupported")
            auto, pads = _pads_params(node)
            params = {"ksize": ksize, "strides": strides,
                      "auto_pad": auto, "pads": pads, "name": out}
            if op == "MaxPool":
                sd._op_named(out, "onnx.maxpool", None, *ins, params=params)
            else:
                # count_include_pad=1: divide by the FULL kernel size
                # everywhere (padded zeros count); default 0 divides by
                # the number of real elements under each window.
                params["include_pad"] = \
                    int(_attr(node, "count_include_pad", 0)) != 0
                sd._op_named(out, "onnx.avgpool", None, *ins, params=params)
        elif op == "GlobalAveragePool":
            sd._op_named(out, "onnx.gap", None, *ins, params={})
        elif op == "BatchNormalization":
            sd._op_named(out, "onnx.batchnorm", None, *ins, params={
                "eps": float(_attr(node, "epsilon", 1e-5))})
        elif op == "Cast":
            sd._op_named(out, "onnx.cast", None, *ins,
                         params={"to": int(_attr(node, "to", 1))})
        elif op == "Clip":
            lo = _attr(node, "min", None)
            hi = _attr(node, "max", None)
            if lo is None and len(node.inputs) > 1 and node.inputs[1]:
                cv = const_val(1)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Clip min unsupported")
                lo = float(np.asarray(cv).reshape(()))
            if hi is None and len(node.inputs) > 2 and node.inputs[2]:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Clip max unsupported")
                hi = float(np.asarray(cv).reshape(()))
            sd._op_named(out, "onnx.clip", None, *ins, params={
                "lo": None if lo is None else float(lo),
                "hi": None if hi is None else float(hi)})
        elif op == "LeakyRelu":
            sd._op_named(out, "onnx.leakyrelu", None, *ins,
                         params={"alpha": float(_attr(node, "alpha", 0.01))})
        elif op == "Elu":
            sd._op_named(out, "onnx.elu", None, *ins,
                         params={"alpha": float(_attr(node, "alpha", 1.0))})
        elif op == "Softplus":
            sd._op_named(out, "onnx.softplus", None, *ins, params={})
        elif op == "HardSigmoid":
            sd._op_named(out, "onnx.hardsigmoid", None, *ins, params={
                "alpha": float(_attr(node, "alpha", 0.2)),
                "beta": float(_attr(node, "beta", 0.5))})
        elif op == "ConvTranspose":
            strides = tuple(node.attrs.get("strides") or (1, 1))
            dil = tuple(node.attrs.get("dilations") or (1, 1))
            groups = int(_attr(node, "group", 1))
            out_pad = tuple(node.attrs.get("output_padding") or (0, 0))
            if groups != 1:
                raise UnsupportedOnnxOpError(
                    f"{out}: grouped ConvTranspose unsupported")
            auto_pad = node.attrs.get("auto_pad", b"NOTSET")
            auto_pad = (auto_pad.decode() if isinstance(
                auto_pad, (bytes, bytearray)) else str(auto_pad))
            if auto_pad not in ("NOTSET", ""):
                raise UnsupportedOnnxOpError(
                    f"{out}: ConvTranspose auto_pad={auto_pad!r} "
                    f"unsupported (export with explicit pads)")
            if node.attrs.get("output_shape") is not None:
                raise UnsupportedOnnxOpError(
                    f"{out}: ConvTranspose output_shape unsupported "
                    f"(export with explicit pads)")
            pads = node.attrs.get("pads")
            sd._op_named(out, "onnx.conv_transpose", None, *ins, params={
                "strides": [int(s) for s in strides],
                "dil": [int(d) for d in dil],
                "pads": None if pads is None else [int(p) for p in pads],
                "out_pad": [int(p) for p in out_pad]})
        elif op == "Pad":
            mode = node.attrs.get("mode", b"constant")
            mode = (mode.decode() if isinstance(mode, (bytes, bytearray))
                    else str(mode))
            pads = node.attrs.get("pads")
            if pads is None:          # opset-11+: pads as input[1]
                pv = const_val(1)
                if pv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: dynamic Pad unsupported")
                pads = np.asarray(pv).reshape(-1).tolist()
            if len(node.inputs) > 3 and node.inputs[3]:
                raise UnsupportedOnnxOpError(
                    f"{out}: opset-18 Pad axes input unsupported "
                    f"(pads must cover every dimension)")
            cval = 0.0
            if len(node.inputs) > 2 and node.inputs[2]:
                cv = const_val(2)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant Pad value unsupported")
                cval = float(np.asarray(cv).reshape(()))
            pads = [int(p) for p in pads]
            jmode = {"constant": "constant", "reflect": "reflect",
                     "edge": "edge"}.get(mode)
            if jmode is None:
                raise UnsupportedOnnxOpError(f"{out}: Pad mode {mode!r}")
            sd._op_named(out, "onnx.pad", None, *ins, params={
                "pads": pads, "jmode": jmode, "cval": cval, "name": out})
        elif op in ("Resize", "Upsample"):
            mode = node.attrs.get("mode", b"nearest")
            mode = (mode.decode() if isinstance(mode, (bytes, bytearray))
                    else str(mode))
            if mode != "nearest":
                raise UnsupportedOnnxOpError(
                    f"{out}: Resize mode {mode!r} unsupported (nearest "
                    f"only)")
            # input layouts differ: Upsample = [X, scales] (or a scales
            # attr at opset 7); Resize = [X, roi, scales, sizes], where
            # scales may be an EMPTY name with sizes given instead —
            # never guess by tensor size, index by position
            scales = node.attrs.get("scales")
            sizes = None
            # opset-10 Resize is [X, scales]; opset-11+ adds roi at idx 1
            scales_idx = (1 if op == "Upsample" or len(node.inputs) == 2
                          else 2)
            if scales is None and len(node.inputs) > scales_idx \
                    and node.inputs[scales_idx]:
                cv = const_val(scales_idx)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant {op} scales unsupported")
                scales = np.asarray(cv).reshape(-1).tolist()
            if scales is None and op == "Resize" and \
                    len(node.inputs) > 3 and node.inputs[3]:
                cv = const_val(3)
                if cv is None:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-constant Resize sizes unsupported")
                sizes = [int(s) for s in np.asarray(cv).reshape(-1)]
            if scales is None and sizes is None:
                raise UnsupportedOnnxOpError(
                    f"{out}: {op} needs constant NCHW scales or sizes")
            if scales is not None:
                if float(scales[0]) != 1.0 or float(scales[1]) != 1.0:
                    raise UnsupportedOnnxOpError(
                        f"{out}: {op} batch/channel scales must be 1, "
                        f"got {scales[:2]}")
                sh, sw = float(scales[2]), float(scales[3])
                if sh != int(sh) or sw != int(sw) or sh < 1 or sw < 1:
                    raise UnsupportedOnnxOpError(
                        f"{out}: non-integer upsample scales ({sh}, {sw})")

            sd._op_named(out, "onnx.resize", None, *ins, params={
                "scales": None if scales is None else [float(s)
                                                      for s in scales],
                "sizes": sizes, "name": out})
        else:
            raise UnsupportedOnnxOpError(
                f"ONNX op '{op}' (node '{out}') is not in the import set")


def importOnnx(path_or_bytes):
    return OnnxGraphMapper.importModel(path_or_bytes)


SameDiff.importOnnx = staticmethod(importOnnx)
