"""WordVectorSerializer (≡ deeplearning4j-nlp ::
loader.WordVectorSerializer) — exchange embeddings with the standard
word2vec C formats.

Formats:
- TEXT  (word2vec -binary 0): header "V D\\n", then "word f1 f2 ... fD\\n".
- BINARY (word2vec -binary 1): header "V D\\n", then per word the
  whitespace-terminated token followed by D little-endian float32s and a
  trailing newline.

`readWord2VecModel` auto-detects the format; `loadStaticModel` returns a
lookup-only StaticWordVectors (the reference's memory-mapped static model —
here a plain numpy table: the vectors feed jnp lookups or an
EmbeddingLayer via `embeddingLayerWeights`)."""
from __future__ import annotations

import os

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import WordVectors


class StaticWordVectors(WordVectors):
    """Lookup-only vectors (no trainer attached)."""

    def __init__(self, table, words):
        self._np_table = np.asarray(table, np.float32)
        self.vocab = VocabCache()
        for w in words:
            self.vocab.add(w)
        # WordVectors._table reads params["syn0"]
        self.params = {"syn0": self._np_table}

    def _table(self):
        return self._np_table

    @property
    def layer_size(self):
        return self._np_table.shape[1]


class WordVectorSerializer:
    """≡ loader.WordVectorSerializer (static-method surface)."""

    # -- write -----------------------------------------------------------
    @staticmethod
    def writeWord2VecModel(vectors, path, binary=False):
        """Write vectors in word2vec C format (text by default)."""
        table = vectors._table()
        vocab = vectors.vocab
        v, d = table.shape
        if binary:
            with open(path, "wb") as f:
                f.write(f"{v} {d}\n".encode("utf-8"))
                for i in range(v):
                    word = vocab.wordAtIndex(i)
                    f.write(word.encode("utf-8") + b" ")
                    f.write(table[i].astype("<f4").tobytes())
                    f.write(b"\n")
        else:
            with open(path, "w", encoding="utf-8") as f:
                f.write(f"{v} {d}\n")
                for i in range(v):
                    word = vocab.wordAtIndex(i)
                    vals = " ".join(f"{x:.6f}" for x in table[i])
                    f.write(f"{word} {vals}\n")

    # reference-compat aliases
    writeWordVectors = writeWord2VecModel

    # -- read ------------------------------------------------------------
    @staticmethod
    def _read_text(path):
        words, rows = [], []
        with open(path, "r", encoding="utf-8") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < d + 1:
                    continue
                words.append(parts[0])
                rows.append(np.asarray(parts[1:d + 1], np.float32))
        if len(words) != v:
            raise ValueError(
                f"{path}: header promises {v} words, file has {len(words)}")
        return np.stack(rows), words

    @staticmethod
    def _read_binary(path):
        with open(path, "rb") as f:
            header = f.readline().decode("utf-8").split()
            v, d = int(header[0]), int(header[1])
            words, rows = [], []
            for _ in range(v):
                chars = []
                while True:
                    c = f.read(1)
                    if not c or c == b" ":
                        break
                    if c != b"\n":
                        chars.append(c)
                words.append(b"".join(chars).decode("utf-8"))
                vec = np.frombuffer(f.read(4 * d), dtype="<f4")
                rows.append(vec.astype(np.float32))
                nl = f.read(1)
                if nl not in (b"\n", b""):
                    f.seek(-1, os.SEEK_CUR)
        return np.stack(rows), words

    @staticmethod
    def _is_binary(path):
        with open(path, "rb") as f:
            f.readline()                 # header is text either way
            chunk = f.read(512)
        try:
            chunk.decode("utf-8")
        except UnicodeDecodeError:
            return True
        # pure-ASCII float text has no NULs / control bytes
        return any(b < 9 for b in chunk)

    @staticmethod
    def readWord2VecModel(path, binary=None):
        """-> StaticWordVectors; format auto-detected unless `binary` set."""
        if binary is None:
            binary = WordVectorSerializer._is_binary(path)
        table, words = (WordVectorSerializer._read_binary(path) if binary
                        else WordVectorSerializer._read_text(path))
        return StaticWordVectors(table, words)

    # reference-compat aliases
    loadStaticModel = readWord2VecModel
    loadTxtVectors = staticmethod(lambda path: (
        WordVectorSerializer.readWord2VecModel(path, binary=False)))

    # -- embedding-layer bridge -----------------------------------------
    @staticmethod
    def embeddingLayerWeights(vectors, extra_tokens=0, seed=0):
        """(V + extra, D) float32 init matrix for EmbeddingLayer: rows 0..V-1
        are the loaded vectors (row i = vocab index i); `extra_tokens`
        appends small-random rows (e.g. OOV/PAD ids) after the vocab."""
        table = vectors._table()
        if not extra_tokens:
            return table.copy()
        rng = np.random.default_rng(seed)
        d = table.shape[1]
        extra = (rng.random((extra_tokens, d), np.float32) - 0.5) / d
        return np.concatenate([table, extra], axis=0)
