"""ParagraphVectors / doc2vec (≡ deeplearning4j-nlp ::
models.paragraphvectors.ParagraphVectors, PV-DBOW + PV-DM).

PV-DBOW: the label (document) vector plays the skip-gram center role and
predicts each word of its document — reuses the jitted SGNS step with the
doc table as syn0. PV-DM: mean(doc vector, context word vectors) predicts
the center word. `inferVector` gradient-descends a fresh doc vector with
all trained tables frozen (jitted closed-form grad, no optimizer state).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _sgns_step


@functools.partial(jax.jit, donate_argnums=(0,))
def _pvdm_step(params, lr, doc_ids, ctx_ids, ctx_mask, center, negatives,
               weights):
    """PV-DM: v = mean(doc vec + context word vecs) → SGNS vs center."""

    def loss_fn(p):
        dv = p["docs"][doc_ids]                       # (B, D)
        wv = p["syn0"][ctx_ids]                       # (B, C, D)
        cnt = ctx_mask.sum(-1, keepdims=True) + 1.0
        v = (dv + (wv * ctx_mask[..., None]).sum(1)) / cnt
        u_pos = p["syn1"][center]
        u_neg = p["syn1"][negatives]
        pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, -1))
        neg = jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", v, u_neg)).sum(-1)
        return -jnp.sum((pos + neg) * weights) / jnp.maximum(weights.sum(), 1.)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


_INFER_CHUNK = 128  # fixed shape → one XLA compile for any document length


@jax.jit
def _infer_step(doc_vec, syn1, lr, context, negatives, mask):
    def loss_fn(v):
        pos = jax.nn.log_sigmoid(syn1[context] @ v) * mask
        neg = (jax.nn.log_sigmoid(-(syn1[negatives] @ v))
               * mask[:, None])
        return -(pos.sum() + neg.sum())

    return doc_vec - lr * jax.grad(loss_fn)(doc_vec)


class LabelledDocument:
    """≡ text.documentiterator.LabelledDocument."""

    def __init__(self, content, labels):
        self.content = content
        self.labels = labels if isinstance(labels, (list, tuple)) else [labels]


class ParagraphVectors(Word2Vec):
    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._min_count = 1
            self._docs = None
            self._dm = False
            self._train_words = True

        def iterate(self, docs):
            """Accepts LabelledDocuments, (label, text) pairs, or raw
            strings (auto-labelled DOC_i)."""
            norm = []
            for i, d in enumerate(docs):
                if isinstance(d, LabelledDocument):
                    norm.append((d.labels[0], d.content))
                elif isinstance(d, tuple):
                    norm.append(d)
                else:
                    norm.append((f"DOC_{i}", d))
            self._docs = norm
            return self

        def sequenceLearningAlgorithm(self, name):
            self._dm = "DM" in str(name).upper()
            return self

        def trainWordVectors(self, flag):
            self._train_words = bool(flag)
            return self

        def build(self):
            if getattr(self, "_hs", False):
                raise ValueError(
                    "ParagraphVectors trains PV-DBOW/PV-DM with negative "
                    "sampling; useHierarchicSoftmax is supported on "
                    "Word2Vec/SequenceVectors (the shared SGNS pipeline)")
            return ParagraphVectors(self)

    def __init__(self, builder):
        super().__init__(builder)
        self.labels = [lab for lab, _ in builder._docs]
        self.label2idx = {lab: i for i, lab in enumerate(self.labels)}

    def _tokenized(self):
        return [self.b._tok.create(text).getTokens()
                for _, text in self.b._docs]

    def fit(self):
        toks = self._tokenized()
        self.buildVocab(toks)
        self._init_params()
        d = self.b._layer_size
        key = jax.random.PRNGKey(self.b._seed + 1)
        self.params["docs"] = (jax.random.uniform(
            key, (len(self.labels), d), jnp.float32) - 0.5) / d
        w2i = self.vocab.word2idx
        docs_ids = [[w2i[t] for t in s if t in w2i] for s in toks]

        if self.b._train_words:
            self._run_epochs(lambda: self._pairs(docs_ids),
                             self.b._epochs * self.b._iterations)
        if self.b._dm:
            self._fit_dm(docs_ids)
        else:
            self._fit_dbow(docs_ids)
        return self

    # -- PV-DBOW: doc id predicts every word in the doc ------------------
    def _fit_dbow(self, docs_ids):
        centers = np.concatenate(
            [np.full(len(ids), di, np.int32)
             for di, ids in enumerate(docs_ids) if ids] or
            [np.zeros(0, np.int32)])
        contexts = np.concatenate(
            [np.asarray(ids, np.int32)
             for ids in docs_ids if ids] or [np.zeros(0, np.int32)])
        if len(centers) == 0:
            return
        dbow = {"syn0": self.params["docs"], "syn1": self.params["syn1"]}
        for _ in range(self.b._epochs * self.b._iterations):
            for cen, ctx, negs, w in self._batches(centers, contexts):
                dbow, _ = _sgns_step(dbow, self.b._lr, cen, ctx, negs, w)
        self.params["docs"], self.params["syn1"] = dbow["syn0"], dbow["syn1"]

    # -- PV-DM -----------------------------------------------------------
    def _fit_dm(self, docs_ids):
        neg_p = self.vocab.negative_table()
        B, K, C = self.b._batch, max(1, self.b._negative), 2 * self.b._window
        rows = []
        for di, ids in enumerate(docs_ids):
            n = len(ids)
            for i in range(n):
                ctx = [ids[j] for j in range(max(0, i - self.b._window),
                                             min(n, i + self.b._window + 1))
                       if j != i]
                rows.append((di, ids[i], ctx))
        if not rows:
            return
        for _ in range(self.b._epochs * self.b._iterations):
            order = self._rng.permutation(len(rows))
            doc_a = np.zeros(len(rows), np.int32)
            cen_a = np.zeros(len(rows), np.int32)
            ctx_a = np.zeros((len(rows), C), np.int32)
            msk_a = np.zeros((len(rows), C), np.float32)
            for k, r in enumerate(order):
                di, ci, ctx = rows[r]
                doc_a[k], cen_a[k] = di, ci
                m = min(len(ctx), C)
                ctx_a[k, :m] = ctx[:m]
                msk_a[k, :m] = 1.0
            n = len(rows)
            pad = (-n) % B
            w = np.concatenate([np.ones(n, np.float32),
                                np.zeros(pad, np.float32)])
            doc_a = np.concatenate([doc_a, np.zeros(pad, np.int32)])
            cen_a = np.concatenate([cen_a, np.zeros(pad, np.int32)])
            ctx_a = np.concatenate([ctx_a, np.zeros((pad, C), np.int32)])
            msk_a = np.concatenate([msk_a, np.zeros((pad, C), np.float32)])
            negs = self._rng.choice(self.vocab.numWords(), size=(n + pad, K),
                                    p=neg_p).astype(np.int32)
            for s in range(0, n + pad, B):
                self.params, _ = _pvdm_step(
                    self.params, self.b._lr,
                    jnp.asarray(doc_a[s:s + B]), jnp.asarray(ctx_a[s:s + B]),
                    jnp.asarray(msk_a[s:s + B]), jnp.asarray(cen_a[s:s + B]),
                    jnp.asarray(negs[s:s + B]), jnp.asarray(w[s:s + B]))

    # -- surface ---------------------------------------------------------
    def getLabelVector(self, label):
        return np.asarray(self.params["docs"], np.float32)[
            self.label2idx[label]]

    def inferVector(self, text, steps=50, lr=0.05):
        toks = self.b._tok.create(text).getTokens()
        ids = [self.vocab.indexOf(t) for t in toks]
        ids = np.asarray([i for i in ids if i >= 0], np.int32)
        d = self.b._layer_size
        vec = jnp.asarray((self._rng.random(d).astype(np.float32) - 0.5) / d)
        if len(ids) == 0:
            return np.asarray(vec)
        # pad/chunk to a fixed shape so _infer_step compiles exactly once
        n = len(ids)
        pad = (-n) % _INFER_CHUNK
        mask = np.concatenate([np.ones(n, np.float32),
                               np.zeros(pad, np.float32)])
        ids = np.concatenate([ids, np.zeros(pad, np.int32)])
        neg_p = self.vocab.negative_table()
        syn1 = self.params["syn1"]
        K = max(1, self.b._negative)
        for _ in range(steps):
            negs = self._rng.choice(self.vocab.numWords(),
                                    size=(len(ids), K),
                                    p=neg_p).astype(np.int32)
            for s in range(0, len(ids), _INFER_CHUNK):
                vec = _infer_step(vec, syn1, lr,
                                  jnp.asarray(ids[s:s + _INFER_CHUNK]),
                                  jnp.asarray(negs[s:s + _INFER_CHUNK]),
                                  jnp.asarray(mask[s:s + _INFER_CHUNK]))
        return np.asarray(vec)

    def similarityToLabel(self, text, label):
        v = self.inferVector(text)
        lv = self.getLabelVector(label)
        den = max(np.linalg.norm(v) * np.linalg.norm(lv), 1e-12)
        return float(v @ lv / den)

    def nearestLabels(self, text, topN=5):
        v = self.inferVector(text)
        tab = np.asarray(self.params["docs"], np.float32)
        sims = tab @ v / np.maximum(
            np.linalg.norm(tab, axis=1) * max(np.linalg.norm(v), 1e-12),
            1e-12)
        order = np.argsort(-sims)[:topN]
        return [self.labels[i] for i in order]
