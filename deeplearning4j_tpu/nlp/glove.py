"""GloVe (≡ deeplearning4j-nlp :: models.glove.Glove).

Co-occurrence counting is host-side (sparse dict with 1/distance
weighting, as in the reference's CoOccurrences pipeline); the weighted
least-squares factorization step — f(X)·(wᵢ·w̃ⱼ + bᵢ + b̃ⱼ − log X)² with
per-parameter AdaGrad — runs as one jitted XLA executable per batch over
fixed-shape (i, j, logX, f) tensors.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import WordVectors
from deeplearning4j_tpu.nlp.tokenization import (CollectionSentenceIterator,
                                                 DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import build_vocab


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _glove_step(params, hist, lr, rows, cols, log_x, f_w, mask):
    def loss_fn(p):
        wi = p["w"][rows]
        wj = p["wc"][cols]
        diff = (wi * wj).sum(-1) + p["b"][rows] + p["bc"][cols] - log_x
        return jnp.sum(f_w * diff * diff * mask) / jnp.maximum(mask.sum(), 1.)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    hist = jax.tree_util.tree_map(lambda h, g: h + g * g, hist, grads)
    params = jax.tree_util.tree_map(
        lambda p, g, h: p - lr * g / jnp.sqrt(h + 1e-8), params, grads, hist)
    return params, hist, loss


class Glove(WordVectors):
    class Builder:
        def __init__(self):
            self._min_count = 1
            self._layer_size = 100
            self._seed = 42
            self._window = 5
            self._lr = 0.05
            self._epochs = 25
            self._xmax = 100.0
            self._alpha = 0.75
            self._batch = 4096
            self._symmetric = True
            self._iter = None
            self._tok = DefaultTokenizerFactory()

        def minWordFrequency(self, v):
            self._min_count = int(v); return self

        def layerSize(self, v):
            self._layer_size = int(v); return self

        def seed(self, v):
            self._seed = int(v); return self

        def windowSize(self, v):
            self._window = int(v); return self

        def learningRate(self, v):
            self._lr = float(v); return self

        def epochs(self, v):
            self._epochs = int(v); return self

        def xMax(self, v):
            self._xmax = float(v); return self

        def alpha(self, v):
            self._alpha = float(v); return self

        def batchSize(self, v):
            self._batch = int(v); return self

        def symmetric(self, v):
            self._symmetric = bool(v); return self

        def iterate(self, sentence_iterator):
            if isinstance(sentence_iterator, (list, tuple)):
                sentence_iterator = CollectionSentenceIterator(
                    sentence_iterator)
            self._iter = sentence_iterator
            return self

        def tokenizerFactory(self, tok):
            self._tok = tok; return self

        def build(self):
            return Glove(self)

    def __init__(self, builder):
        self.b = builder
        self.vocab = None
        self.params = None
        self._rng = np.random.default_rng(builder._seed)

    def _table(self):
        # GloVe convention: final vectors = w + context w
        return np.asarray(self.params["w"] + self.params["wc"], np.float32)

    def _cooccurrences(self, sentences_ids):
        co = {}
        for ids in sentences_ids:
            n = len(ids)
            for i in range(n):
                for j in range(max(0, i - self.b._window), i):
                    w = 1.0 / (i - j)
                    co[(ids[i], ids[j])] = co.get((ids[i], ids[j]), 0.0) + w
                    if self.b._symmetric:
                        co[(ids[j], ids[i])] = co.get(
                            (ids[j], ids[i]), 0.0) + w
        return co

    def fit(self):
        toks = [self.b._tok.create(s).getTokens() for s in self.b._iter]
        self.vocab = build_vocab(toks, self.b._min_count)
        w2i = self.vocab.word2idx
        ids = [[w2i[t] for t in s if t in w2i] for s in toks]
        co = self._cooccurrences(ids)
        if not co:
            raise ValueError("no co-occurrences (corpus too small)")

        v, d = self.vocab.numWords(), self.b._layer_size
        key = jax.random.PRNGKey(self.b._seed)
        k1, k2 = jax.random.split(key)
        scale = 0.5 / d
        self.params = {
            "w": jax.random.uniform(k1, (v, d), minval=-scale, maxval=scale),
            "wc": jax.random.uniform(k2, (v, d), minval=-scale, maxval=scale),
            "b": jnp.zeros((v,)), "bc": jnp.zeros((v,)),
        }
        hist = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1e-8), self.params)

        pairs = np.asarray(list(co.keys()), np.int32)
        xs = np.asarray(list(co.values()), np.float64)
        log_x = np.log(xs).astype(np.float32)
        f_w = np.minimum((xs / self.b._xmax) ** self.b._alpha,
                         1.0).astype(np.float32)
        B = self.b._batch
        n = len(pairs)
        pad = (-n) % B
        mask = np.concatenate([np.ones(n, np.float32),
                               np.zeros(pad, np.float32)])
        pairs = np.concatenate([pairs, np.zeros((pad, 2), np.int32)])
        log_x = np.concatenate([log_x, np.zeros(pad, np.float32)])
        f_w = np.concatenate([f_w, np.zeros(pad, np.float32)])

        for _ in range(self.b._epochs):
            perm = self._rng.permutation(len(pairs))
            for s in range(0, len(pairs), B):
                sl = perm[s:s + B]
                self.params, hist, _ = _glove_step(
                    self.params, hist, self.b._lr,
                    jnp.asarray(pairs[sl, 0]), jnp.asarray(pairs[sl, 1]),
                    jnp.asarray(log_x[sl]), jnp.asarray(f_w[sl]),
                    jnp.asarray(mask[sl]))
        return self
