"""FastText (≡ deeplearning4j-nlp :: models.fasttext.FastText — subword
skip-gram).

Each word's input vector is the mean of its own embedding plus hashed
character n-gram bucket embeddings (FNV-1a hashing into a fixed bucket
table, as fastText does). The per-word n-gram id matrix is precomputed
host-side into a fixed (V, max_ngrams) padded tensor so the training step
— masked-mean gather + SGNS loss + update — stays one jitted executable.
OOV words get vectors from their n-grams alone.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import Word2Vec


def _fnv1a(s):
    h = np.uint64(2166136261)
    for ch in s.encode("utf-8"):
        h = np.uint64((int(h) ^ ch) * 16777619 & 0xFFFFFFFFFFFFFFFF)
    return int(h)


def char_ngrams(word, min_n=3, max_n=6):
    w = f"<{word}>"
    out = []
    for n in range(min_n, max_n + 1):
        for i in range(len(w) - n + 1):
            g = w[i:i + n]
            if g != w:
                out.append(g)
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _ft_step(params, lr, ngram_ids, ngram_mask, context, negatives, weights):
    """ngram_ids: (B, G) rows into the combined [word | bucket] table;
    row 0 of the mask selects real entries (word id always present)."""

    def loss_fn(p):
        emb = p["syn0"][ngram_ids]                    # (B, G, D)
        cnt = jnp.maximum(ngram_mask.sum(-1, keepdims=True), 1.0)
        v = (emb * ngram_mask[..., None]).sum(1) / cnt
        u_pos = p["syn1"][context]
        u_neg = p["syn1"][negatives]
        pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, -1))
        neg = jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", v, u_neg)).sum(-1)
        return -jnp.sum((pos + neg) * weights) / jnp.maximum(weights.sum(), 1.)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


class FastText(Word2Vec):
    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._min_count = 1
            self._buckets = 1 << 17
            self._min_n, self._max_n = 3, 6
            self._max_ngrams = 24

        def bucket(self, v):
            self._buckets = int(v); return self

        def minN(self, v):
            self._min_n = int(v); return self

        def maxN(self, v):
            self._max_n = int(v); return self

        def build(self):
            if getattr(self, "_hs", False):
                raise ValueError(
                    "FastText's subword step trains negative sampling; "
                    "useHierarchicSoftmax is supported on "
                    "Word2Vec/SequenceVectors (the shared SGNS pipeline)")
            return FastText(self)

    def __init__(self, builder):
        super().__init__(builder)
        self._ngram_ids = None
        self._ngram_mask = None

    def _word_ngram_row(self, word, widx=None):
        """Row of table ids: [word_id?, bucket ids...] padded to max."""
        G = self.b._max_ngrams
        v = self.vocab.numWords()
        ids, mask = [], []
        if widx is not None:
            ids.append(widx)
            mask.append(1.0)
        for g in char_ngrams(word, self.b._min_n, self.b._max_n)[:G - len(ids)]:
            ids.append(v + _fnv1a(g) % self.b._buckets)
            mask.append(1.0)
        while len(ids) < G:
            ids.append(0)
            mask.append(0.0)
        return np.asarray(ids, np.int32), np.asarray(mask, np.float32)

    def _init_params(self):
        v, d = self.vocab.numWords(), self.b._layer_size
        key = jax.random.PRNGKey(self.b._seed)
        table = (jax.random.uniform(
            key, (v + self.b._buckets, d), jnp.float32) - 0.5) / d
        self.params = {"syn0": table, "syn1": jnp.zeros((v, d), jnp.float32)}
        rows = [self._word_ngram_row(w, i)
                for i, w in enumerate(self.vocab.idx2word)]
        self._ngram_ids = np.stack([r[0] for r in rows])
        self._ngram_mask = np.stack([r[1] for r in rows])

    def _run_epochs(self, pairs_fn, epochs):
        for _ in range(epochs):
            centers, contexts = pairs_fn()
            for cen, ctx, negs, w in self._batches(centers, contexts):
                # cen is host-side: ngram row gather stays on host, no sync
                self.params, _ = _ft_step(
                    self.params, self.b._lr,
                    self._ngram_ids[cen], self._ngram_mask[cen],
                    ctx, negs, w)
        self._cached_table = None   # tables changed; recompute on lookup
        self._cached_syn0 = None

    # -- lookup: in-vocab mean(word+ngrams); OOV from ngrams alone -------
    def _table(self):
        # the (V, G, D) gather is expensive; params are frozen at lookup
        # time, so reduce once and reuse across similarity queries
        if getattr(self, "_cached_table", None) is None:
            tab = np.asarray(self.params["syn0"], np.float32)
            emb = tab[self._ngram_ids]                  # (V, G, D)
            cnt = np.maximum(self._ngram_mask.sum(-1, keepdims=True), 1.0)
            self._cached_table = (emb * self._ngram_mask[..., None]
                                  ).sum(1) / cnt
        return self._cached_table

    def getWordVector(self, word):
        i = self.vocab.indexOf(word)
        if i >= 0:
            return self._table()[i]
        # OOV: n-grams only, against one cached host copy of syn0
        ids, mask = self._word_ngram_row(word)
        if mask.sum() == 0:
            raise KeyError(f"no n-grams for OOV word {word!r}")
        if getattr(self, "_cached_syn0", None) is None:
            self._cached_syn0 = np.asarray(self.params["syn0"], np.float32)
        emb = self._cached_syn0[ids]
        return (emb * mask[:, None]).sum(0) / max(mask.sum(), 1.0)
