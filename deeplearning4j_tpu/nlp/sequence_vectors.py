"""SequenceVectors (≡ deeplearning4j-nlp ::
models.sequencevectors.SequenceVectors + AbstractSequenceIterator /
sequence.Sequence<SequenceElement>).

The reference's generic embedding trainer: Word2Vec and ParagraphVectors
are specializations of it, and users drive it directly to embed ANY
discrete-element sequences (product ids, event streams, graph walks)
with a custom sequence iterator.

Here it reuses the whole Word2Vec pipeline — vocab building, dynamic
windows, subsampling, unigram^0.75 negatives, and the single jitted
skip-gram-negative-sampling executable — over caller-supplied
PRE-TOKENIZED sequences (no tokenizer involved, so elements may contain
any characters). All WordVectors lookups (``getWordVector``,
``wordsNearest``, ``similarity``) work on the elements.
"""
from __future__ import annotations

from deeplearning4j_tpu.nlp.word2vec import Word2Vec

__all__ = ["AbstractSequenceIterator", "SequenceVectors"]


def _elements(seq):
    if isinstance(seq, str):
        raise TypeError(
            "SequenceVectors takes sequences of ELEMENTS (lists of "
            "strings), not raw sentence strings — iterating a string "
            "would embed single characters. Use Word2Vec for text, or "
            "split the sentence first.")
    return [str(e) for e in seq]


class AbstractSequenceIterator:
    """≡ sequencevectors.iterators.AbstractSequenceIterator — iterates
    sequences (lists) of string elements. Build from any collection."""

    def __init__(self, sequences):
        self._seqs = [_elements(s) for s in sequences]

    def __iter__(self):
        return iter(self._seqs)

    def sequences(self):
        return self._seqs


class SequenceVectors(Word2Vec):
    """Built via the same fluent Builder; ``iterate`` takes an
    AbstractSequenceIterator or a plain list of element lists."""

    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._min_count = 1          # reference default for sequences

        def iterate(self, sequence_iterator):
            self._iter = sequence_iterator
            return self

        def build(self):
            return SequenceVectors(self)

    def _tokenized(self):
        it = self.b._iter
        if it is None:
            raise ValueError("SequenceVectors.Builder().iterate(...) not set")
        if isinstance(it, AbstractSequenceIterator):
            return it.sequences()
        return [_elements(seq) for seq in it]
