"""CnnSentenceDataSetIterator (≡ deeplearning4j-nlp ::
org.deeplearning4j.iterator.CnnSentenceDataSetIterator +
provider.LabeledSentenceProvider / CollectionLabeledSentenceProvider).

Sentences → word-vector tensors for CNN/RNN text classifiers:

- Format.CNN2D: features (B, 1, maxLen, vectorSize) — the "sentence as
  image" layout Kim-CNN uses (1 channel, words on the H axis)
- Format.CNN1D / RNN: features (B, vectorSize, maxLen) — channels-first
  time series, the layout Convolution1D/LSTM layers consume

Variable sentence lengths are handled the reference way: per-batch pad
to the longest sentence (capped at maxSentenceLength) + a feature mask
of shape (B, maxLen); unknown words are skipped (or mapped to
``unknownWordHandling="UseUnknown"`` → the UNK vector). Batches are
host-assembled numpy — the device consumes them through the same jitted
fit path as every other iterator.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

__all__ = ["CollectionLabeledSentenceProvider", "CnnSentenceDataSetIterator"]


class CollectionLabeledSentenceProvider:
    """≡ iterator.provider.CollectionLabeledSentenceProvider."""

    def __init__(self, sentences, labels):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        self.sentences = list(sentences)
        self.labels = [str(l) for l in labels]
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self.sentences)

    def nextSentence(self):
        s, l = self.sentences[self._pos], self.labels[self._pos]
        self._pos += 1
        return s, l

    def reset(self):
        self._pos = 0

    def totalNumSentences(self):
        return len(self.sentences)

    def allLabels(self):
        return sorted(set(self.labels))

    def numLabelClasses(self):
        return len(self.allLabels())


class CnnSentenceDataSetIterator(DataSetIterator):
    class Format:
        CNN2D = "CNN2D"
        CNN1D = "CNN1D"
        RNN = "RNN"

    class Builder:
        def __init__(self, format="CNN2D"):
            self._format = format
            self._provider = None
            self._wv = None
            self._max_len = 256
            self._batch = 32
            self._unknown = "RemoveWord"   # or "UseUnknown"
            self._unknown_word = None
            self._tokenizer = None
            self._min_length = 1

        def sentenceProvider(self, p):
            self._provider = p; return self

        def wordVectors(self, wv):
            self._wv = wv; return self

        def maxSentenceLength(self, v):
            self._max_len = int(v); return self

        def minibatchSize(self, v):
            self._batch = int(v); return self

        def unknownWordHandling(self, v):
            self._unknown = str(v); return self

        def useUnknown(self, word):
            self._unknown = "UseUnknown"
            self._unknown_word = word
            return self

        def tokenizerFactory(self, tok):
            self._tokenizer = tok; return self

        def build(self):
            if self._provider is None or self._wv is None:
                raise ValueError("sentenceProvider and wordVectors required")
            return CnnSentenceDataSetIterator(self)

    def __init__(self, b):
        super().__init__(b._batch)
        self.b = b
        self.provider = b._provider
        self.wv = b._wv
        self.labels_list = self.provider.allLabels()
        self._label_idx = {l: i for i, l in enumerate(self.labels_list)}
        # vector size probed from any in-vocab word (reference: lookupTable)
        self.vector_size = int(
            np.asarray(self.wv._table()).shape[1])

    # -- protocol --------------------------------------------------------
    def numExamples(self):
        return self.provider.totalNumSentences()

    def totalOutcomes(self):
        return len(self.labels_list)

    def inputColumns(self):
        return self.vector_size

    def getLabels(self):
        return self.labels_list

    def reset(self):
        super().reset()
        self.provider.reset()

    def hasNext(self):
        return self.provider.hasNext()

    def _tokens(self, sentence):
        if self.b._tokenizer is not None:
            tok = self.b._tokenizer.create(sentence)
            toks = [tok.nextToken() for _ in range(tok.countTokens())]
        else:
            toks = sentence.lower().split()
        out = []
        for t in toks:
            if self.wv.hasWord(t):
                out.append(self.wv.getWordVector(t))
            elif self.b._unknown == "UseUnknown":
                if self.b._unknown_word and self.wv.hasWord(
                        self.b._unknown_word):
                    out.append(self.wv.getWordVector(self.b._unknown_word))
                else:
                    out.append(np.zeros(self.vector_size, np.float32))
            # RemoveWord: skip
        return out[: self.b._max_len]

    def next(self, num=None):
        self._check_has_next()
        num = num or self._batch
        vecs, labels = [], []
        while self.provider.hasNext() and len(vecs) < num:
            s, lab = self.provider.nextSentence()
            tv = self._tokens(s)
            if len(tv) < self.b._min_length:
                tv = [np.zeros(self.vector_size, np.float32)]
            vecs.append(np.stack(tv))
            labels.append(self._label_idx[lab])
        bsz = len(vecs)
        max_len = max(v.shape[0] for v in vecs)
        mask = np.zeros((bsz, max_len), np.float32)
        dense = np.zeros((bsz, max_len, self.vector_size), np.float32)
        for i, v in enumerate(vecs):
            dense[i, : v.shape[0]] = v
            mask[i, : v.shape[0]] = 1.0
        y = np.eye(len(self.labels_list), dtype=np.float32)[labels]
        if self.b._format == self.Format.CNN2D:
            feats = dense[:, None, :, :]          # (B, 1, maxLen, vecSize)
        else:                                      # CNN1D / RNN layout
            feats = dense.transpose(0, 2, 1)       # (B, vecSize, maxLen)
        self._cursor += bsz
        return self._maybe_preprocess(DataSet(feats, y, featuresMask=mask))
