"""Vocabulary cache (≡ deeplearning4j-nlp :: models.word2vec.wordstore.
VocabCache / AbstractCache): word↔index maps, frequencies, the unigram^0.75
negative-sampling table, and frequent-word subsampling probabilities.
"""
from __future__ import annotations

import numpy as np


class VocabCache:
    def __init__(self):
        self.word2idx = {}
        self.idx2word = []
        self.counts = []

    # -- building --------------------------------------------------------
    def add(self, word, count=1):
        if word not in self.word2idx:
            self.word2idx[word] = len(self.idx2word)
            self.idx2word.append(word)
            self.counts.append(0)
        self.counts[self.word2idx[word]] += count

    def prune(self, min_count):
        keep = [(w, c) for w, c in zip(self.idx2word, self.counts)
                if c >= min_count]
        keep.sort(key=lambda wc: -wc[1])
        self.word2idx = {w: i for i, (w, _) in enumerate(keep)}
        self.idx2word = [w for w, _ in keep]
        self.counts = [c for _, c in keep]

    # -- queries (≡ VocabCache surface) ----------------------------------
    def numWords(self):
        return len(self.idx2word)

    def containsWord(self, word):
        return word in self.word2idx

    def indexOf(self, word):
        return self.word2idx.get(word, -1)

    def wordAtIndex(self, idx):
        return self.idx2word[idx]

    def wordFrequency(self, word):
        i = self.word2idx.get(word)
        return 0 if i is None else self.counts[i]

    def totalWordOccurrences(self):
        return int(sum(self.counts))

    def words(self):
        return list(self.idx2word)

    # -- sampling helpers ------------------------------------------------
    def negative_table(self, power=0.75):
        """Unigram^power distribution (≡ Word2Vec's negative-sampling
        table, as a probability vector rather than a 100M-slot array)."""
        p = np.asarray(self.counts, np.float64) ** power
        return p / p.sum()

    def keep_probs(self, sample=1e-3):
        """Per-word keep probability for frequent-word subsampling
        (word2vec's t-threshold formula)."""
        if not sample:
            return np.ones(len(self.counts))
        freq = np.asarray(self.counts, np.float64)
        freq = freq / max(1.0, freq.sum())
        keep = np.sqrt(sample / np.maximum(freq, 1e-12))
        return np.clip(keep, 0.0, 1.0)


def build_vocab(sentences_tokens, min_count=1):
    vocab = VocabCache()
    for toks in sentences_tokens:
        for t in toks:
            vocab.add(t)
    vocab.prune(min_count)
    return vocab
