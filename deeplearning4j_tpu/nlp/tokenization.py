"""Tokenization (≡ deeplearning4j-nlp :: text.tokenization.tokenizer.*,
tokenizerfactory.DefaultTokenizerFactory / NGramTokenizerFactory,
preprocessor.CommonPreprocessor).

Host-side text handling — tokenization never touches the accelerator; it
feeds integer id batches into the jitted embedding-training steps.
"""
from __future__ import annotations

import re


class TokenPreProcess:
    """≡ tokenization.tokenizer.TokenPreProcess protocol."""

    def preProcess(self, token):
        return token


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (≡ CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def preProcess(self, token):
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def preProcess(self, token):
        return token.lower()


class Tokenizer:
    """≡ tokenization.tokenizer.Tokenizer — iterator surface over tokens."""

    def __init__(self, tokens, pre=None):
        if pre is not None:
            tokens = [pre.preProcess(t) for t in tokens]
        self._tokens = [t for t in tokens if t]
        self._idx = 0

    def hasMoreTokens(self):
        return self._idx < len(self._tokens)

    def nextToken(self):
        tok = self._tokens[self._idx]
        self._idx += 1
        return tok

    def countTokens(self):
        return len(self._tokens)

    def getTokens(self):
        return list(self._tokens)


class TokenizerFactory:
    def setTokenPreProcessor(self, pre):
        self._pre = pre
        return self

    def getTokenPreProcessor(self):
        return getattr(self, "_pre", None)


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (≡ DefaultTokenizerFactory)."""

    _pre = None

    def create(self, text):
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-gram tokenizer (≡ NGramTokenizerFactory): emits all n-grams
    with minN <= n <= maxN joined by spaces."""

    _pre = None

    def __init__(self, minN=1, maxN=1):
        self.minN, self.maxN = int(minN), int(maxN)

    def create(self, text):
        words = Tokenizer(text.split(), self._pre).getTokens()
        out = []
        for n in range(self.minN, self.maxN + 1):
            for i in range(len(words) - n + 1):
                out.append(" ".join(words[i:i + n]))
        return Tokenizer(out)


class SentenceIterator:
    """≡ text.sentenceiterator.SentenceIterator protocol."""

    def nextSentence(self):
        raise NotImplementedError

    def hasNext(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.nextSentence()


class CollectionSentenceIterator(SentenceIterator):
    """≡ CollectionSentenceIterator — iterate an in-memory list."""

    def __init__(self, sentences):
        self._sentences = list(sentences)
        self._idx = 0

    def nextSentence(self):
        s = self._sentences[self._idx]
        self._idx += 1
        return s

    def hasNext(self):
        return self._idx < len(self._sentences)

    def reset(self):
        self._idx = 0


class BasicLineIterator(SentenceIterator):
    """≡ BasicLineIterator — one sentence per line from a file path."""

    def __init__(self, path):
        self.path = path
        self.reset()

    def reset(self):
        with open(self.path, "r", encoding="utf-8") as f:
            self._lines = [ln.strip() for ln in f if ln.strip()]
        self._idx = 0

    def nextSentence(self):
        s = self._lines[self._idx]
        self._idx += 1
        return s

    def hasNext(self):
        return self._idx < len(self._lines)
