"""Text vectorizers (≡ deeplearning4j-nlp ::
org.deeplearning4j.bagofwords.vectorizer.BagOfWordsVectorizer /
TfidfVectorizer).

Reference shape: Builder with a tokenizer factory + sentence iterator,
``fit()`` builds the vocabulary, ``transform(text)`` returns a row
vector, ``vectorize(text, label)`` a DataSet — fed to dense classifiers.

Host-side counting (vocabulary statistics are not an accelerator
workload); the produced fixed-shape (N, V) float32 matrices flow into
the same jitted fit/evaluate paths as every other DataSet.
"""
from __future__ import annotations

import math

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import build_vocab

__all__ = ["BagOfWordsVectorizer", "TfidfVectorizer"]


class _BaseVectorizer:
    class Builder:
        def __init__(self):
            self._tok = DefaultTokenizerFactory()
            self._min_count = 1
            self._iter = None
            self._labels = None

        def tokenizerFactory(self, tok):
            self._tok = tok; return self

        def minWordFrequency(self, v):
            self._min_count = int(v); return self

        def iterate(self, sentences):
            self._iter = list(sentences); return self

        def labels(self, labels):
            self._labels = [str(l) for l in labels]; return self

        def build(self):
            raise NotImplementedError("use a concrete vectorizer's Builder")

    def __init__(self, b):
        self.b = b
        self.vocab = None
        # declaration order, as the reference's LabelsSource.indexOf —
        # sorting would silently permute one-hot columns
        self._labels_list = (list(dict.fromkeys(b._labels))
                             if b._labels else None)

    def _tokens(self, text):
        return self.b._tok.create(text).getTokens()

    def fit(self, sentences=None):
        sentences = sentences if sentences is not None else self.b._iter
        if sentences is None:
            raise ValueError("no corpus: pass sentences or Builder.iterate")
        self._fit_docs_impl([self._tokens(s) for s in sentences])
        return self

    def _fit_docs_impl(self, docs):
        self.vocab = build_vocab(docs, self.b._min_count)
        if self.vocab.numWords() == 0:
            raise ValueError("empty vocabulary after min-count pruning")
        self._post_fit(docs)   # docs stay local — not retained past fit

    def _post_fit(self, docs):
        pass

    def vocabSize(self):
        return self.vocab.numWords()

    def _check_fit(self):
        if self.vocab is None:
            raise ValueError("call fit() first")

    def _count(self, row, toks):
        for t in toks:
            i = self.vocab.indexOf(t)
            if i >= 0:
                row[i] += 1.0

    def transform(self, text):
        """One row vector (V,) for a text (or pre-tokenized sequence)."""
        self._check_fit()
        toks = (self._tokens(text) if isinstance(text, str)
                else [str(t) for t in text])
        row = np.zeros(self.vocab.numWords(), np.float32)
        self._fill(row, toks)
        return row

    def transformAll(self, sentences):
        return np.stack([self.transform(s) for s in sentences])

    def vectorize(self, text, label):
        """≡ vectorize(String, String) → DataSet with a one-hot label."""
        if self._labels_list is None:
            raise ValueError("Builder.labels(...) not set")
        if str(label) not in self._labels_list:
            raise ValueError(
                f"unknown label {label!r}; Builder.labels(...) declared "
                f"{self._labels_list}")
        y = np.zeros((1, len(self._labels_list)), np.float32)
        y[0, self._labels_list.index(str(label))] = 1.0
        return DataSet(self.transform(text)[None, :], y)

    def fitTransform(self, sentences):
        docs = [self._tokens(s) for s in sentences]   # tokenize ONCE
        self._fit_docs_impl(docs)
        return self.transformAll(docs)   # transform accepts token lists


class BagOfWordsVectorizer(_BaseVectorizer):
    """Raw term counts (≡ bagofwords.vectorizer.BagOfWordsVectorizer)."""

    class Builder(_BaseVectorizer.Builder):
        def build(self):
            return BagOfWordsVectorizer(self)

    def _fill(self, row, toks):
        self._count(row, toks)


class TfidfVectorizer(_BaseVectorizer):
    """tf·idf weights with the reference's smoothed idf
    (log(1 + N / df)) — fit() computes document frequencies."""

    class Builder(_BaseVectorizer.Builder):
        def build(self):
            return TfidfVectorizer(self)

    def _post_fit(self, docs):
        n_docs = len(docs)
        v = self.vocab.numWords()
        df = np.zeros(v, np.float64)
        for toks in docs:
            for i in {self.vocab.indexOf(t) for t in set(toks)}:
                if i >= 0:
                    df[i] += 1.0
        self._idf = np.array(
            [math.log(1.0 + n_docs / df[i]) if df[i] else 0.0
             for i in range(v)], np.float32)

    def _fill(self, row, toks):
        self._count(row, toks)
        row *= self._idf / max(len(toks), 1)   # tf = count/len(doc)

    def tfidfWord(self, word, doc_tokens):
        """≡ TfidfVectorizer.tfidfWord — the weight one word gets in one
        document."""
        self._check_fit()
        i = self.vocab.indexOf(word)
        if i < 0:
            return 0.0
        tf = doc_tokens.count(word) / max(len(doc_tokens), 1)
        return float(tf * self._idf[i])
