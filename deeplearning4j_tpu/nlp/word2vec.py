"""Word2Vec (≡ deeplearning4j-nlp :: models.word2vec.Word2Vec and
models.embeddings.wordvectors.WordVectors).

TPU-first design: the reference trains skip-gram negative sampling with
per-pair scalar updates in Java threads (SkipGram/CBOW ops in libnd4j).
Here training pairs are generated host-side into fixed-shape integer
batches and the WHOLE update — embedding gathers, logits, log-sigmoid
loss, gradients, optimizer — is ONE jitted XLA executable with donated
embedding tables. Negative sampling uses the same unigram^0.75 table;
frequent-word subsampling uses the same t-threshold formula.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (CollectionSentenceIterator,
                                                 DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab


def _build_huffman(counts):
    """Huffman tree over word counts (≡ the reference's
    VocabConstructor/Huffman pass) -> per-word padded path tables:
    points (V, L) int32 inner-node ids root-first, codes (V, L) float32
    binary codes, mask (V, L) float32 validity. Frequent words get short
    codes (prefix-free by construction)."""
    import heapq

    v = len(counts)
    if v <= 1:
        return (np.zeros((v, 1), np.int32), np.zeros((v, 1), np.float32),
                np.zeros((v, 1), np.float32))
    heap = [(int(c), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent, side = {}, {}
    nxt = v
    while len(heap) > 1:
        c1, n1 = heapq.heappop(heap)
        c2, n2 = heapq.heappop(heap)
        parent[n1], parent[n2] = nxt, nxt
        side[n1], side[n2] = 0, 1
        heapq.heappush(heap, (c1 + c2, nxt))
        nxt += 1
    root = heap[0][1]
    paths, codes = [], []
    max_len = 1
    for w in range(v):
        p, c = [], []
        node = w
        while node != root:
            c.append(side[node])
            p.append(parent[node] - v)      # inner-node id, 0..V-2
            node = parent[node]
        p.reverse()
        c.reverse()
        paths.append(p)
        codes.append(c)
        max_len = max(max_len, len(p))
    points = np.zeros((v, max_len), np.int32)
    cod = np.zeros((v, max_len), np.float32)
    mask = np.zeros((v, max_len), np.float32)
    for w in range(v):
        n = len(paths[w])
        points[w, :n] = paths[w]
        cod[w, :n] = codes[w]
        mask[w, :n] = 1.0
    return points, cod, mask


@functools.partial(jax.jit, donate_argnums=(0,))
def _hs_step(params, lr, center, context, points, codes, mask, weights):
    """One hierarchical-softmax SGD step (≡ the reference's
    HierarchicSoftmax learning algorithm), batched: every pair touches
    only its context word's ~log2(V) Huffman inner nodes, gathered as one
    (B, L, D) read — the batched-hardware-native form of the JVM's
    per-node scalar loop."""

    def loss_fn(p):
        v = p["syn0"][center]                       # (B, D)
        pts = points[context]                       # (B, L)
        u = p["syn1"][pts]                          # (B, L, D)
        s = jnp.einsum("bd,bld->bl", v, u)
        sign = 1.0 - 2.0 * codes[context]
        ll = jax.nn.log_sigmoid(sign * s) * mask[context]
        # SUM over pairs, not mean: the reference applies its learning
        # rate PER training pair (online SGD); a batch-mean divides the
        # per-pair step by B (=512 default), leaving the embeddings at
        # ~their random init within any realistic epoch budget — the
        # measured "similarity" was just init noise (root cause of the
        # seed's two topic-clustering test failures)
        return -jnp.sum(ll.sum(-1) * weights)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


@functools.partial(jax.jit, donate_argnums=(0,))
def _sgns_step(params, lr, center, context, negatives, weights):
    """One skip-gram-negative-sampling SGD step (whole batch, one XLA exec).

    center/context: (B,) int32; negatives: (B, K) int32; weights: (B,)
    0/1 mask so padded tail pairs contribute nothing.
    """

    def loss_fn(p):
        v = p["syn0"][center]                       # (B, D)
        u_pos = p["syn1"][context]                  # (B, D)
        u_neg = p["syn1"][negatives]                # (B, K, D)
        pos = jax.nn.log_sigmoid(jnp.sum(v * u_pos, -1))
        neg = jax.nn.log_sigmoid(-jnp.einsum("bd,bkd->bk", v, u_neg)).sum(-1)
        # sum, not mean — per-pair learning-rate semantics (see _hs_step)
        return -jnp.sum((pos + neg) * weights)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


class WordVectors:
    """Lookup/similarity surface (≡ embeddings.wordvectors.WordVectors)."""

    vocab: VocabCache

    def _table(self):
        return np.asarray(self.params["syn0"], np.float32)

    def hasWord(self, word):
        return self.vocab.containsWord(word)

    def getWordVector(self, word):
        i = self.vocab.indexOf(word)
        if i < 0:
            raise KeyError(f"word not in vocab: {word!r}")
        return self._table()[i]

    def getWordVectorMatrix(self, word):
        return self.getWordVector(word)

    def similarity(self, w1, w2):
        a, b = self.getWordVector(w1), self.getWordVector(w2)
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 0.0
        return float(a @ b / (na * nb))

    def wordsNearest(self, word_or_vec, topN=10):
        if isinstance(word_or_vec, str):
            vec = self.getWordVector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec, exclude = np.asarray(word_or_vec, np.float32), set()
        tab = self._table()
        norms = np.linalg.norm(tab, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = tab @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.wordAtIndex(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= topN:
                break
        return out

    def vocabSize(self):
        return self.vocab.numWords()


class Word2Vec(WordVectors):
    """≡ models.word2vec.Word2Vec — built via the same fluent Builder."""

    class Builder:
        def __init__(self):
            self._min_count = 5
            self._iterations = 1
            self._epochs = 1
            self._layer_size = 100
            self._seed = 42
            self._window = 5
            self._lr = 0.025
            self._negative = 5
            self._hs = False
            self._sample = 1e-3
            self._batch = 1024
            self._iter = None
            self._tok = DefaultTokenizerFactory()

        def minWordFrequency(self, v):
            self._min_count = int(v); return self

        def iterations(self, v):
            self._iterations = int(v); return self

        def epochs(self, v):
            self._epochs = int(v); return self

        def layerSize(self, v):
            self._layer_size = int(v); return self

        def seed(self, v):
            self._seed = int(v); return self

        def windowSize(self, v):
            self._window = int(v); return self

        def learningRate(self, v):
            self._lr = float(v); return self

        def negativeSample(self, v):
            self._negative = int(v); return self

        def useHierarchicSoftmax(self, flag=True):
            """≡ Word2Vec.Builder.useHierarchicSoftmax: train against the
            Huffman-tree output layer instead of negative sampling (each
            pair updates its context's ~log2(V) inner nodes)."""
            self._hs = bool(flag); return self

        def sampling(self, v):
            self._sample = float(v); return self

        def batchSize(self, v):
            self._batch = int(v); return self

        def iterate(self, sentence_iterator):
            if isinstance(sentence_iterator, (list, tuple)):
                sentence_iterator = CollectionSentenceIterator(
                    sentence_iterator)
            self._iter = sentence_iterator
            return self

        def tokenizerFactory(self, tok):
            self._tok = tok; return self

        def build(self):
            return Word2Vec(self)

    def __init__(self, builder):
        self.b = builder
        self.vocab = VocabCache()
        self.params = None
        self._rng = np.random.default_rng(builder._seed)

    # -- corpus → ids ----------------------------------------------------
    def _tokenized(self):
        out = []
        for sent in self.b._iter:
            out.append(self.b._tok.create(sent).getTokens())
        return out

    def buildVocab(self, sentences_tokens):
        self.vocab = build_vocab(sentences_tokens, self.b._min_count)

    def _init_params(self):
        v, d = self.vocab.numWords(), self.b._layer_size
        key = jax.random.PRNGKey(self.b._seed)
        syn0 = (jax.random.uniform(key, (v, d), jnp.float32) - 0.5) / d
        # hierarchical softmax trains V-1 inner-node vectors instead of
        # per-word output vectors
        rows = max(v - 1, 1) if self.b._hs else v
        self.params = {"syn0": syn0,
                       "syn1": jnp.zeros((rows, d), jnp.float32)}
        if self.b._hs:
            pts, codes, mask = _build_huffman(self.vocab.counts)
            self._hs_tables = (jnp.asarray(pts), jnp.asarray(codes),
                               jnp.asarray(mask))

    def _pairs(self, sentences_ids):
        """Skip-gram pairs with dynamic window + subsampling (host side)."""
        keep = self.vocab.keep_probs(self.b._sample)
        centers, contexts = [], []
        for ids in sentences_ids:
            ids = np.asarray(ids, np.int64)
            if self.b._sample:
                ids = ids[self._rng.random(len(ids)) < keep[ids]]
            n = len(ids)
            if n < 2:
                continue
            for i in range(n):
                b = self._rng.integers(1, self.b._window + 1)
                lo, hi = max(0, i - b), min(n, i + b + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(ids[i])
                        contexts.append(ids[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _batches(self, centers, contexts):
        """Shared epoch batcher: shuffle, pad to the fixed batch shape,
        sample negatives from the unigram^0.75 table, yield host-side
        (center, context, negatives, weights) slices — jit uploads them,
        so callers can still index host tables by center id for free."""
        n = len(centers)
        if n == 0:
            return
        B, K = self.b._batch, max(1, self.b._negative)
        neg_p = self.vocab.negative_table()
        perm = self._rng.permutation(n)
        centers, contexts = centers[perm], contexts[perm]
        pad = (-n) % B
        weights = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)])
        centers = np.concatenate([centers, np.zeros(pad, np.int32)])
        contexts = np.concatenate([contexts, np.zeros(pad, np.int32)])
        if getattr(self.b, "_hs", False):   # HS path never reads them
            negs = np.zeros((len(centers), 1), np.int32)
        else:
            negs = self._rng.choice(self.vocab.numWords(),
                                    size=(len(centers), K),
                                    p=neg_p).astype(np.int32)
        for s in range(0, len(centers), B):
            yield (centers[s:s + B], contexts[s:s + B],
                   negs[s:s + B], weights[s:s + B])

    def _run_epochs(self, centers_contexts_fn, epochs):
        hs = getattr(self.b, "_hs", False)
        for _ in range(epochs):
            centers, contexts = centers_contexts_fn()
            for cen, ctx, negs, w in self._batches(centers, contexts):
                if hs:
                    pts, codes, mask = self._hs_tables
                    self.params, _ = _hs_step(self.params, self.b._lr,
                                              cen, ctx, pts, codes, mask,
                                              w)
                else:
                    self.params, _ = _sgns_step(self.params, self.b._lr,
                                                cen, ctx, negs, w)

    def fit(self):
        toks = self._tokenized()
        self.buildVocab(toks)
        if self.vocab.numWords() == 0:
            raise ValueError("empty vocabulary after min-count pruning")
        self._init_params()
        w2i = self.vocab.word2idx
        sentences_ids = [[w2i[t] for t in s if t in w2i] for s in toks]
        self._run_epochs(lambda: self._pairs(sentences_ids),
                         self.b._epochs * self.b._iterations)
        return self
