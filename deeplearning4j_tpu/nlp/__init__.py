"""NLP (≡ deeplearning4j-nlp): Word2Vec, ParagraphVectors, GloVe,
FastText, tokenizers, sentence iterators, vocabulary cache."""
from deeplearning4j_tpu.nlp.tokenization import (BasicLineIterator,
                                                 CollectionSentenceIterator,
                                                 CommonPreprocessor,
                                                 DefaultTokenizerFactory,
                                                 LowCasePreProcessor,
                                                 NGramTokenizerFactory,
                                                 SentenceIterator, Tokenizer,
                                                 TokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, WordVectors
from deeplearning4j_tpu.nlp.paragraph_vectors import (LabelledDocument,
                                                      ParagraphVectors)
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.fasttext import FastText, char_ngrams
from deeplearning4j_tpu.nlp.serializer import (StaticWordVectors,
                                               WordVectorSerializer)
from deeplearning4j_tpu.nlp.cnn_sentence_iterator import (
    CnnSentenceDataSetIterator, CollectionLabeledSentenceProvider)
from deeplearning4j_tpu.nlp.sequence_vectors import (AbstractSequenceIterator,
                                                     SequenceVectors)
from deeplearning4j_tpu.nlp.vectorizers import (BagOfWordsVectorizer,
                                                TfidfVectorizer)

__all__ = [
    "WordVectorSerializer", "StaticWordVectors",
    "BasicLineIterator", "CollectionSentenceIterator", "CommonPreprocessor",
    "DefaultTokenizerFactory", "LowCasePreProcessor", "NGramTokenizerFactory",
    "SentenceIterator", "Tokenizer", "TokenizerFactory", "VocabCache",
    "build_vocab", "Word2Vec", "WordVectors", "LabelledDocument",
    "ParagraphVectors", "Glove", "FastText", "char_ngrams",
    "CnnSentenceDataSetIterator", "CollectionLabeledSentenceProvider",
    "SequenceVectors", "AbstractSequenceIterator",
    "BagOfWordsVectorizer", "TfidfVectorizer",
]
