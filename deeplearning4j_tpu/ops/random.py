"""Seeded PRNG streams (≡ nd4j NativeRandom / Nd4j.getRandom).

A stateful convenience wrapper over jax.random: each draw splits the key, so
host-side data/init code gets ND4J-style sequential semantics while
everything inside jit still takes explicit keys.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class RandomState:
    def __init__(self, seed: int = 0):
        # LAZY key creation: PRNGKey() initializes the XLA backend, and the
        # module-level Nd4j singleton builds a RandomState at import — an
        # eager key here breaks jax.distributed.initialize(), which must
        # run before ANY backend touch (multi-host bring-up).
        self._seed = int(seed)
        self._key = None

    def setSeed(self, seed: int):
        self._seed = int(seed)
        self._key = None

    def split(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def uniform(self, shape=(), low=0.0, high=1.0, dtype=jnp.float32):
        return jax.random.uniform(self.split(), shape, dtype=dtype, minval=low, maxval=high)

    def normal(self, shape=(), mean=0.0, std=1.0, dtype=jnp.float32):
        return mean + std * jax.random.normal(self.split(), shape, dtype=dtype)

    def randint(self, low, high, shape=()):
        return jax.random.randint(self.split(), shape, low, high)

    def bernoulli(self, p, shape=()):
        return jax.random.bernoulli(self.split(), p, shape)

    def permutation(self, n):
        return jax.random.permutation(self.split(), n)

    def shuffle(self, x, axis=0):
        return jax.random.permutation(self.split(), x, axis=axis, independent=False)
