"""`nd` — the Nd4j-equivalent array factory + op-catalog namespace.

Parity target: nd4j-api :: org.nd4j.linalg.factory.Nd4j and the
`Transforms` op catalog (reference mount empty; reconstructed surface).
Usage mirrors the reference: `nd.zeros(3, 4)`, `nd.rand(2, 2)`,
`nd.exp(x)`, `nd.concat(0, a, b)`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.ops  # noqa: F401 — segment_* reductions
import numpy as np

from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax, resolve_dtype
from deeplearning4j_tpu.ops.random import RandomState


def _shape(args):
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(args[0])
    return tuple(int(a) for a in args)



def _num_segments(ids, num_segments):
    return int(num_segments) if num_segments is not None \
        else int(jnp.max(ids)) + 1


class _Nd:
    """Singleton factory namespace (≡ static class Nd4j)."""

    def __init__(self):
        self._random = RandomState(0)
        self.default_dtype = jnp.float32

    # -- randomness ------------------------------------------------------
    def getRandom(self):
        return self._random

    def setSeed(self, seed):
        self._random = RandomState(int(seed))

    # -- creation --------------------------------------------------------
    def create(self, data, shape=None, dtype=None):
        arr = NDArray(data, dtype=dtype or self.default_dtype)
        if shape is not None:
            arr = arr.reshape(shape)
        return arr

    def array(self, data, dtype=None):
        return NDArray(data, dtype=dtype)

    def zeros(self, *shape, dtype=None):
        return NDArray(jnp.zeros(_shape(shape), dtype=resolve_dtype(dtype) or self.default_dtype))

    def ones(self, *shape, dtype=None):
        return NDArray(jnp.ones(_shape(shape), dtype=resolve_dtype(dtype) or self.default_dtype))

    def zerosLike(self, x):
        return NDArray(jnp.zeros_like(as_jax(x)))

    def onesLike(self, x):
        return NDArray(jnp.ones_like(as_jax(x)))

    def valueArrayOf(self, shape, value, dtype=None):
        return NDArray(jnp.full(_shape([shape]) if isinstance(shape, (tuple, list)) else (shape,),
                                value, dtype=resolve_dtype(dtype) or self.default_dtype))

    def full(self, shape, value, dtype=None):
        return NDArray(jnp.full(tuple(shape), value, dtype=resolve_dtype(dtype) or self.default_dtype))

    def eye(self, n, dtype=None):
        return NDArray(jnp.eye(n, dtype=resolve_dtype(dtype) or self.default_dtype))

    def linspace(self, start, stop, num, dtype=None):
        return NDArray(jnp.linspace(start, stop, num, dtype=resolve_dtype(dtype) or self.default_dtype))

    def arange(self, *args, dtype=None):
        return NDArray(jnp.arange(*args, dtype=resolve_dtype(dtype)))

    def rand(self, *shape):
        return NDArray(self._random.uniform(_shape(shape)))

    def randn(self, *shape):
        return NDArray(self._random.normal(_shape(shape)))

    def randint(self, low, high, shape):
        return NDArray(self._random.randint(low, high, tuple(shape)))

    def empty(self, dtype=None):
        return NDArray(jnp.zeros((0,), dtype=resolve_dtype(dtype) or self.default_dtype))

    def scalar(self, value, dtype=None):
        return NDArray(jnp.asarray(value, dtype=resolve_dtype(dtype)))

    # -- combination -----------------------------------------------------
    def concat(self, dim, *arrays):
        return NDArray(jnp.concatenate([as_jax(a) for a in arrays], axis=dim))

    def vstack(self, *arrays):
        return NDArray(jnp.vstack([as_jax(a) for a in arrays]))

    def hstack(self, *arrays):
        return NDArray(jnp.hstack([as_jax(a) for a in arrays]))

    def stack(self, dim, *arrays):
        return NDArray(jnp.stack([as_jax(a) for a in arrays], axis=dim))

    def pile(self, *arrays):
        return self.stack(0, *arrays)

    def tile(self, x, *reps):
        return NDArray(jnp.tile(as_jax(x), _shape(reps)))

    def repeat(self, x, repeats, axis=None):
        return NDArray(jnp.repeat(as_jax(x), repeats, axis=axis))

    def where(self, cond, x=None, y=None):
        if x is None:
            return NDArray(jnp.argwhere(as_jax(cond)))
        return NDArray(jnp.where(as_jax(cond), as_jax(x), as_jax(y)))

    def pad(self, x, pad_width, mode="constant", value=0.0):
        kw = {"constant_values": value} if mode == "constant" else {}
        return NDArray(jnp.pad(as_jax(x), pad_width, mode=mode, **kw))

    def sortWithIndices(self, x, dim=-1, ascending=True):
        a = as_jax(x)
        idx = jnp.argsort(a, axis=dim)
        if not ascending:
            idx = jnp.flip(idx, axis=dim)
        return NDArray(jnp.take_along_axis(a, idx, axis=dim)), NDArray(idx)

    def sort(self, x, dim=-1, ascending=True):
        a = jnp.sort(as_jax(x), axis=dim)
        return NDArray(a if ascending else jnp.flip(a, axis=dim))

    def flip(self, x, *dims):
        return NDArray(jnp.flip(as_jax(x), axis=_shape(dims) if dims else None))

    def gather(self, x, indices, axis=0):
        return NDArray(jnp.take(as_jax(x), as_jax(indices).astype(jnp.int32), axis=axis))

    def oneHot(self, indices, depth, dtype=None):
        return NDArray(jax.nn.one_hot(as_jax(indices).astype(jnp.int32), depth,
                                      dtype=resolve_dtype(dtype) or self.default_dtype))

    def diag(self, x):
        return NDArray(jnp.diag(as_jax(x)))

    # -- transforms op catalog (≡ ops.transforms.Transforms) -------------
    # -- scatter ops (≡ Nd4j scatter_upd/scatter_add/... via op exec) -----
    def scatterUpdate(self, ref, indices, updates):
        """ref[indices[i]] = updates[i] along dim 0; duplicate indices take
        the LAST update (the reference's scatter_upd ordering — a bare
        .at[].set() is nondeterministic for duplicates on XLA, so the last
        occurrence is selected explicitly via segment_max)."""
        a = as_jax(ref)
        ids = jnp.asarray(indices)
        upd = as_jax(updates)
        n = ids.shape[0]
        last = jax.ops.segment_max(jnp.arange(n), ids,
                                   num_segments=a.shape[0])
        touched = jax.ops.segment_sum(jnp.ones_like(ids), ids,
                                      num_segments=a.shape[0]) > 0
        gathered = upd[jnp.clip(last, 0, n - 1)]
        mask = touched.reshape((-1,) + (1,) * (a.ndim - 1))
        return NDArray(jnp.where(mask, gathered, a))

    def scatterAdd(self, ref, indices, updates):
        a = as_jax(ref)
        return NDArray(a.at[jnp.asarray(indices)].add(as_jax(updates)))

    def scatterSub(self, ref, indices, updates):
        a = as_jax(ref)
        return NDArray(a.at[jnp.asarray(indices)].add(-as_jax(updates)))

    def scatterMax(self, ref, indices, updates):
        a = as_jax(ref)
        return NDArray(a.at[jnp.asarray(indices)].max(as_jax(updates)))

    def scatterMin(self, ref, indices, updates):
        a = as_jax(ref)
        return NDArray(a.at[jnp.asarray(indices)].min(as_jax(updates)))

    # -- segment reductions (≡ nd4j segment_* / unsorted_segment_* ops) ---
    def segmentSum(self, data, segment_ids, num_segments=None):
        ids = jnp.asarray(segment_ids)
        n = _num_segments(ids, num_segments)
        return NDArray(jax.ops.segment_sum(as_jax(data), ids,
                                           num_segments=n))

    def unsortedSegmentSum(self, data, segment_ids, num_segments):
        return self.segmentSum(data, segment_ids, num_segments)

    def segmentMean(self, data, segment_ids, num_segments=None):
        ids = jnp.asarray(segment_ids)
        n = _num_segments(ids, num_segments)
        tot = jax.ops.segment_sum(as_jax(data), ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, jnp.float32), ids,
                                  num_segments=n)
        shape = (n,) + (1,) * (tot.ndim - 1)
        return NDArray(tot / jnp.maximum(cnt, 1.0).reshape(shape))

    def unsortedSegmentMean(self, data, segment_ids, num_segments):
        return self.segmentMean(data, segment_ids, num_segments)

    def segmentMax(self, data, segment_ids, num_segments=None):
        ids = jnp.asarray(segment_ids)
        n = _num_segments(ids, num_segments)
        return NDArray(jax.ops.segment_max(as_jax(data), ids,
                                           num_segments=n))

    def unsortedSegmentMax(self, data, segment_ids, num_segments):
        return self.segmentMax(data, segment_ids, num_segments)

    def segmentMin(self, data, segment_ids, num_segments=None):
        ids = jnp.asarray(segment_ids)
        n = _num_segments(ids, num_segments)
        return NDArray(jax.ops.segment_min(as_jax(data), ids,
                                           num_segments=n))

    def unsortedSegmentMin(self, data, segment_ids, num_segments):
        return self.segmentMin(data, segment_ids, num_segments)

    def segmentProd(self, data, segment_ids, num_segments=None):
        ids = jnp.asarray(segment_ids)
        n = _num_segments(ids, num_segments)
        return NDArray(jax.ops.segment_prod(as_jax(data), ids,
                                            num_segments=n))

    def unsortedSegmentProd(self, data, segment_ids, num_segments):
        return self.segmentProd(data, segment_ids, num_segments)

    # -- shape utilities --------------------------------------------------
    def expandDims(self, x, dim):
        return NDArray(jnp.expand_dims(as_jax(x), int(dim)))

    def squeeze(self, x, dim=None):
        return NDArray(jnp.squeeze(as_jax(x),
                                   None if dim is None else int(dim)))

    def meshgrid(self, *xs, indexing="ij"):
        return [NDArray(g) for g in
                jnp.meshgrid(*[as_jax(x) for x in xs], indexing=indexing)]

    def triu(self, x, k=0):
        return NDArray(jnp.triu(as_jax(x), int(k)))

    def tril(self, x, k=0):
        return NDArray(jnp.tril(as_jax(x), int(k)))

    def _unary(self, x, fn):
        return NDArray(fn(as_jax(x)))

    def exp(self, x):
        return self._unary(x, jnp.exp)

    def log(self, x):
        return self._unary(x, jnp.log)

    def log1p(self, x):
        return self._unary(x, jnp.log1p)

    def sqrt(self, x):
        return self._unary(x, jnp.sqrt)

    def square(self, x):
        return self._unary(x, jnp.square)

    def abs(self, x):
        return self._unary(x, jnp.abs)

    def sign(self, x):
        return self._unary(x, jnp.sign)

    def floor(self, x):
        return self._unary(x, jnp.floor)

    def ceil(self, x):
        return self._unary(x, jnp.ceil)

    def round(self, x):
        return self._unary(x, jnp.round)

    def sin(self, x):
        return self._unary(x, jnp.sin)

    def cos(self, x):
        return self._unary(x, jnp.cos)

    def tan(self, x):
        return self._unary(x, jnp.tan)

    def tanh(self, x):
        return self._unary(x, jnp.tanh)

    def sigmoid(self, x):
        return self._unary(x, jax.nn.sigmoid)

    def relu(self, x):
        return self._unary(x, jax.nn.relu)

    def leakyRelu(self, x, alpha=0.01):
        return NDArray(jax.nn.leaky_relu(as_jax(x), negative_slope=alpha))

    def elu(self, x):
        return self._unary(x, jax.nn.elu)

    def softmax(self, x, axis=-1):
        return NDArray(jax.nn.softmax(as_jax(x), axis=axis))

    def logSoftmax(self, x, axis=-1):
        return NDArray(jax.nn.log_softmax(as_jax(x), axis=axis))

    def softplus(self, x):
        return self._unary(x, jax.nn.softplus)

    def pow(self, x, p):
        return NDArray(jnp.power(as_jax(x), p))

    def clip(self, x, lo, hi):
        return NDArray(jnp.clip(as_jax(x), lo, hi))

    def isNaN(self, x):
        return self._unary(x, jnp.isnan)

    def isInf(self, x):
        return self._unary(x, jnp.isinf)

    def maximum(self, a, b):
        return NDArray(jnp.maximum(as_jax(a), as_jax(b)))

    def minimum(self, a, b):
        return NDArray(jnp.minimum(as_jax(a), as_jax(b)))

    def cosineSim(self, a, b):
        a, b = as_jax(a).ravel(), as_jax(b).ravel()
        return float(jnp.dot(a, b) / (jnp.linalg.norm(a) * jnp.linalg.norm(b) + 1e-12))

    def euclideanDistance(self, a, b):
        return float(jnp.linalg.norm(as_jax(a).ravel() - as_jax(b).ravel()))

    def manhattanDistance(self, a, b):
        return float(jnp.sum(jnp.abs(as_jax(a).ravel() - as_jax(b).ravel())))

    # -- linalg ----------------------------------------------------------
    def matmul(self, a, b):
        return NDArray(jnp.matmul(as_jax(a), as_jax(b)))

    gemm = matmul

    def dot(self, a, b):
        return NDArray(jnp.dot(as_jax(a), as_jax(b)))

    def norm2(self, x):
        return float(jnp.linalg.norm(as_jax(x)))

    # -- host/device -----------------------------------------------------
    def toNumpy(self, x):
        return np.asarray(as_jax(x))

    def fromNumpy(self, x):
        return NDArray(x)

    # -- array file IO (≡ Nd4j.write/read/saveBinary/readBinary/
    #    writeTxt/readTxt — npy is the interchange format here, matching
    #    Nd4j.writeAsNumpy/createFromNpyFile) ---------------------------
    def write(self, arr, path_or_stream):
        a = np.asarray(as_jax(arr))
        if isinstance(path_or_stream, str):
            # np.save appends .npy to bare string paths — honour the
            # exact path the caller asked for
            with open(path_or_stream, "wb") as f:
                np.save(f, a, allow_pickle=False)
        else:
            np.save(path_or_stream, a, allow_pickle=False)

    saveBinary = write
    writeAsNumpy = write

    def read(self, path_or_stream):
        return NDArray(np.load(path_or_stream, allow_pickle=False))

    readBinary = read
    createFromNpyFile = read

    def writeTxt(self, arr, path):
        a = np.asarray(as_jax(arr))
        with open(path, "w") as f:
            f.write(f"# shape={a.shape} dtype={a.dtype.name}\n")
            flat = a.reshape(1, 1) if a.ndim == 0 else (
                a.reshape(-1, a.shape[-1]) if a.ndim > 1 else a[None, :])
            # value-exact round trip like the npy path: integers print as
            # integers, floats with full precision (%.17g survives f64)
            fmt = "%d" if np.issubdtype(a.dtype, np.integer) else "%.17g"
            np.savetxt(f, flat, fmt=fmt)

    def readTxt(self, path):
        with open(path) as f:
            header = f.readline()
            import ast
            shape = ast.literal_eval(
                header.split("shape=")[1].split(" dtype")[0])
            dtype = np.dtype(header.split("dtype=")[1].strip())
            # parse integers as integers — routing them through float64
            # would silently truncate values beyond 2**53. Files written
            # before the integer fmt existed hold scientific notation, so
            # fall back to the float path for those.
            body = f.read()
            if np.issubdtype(dtype, np.integer):
                try:
                    data = np.loadtxt(body.splitlines(), dtype=dtype,
                                      ndmin=2)
                except ValueError:
                    data = np.loadtxt(body.splitlines(), dtype=np.float64,
                                      ndmin=2)
            else:
                data = np.loadtxt(body.splitlines(), dtype=np.float64,
                                  ndmin=2)
        return NDArray(data.reshape(shape).astype(dtype))


nd = _Nd()
