from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax, resolve_dtype
from deeplearning4j_tpu.ops.factory import nd
from deeplearning4j_tpu.ops.ops import (BooleanIndexing, Conditions,
                                        Transforms)
from deeplearning4j_tpu.ops.random import RandomState

__all__ = ["NDArray", "nd", "RandomState", "as_jax", "resolve_dtype",
           "BooleanIndexing", "Conditions", "Transforms"]
