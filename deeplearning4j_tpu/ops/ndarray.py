"""NDArray — the INDArray-equivalent array facade.

Parity target: nd4j-api :: org.nd4j.linalg.api.ndarray.INDArray (reference
mount empty; surface reconstructed from the Eclipse ND4J API). The facade
wraps a `jax.Array`; all math lowers to jax.numpy so it fuses under jit and
tiles onto the TPU MXU/VPU. Unlike INDArray there is no mutable device
buffer: "in-place" (`addi`, `muli`, ...) methods rebind the wrapped value
and return self, which preserves the reference's calling convention while
staying functional underneath.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_DTYPES = {
    "float": jnp.float32, "float32": jnp.float32, "double": jnp.float64,
    "float64": jnp.float64, "half": jnp.float16, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "int": jnp.int32, "int32": jnp.int32,
    "long": jnp.int64, "int64": jnp.int64, "int16": jnp.int16,
    "int8": jnp.int8, "uint8": jnp.uint8, "bool": jnp.bool_,
}


def resolve_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _DTYPES[dtype.lower()]
    return jnp.dtype(dtype)


def as_jax(x):
    """Unwrap NDArray / convert python+numpy values to a jnp array."""
    if isinstance(x, NDArray):
        return x.jax()
    return jnp.asarray(x)


def _wrap(x):
    return NDArray(x)


class NDArray:
    """N-dimensional array with the INDArray calling convention."""

    __slots__ = ("_a",)
    # Make jnp.asarray(NDArray) and reverse binary ops prefer our methods.
    __array_priority__ = 100

    def __init__(self, value, dtype=None):
        dt = resolve_dtype(dtype)
        if isinstance(value, NDArray):
            value = value._a
        self._a = jnp.asarray(value, dtype=dt)

    # -- interop ---------------------------------------------------------
    def jax(self):
        return self._a

    def numpy(self):
        return np.asarray(self._a)

    def toDoubleVector(self):
        return self.numpy().astype(np.float64).ravel()

    def toFloatVector(self):
        return self.numpy().astype(np.float32).ravel()

    def toIntVector(self):
        return self.numpy().astype(np.int64).ravel()

    def __array__(self, dtype=None):
        a = np.asarray(self._a)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._a

    # -- shape / dtype ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._a.shape)

    @property
    def dtype(self):
        return self._a.dtype

    def rank(self):
        return self._a.ndim

    def length(self):
        return int(np.prod(self._a.shape)) if self._a.ndim else 1

    def size(self, dim):
        return self._a.shape[dim]

    def isScalar(self):
        return self._a.ndim == 0 or self.length() == 1

    def isVector(self):
        return self._a.ndim == 1 or (self._a.ndim == 2 and 1 in self._a.shape)

    def isMatrix(self):
        return self._a.ndim == 2

    def rows(self):
        return self._a.shape[0]

    def columns(self):
        return self._a.shape[1]

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _wrap(self._a.reshape(shape))

    def ravel(self):
        return _wrap(self._a.ravel())

    def transpose(self, *axes):
        if not axes:
            return _wrap(self._a.T)
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _wrap(jnp.transpose(self._a, axes))

    def permute(self, *axes):
        return self.transpose(*axes)

    def swapAxes(self, a, b):
        return _wrap(jnp.swapaxes(self._a, a, b))

    def broadcast(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _wrap(jnp.broadcast_to(self._a, shape))

    def dup(self):
        return _wrap(self._a)

    def castTo(self, dtype):
        return _wrap(self._a.astype(resolve_dtype(dtype)))

    def astype(self, dtype):
        return self.castTo(dtype)

    # -- elementwise arithmetic (returning copies) -----------------------
    def _binary(self, other, fn):
        return _wrap(fn(self._a, as_jax(other)))

    def add(self, other):
        return self._binary(other, jnp.add)

    def sub(self, other):
        return self._binary(other, jnp.subtract)

    def mul(self, other):
        return self._binary(other, jnp.multiply)

    def div(self, other):
        return self._binary(other, jnp.divide)

    def rsub(self, other):
        return _wrap(as_jax(other) - self._a)

    def rdiv(self, other):
        return _wrap(as_jax(other) / self._a)

    def neg(self):
        return _wrap(-self._a)

    # -- "in-place" variants: rebind and return self ---------------------
    def _inplace(self, other, fn):
        self._a = fn(self._a, as_jax(other))
        return self

    def addi(self, other):
        return self._inplace(other, jnp.add)

    def subi(self, other):
        return self._inplace(other, jnp.subtract)

    def muli(self, other):
        return self._inplace(other, jnp.multiply)

    def divi(self, other):
        return self._inplace(other, jnp.divide)

    def assign(self, other):
        val = as_jax(other)
        self._a = jnp.broadcast_to(val, self._a.shape).astype(self._a.dtype)
        return self

    def negi(self):
        self._a = -self._a
        return self

    # -- linalg ----------------------------------------------------------
    def mmul(self, other):
        return _wrap(jnp.matmul(self._a, as_jax(other)))

    def dot(self, other):
        return _wrap(jnp.dot(self._a, as_jax(other)))

    def tensorMmul(self, other, axes):
        return _wrap(jnp.tensordot(self._a, as_jax(other), axes=axes))

    # -- broadcast-along-dimension (ND4J row/column ops) -----------------
    def addRowVector(self, row):
        return _wrap(self._a + as_jax(row).reshape(1, -1))

    def addColumnVector(self, col):
        return _wrap(self._a + as_jax(col).reshape(-1, 1))

    def subRowVector(self, row):
        return _wrap(self._a - as_jax(row).reshape(1, -1))

    def subColumnVector(self, col):
        return _wrap(self._a - as_jax(col).reshape(-1, 1))

    def mulRowVector(self, row):
        return _wrap(self._a * as_jax(row).reshape(1, -1))

    def mulColumnVector(self, col):
        return _wrap(self._a * as_jax(col).reshape(-1, 1))

    def divRowVector(self, row):
        return _wrap(self._a / as_jax(row).reshape(1, -1))

    def divColumnVector(self, col):
        return _wrap(self._a / as_jax(col).reshape(-1, 1))

    # -- reductions ------------------------------------------------------
    def _reduce(self, fn, dims, keepdims=False):
        axis = None
        if dims:
            axis = dims[0] if len(dims) == 1 else tuple(dims)
        return _wrap(fn(self._a, axis=axis, keepdims=keepdims))

    def sum(self, *dims, keepdims=False):
        return self._reduce(jnp.sum, dims, keepdims)

    def mean(self, *dims, keepdims=False):
        return self._reduce(jnp.mean, dims, keepdims)

    def max(self, *dims, keepdims=False):
        return self._reduce(jnp.max, dims, keepdims)

    def min(self, *dims, keepdims=False):
        return self._reduce(jnp.min, dims, keepdims)

    def prod(self, *dims, keepdims=False):
        return self._reduce(jnp.prod, dims, keepdims)

    def std(self, *dims, biasCorrected=True, keepdims=False):
        ddof = 1 if biasCorrected else 0
        axis = None
        if dims:
            axis = dims[0] if len(dims) == 1 else tuple(dims)
        return _wrap(jnp.std(self._a, axis=axis, ddof=ddof, keepdims=keepdims))

    def var(self, *dims, biasCorrected=True, keepdims=False):
        ddof = 1 if biasCorrected else 0
        axis = None
        if dims:
            axis = dims[0] if len(dims) == 1 else tuple(dims)
        return _wrap(jnp.var(self._a, axis=axis, ddof=ddof, keepdims=keepdims))

    def argMax(self, *dims):
        axis = dims[0] if dims else None
        return _wrap(jnp.argmax(self._a, axis=axis))

    def argMin(self, *dims):
        axis = dims[0] if dims else None
        return _wrap(jnp.argmin(self._a, axis=axis))

    def norm1(self, *dims):
        return self._reduce(lambda a, axis, keepdims: jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims), dims)

    def norm2(self, *dims):
        return self._reduce(lambda a, axis, keepdims: jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdims)), dims)

    def normmax(self, *dims):
        return self._reduce(lambda a, axis, keepdims: jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims), dims)

    def cumsum(self, dim=0):
        return _wrap(jnp.cumsum(self._a, axis=dim))

    def cumprod(self, dim=0):
        return _wrap(jnp.cumprod(self._a, axis=dim))

    # -- absolute-value reductions (≡ INDArray.amax/amin/amean/asum) ------
    def amax(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.max(
            jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    def amin(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.min(
            jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    def amean(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.mean(
            jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    def asum(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.sum(
            jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    # -- entropy reductions (≡ INDArray.entropy/shannonEntropy/logEntropy):
    # defined over the array as a probability/likelihood surface
    def entropy(self, *dims):
        return self._reduce(lambda a, axis, keepdims: -jnp.sum(
            a * jnp.log(a), axis=axis, keepdims=keepdims), dims)

    def shannonEntropy(self, *dims):
        return self._reduce(lambda a, axis, keepdims: -jnp.sum(
            a * jnp.log2(a), axis=axis, keepdims=keepdims), dims)

    def logEntropy(self, *dims):
        return _wrap(jnp.log(jnp.asarray(self.entropy(*dims))))

    # -- views (≡ INDArray.slice / tensorAlongDimension / repeat / tile) --
    def slice(self, i, dim=0):
        """i-th subtensor along `dim` (≡ INDArray.slice)."""
        return _wrap(jnp.take(self._a, int(i), axis=int(dim)))

    def tensorAlongDimension(self, index, *dims):
        """The index-th tensor when iterating over all dims NOT in `dims`
        (≡ INDArray.tensorAlongDimension / TAD). Kept-out dims iterate in
        C order, matching the reference's TAD enumeration."""
        dims = sorted(d % self._a.ndim for d in dims)
        iter_dims = [d for d in range(self._a.ndim) if d not in dims]
        # move iteration dims to the front, flatten, index
        perm = iter_dims + dims
        moved = jnp.transpose(self._a, perm)
        lead = 1
        for d in iter_dims:
            lead *= self._a.shape[d]
        moved = moved.reshape((lead,) + tuple(self._a.shape[d] for d in dims))
        return _wrap(moved[int(index)])

    def tensorsAlongDimension(self, *dims):
        """Count of TADs for `dims` (≡ INDArray.tensorsAlongDimension)."""
        dims = {d % self._a.ndim for d in dims}
        n = 1
        for d in range(self._a.ndim):
            if d not in dims:
                n *= self._a.shape[d]
        return n

    def repeat(self, dim, repeats):
        """≡ INDArray.repeat(dimension, repeatTimes) — dimension FIRST,
        matching the reference signature."""
        return _wrap(jnp.repeat(self._a, int(repeats), axis=int(dim)))

    def tile(self, *reps):
        return _wrap(jnp.tile(self._a, reps))

    def diag(self):
        return _wrap(jnp.diag(self._a))

    # -- comparisons -----------------------------------------------------
    def gt(self, other):
        return self._binary(other, jnp.greater)

    def gte(self, other):
        return self._binary(other, jnp.greater_equal)

    def lt(self, other):
        return self._binary(other, jnp.less)

    def lte(self, other):
        return self._binary(other, jnp.less_equal)

    def eq(self, other):
        return self._binary(other, jnp.equal)

    def neq(self, other):
        return self._binary(other, jnp.not_equal)

    def equalsWithEps(self, other, eps=1e-5):
        a, b = self._a, as_jax(other)
        return a.shape == b.shape and bool(jnp.all(jnp.abs(a - b) <= eps))

    def equals(self, other):
        return self.equalsWithEps(other, 1e-5)

    # -- indexing --------------------------------------------------------
    def __getitem__(self, idx):
        if isinstance(idx, NDArray):
            idx = idx.jax()
        return _wrap(self._a[idx])

    def get(self, *idx):
        return self.__getitem__(tuple(i if not isinstance(i, slice) else i for i in idx))

    def getScalar(self, *idx):
        return _wrap(self._a[tuple(idx)])

    def getDouble(self, *idx):
        return float(self._a[tuple(int(i) for i in idx)])

    def getInt(self, *idx):
        return int(self._a[tuple(int(i) for i in idx)])

    def getRow(self, i):
        return _wrap(self._a[i])

    def getColumn(self, i):
        return _wrap(self._a[:, i])

    def getRows(self, *rows):
        return _wrap(self._a[jnp.asarray(rows)])

    def getColumns(self, *cols):
        return _wrap(self._a[:, jnp.asarray(cols)])

    def put(self, idx, value):
        if isinstance(idx, (tuple, list)):
            idx = tuple(idx)
        self._a = self._a.at[idx].set(as_jax(value))
        return self

    def putScalar(self, idx, value):
        if isinstance(idx, (tuple, list)):
            idx = tuple(int(i) for i in idx)
        self._a = self._a.at[idx].set(value)
        return self

    def putRow(self, i, row):
        self._a = self._a.at[i].set(as_jax(row))
        return self

    def putColumn(self, i, col):
        self._a = self._a.at[:, i].set(as_jax(col))
        return self

    def __setitem__(self, idx, value):
        self.put(idx, value)

    # -- python protocol -------------------------------------------------
    def __add__(self, other):
        return self.add(other)

    __radd__ = __add__

    def __sub__(self, other):
        return self.sub(other)

    def __rsub__(self, other):
        return self.rsub(other)

    def __mul__(self, other):
        return self.mul(other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self.div(other)

    def __rtruediv__(self, other):
        return self.rdiv(other)

    def __matmul__(self, other):
        return self.mmul(other)

    def __neg__(self):
        return self.neg()

    def __pow__(self, p):
        return _wrap(self._a ** p)

    def __len__(self):
        return self._a.shape[0]

    def __float__(self):
        return float(self._a)

    def __int__(self):
        return int(self._a)

    def __repr__(self):
        return f"NDArray{self.shape}{np.asarray(self._a)!r}"

    def __str__(self):
        return str(np.asarray(self._a))


def _ndarray_flatten(x):
    return (x._a,), None


def _ndarray_unflatten(aux, children):
    obj = NDArray.__new__(NDArray)
    obj._a = children[0]
    return obj


jax.tree_util.register_pytree_node(NDArray, _ndarray_flatten, _ndarray_unflatten)
