"""Transforms op catalog (≡ nd4j-api ::
org.nd4j.linalg.ops.transforms.Transforms + the static op surface of
org.nd4j.linalg.factory.Nd4j: exec'd custom ops like softmax, boolean
indexing/conditions, comparisons).

Every function accepts NDArray/numpy/jax inputs and returns NDArray;
all lower to jax.numpy so calls inside a jit trace fuse into the
surrounding executable (the reference dispatches each as a separate
libnd4j op launch)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.factory import nd
from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax


def _u(fn):
    def wrapped(x, *args, **kw):
        return NDArray(fn(as_jax(x), *args, **kw))
    return wrapped


def _b(fn):
    def wrapped(a, b, *args, **kw):
        return NDArray(fn(as_jax(a), as_jax(b), *args, **kw))
    return wrapped


class Transforms:
    """≡ ops.transforms.Transforms static methods."""

    exp = staticmethod(_u(jnp.exp))
    log = staticmethod(_u(jnp.log))
    log1p = staticmethod(_u(jnp.log1p))
    sqrt = staticmethod(_u(jnp.sqrt))
    abs = staticmethod(_u(jnp.abs))
    sign = staticmethod(_u(jnp.sign))
    floor = staticmethod(_u(jnp.floor))
    ceil = staticmethod(_u(jnp.ceil))
    round = staticmethod(_u(jnp.round))
    sin = staticmethod(nd.sin)
    cos = staticmethod(nd.cos)
    tan = staticmethod(nd.tan)
    asin = staticmethod(_u(jnp.arcsin))
    acos = staticmethod(_u(jnp.arccos))
    atan = staticmethod(_u(jnp.arctan))
    sinh = staticmethod(_u(jnp.sinh))
    cosh = staticmethod(_u(jnp.cosh))
    tanh = staticmethod(nd.tanh)
    atanh = staticmethod(_u(jnp.arctanh))
    sigmoid = staticmethod(nd.sigmoid)
    sigmoidDerivative = staticmethod(_u(
        lambda x: jax.nn.sigmoid(x) * (1 - jax.nn.sigmoid(x))))
    hardSigmoid = staticmethod(_u(jax.nn.hard_sigmoid))
    hardTanh = staticmethod(_u(lambda x: jnp.clip(x, -1.0, 1.0)))
    relu = staticmethod(nd.relu)
    relu6 = staticmethod(_u(jax.nn.relu6))
    leakyRelu = staticmethod(nd.leakyRelu)
    elu = staticmethod(nd.elu)
    softPlus = staticmethod(nd.softplus)
    softsign = staticmethod(_u(jax.nn.soft_sign))
    gelu = staticmethod(_u(jax.nn.gelu))
    swish = staticmethod(_u(jax.nn.swish))
    mish = staticmethod(_u(lambda x: x * jnp.tanh(jax.nn.softplus(x))))
    erf = staticmethod(_u(jax.lax.erf))
    rsqrt = staticmethod(_u(jax.lax.rsqrt))
    reciprocal = staticmethod(_u(lambda x: 1.0 / x))
    square = staticmethod(_u(jnp.square))
    neg = staticmethod(_u(jnp.negative))

    softmax = staticmethod(nd.softmax)
    logSoftmax = staticmethod(nd.logSoftmax)
    pow = staticmethod(nd.pow)
    max = staticmethod(nd.maximum)
    min = staticmethod(nd.minimum)
    clip = staticmethod(nd.clip)

    atan2 = staticmethod(_b(jnp.arctan2))
    floorDiv = staticmethod(_b(jnp.floor_divide))
    floorMod = staticmethod(_b(jnp.mod))       # sign follows divisor
    fmod = staticmethod(_b(jnp.fmod))          # sign follows dividend

    # boolean ops (≡ Transforms.and/or/xor/not over condition arrays)
    and_ = staticmethod(_b(jnp.logical_and))
    or_ = staticmethod(_b(jnp.logical_or))
    xor = staticmethod(_b(jnp.logical_xor))
    not_ = staticmethod(_u(jnp.logical_not))

    @staticmethod
    def unitVec(x):
        a = as_jax(x)
        return NDArray(a / jnp.maximum(jnp.linalg.norm(a), 1e-12))

    @staticmethod
    def normalizeZeroMeanAndUnitVariance(x):
        a = as_jax(x)
        return NDArray((a - a.mean()) / jnp.maximum(a.std(), 1e-12))

    cosineSim = staticmethod(nd.cosineSim)
    euclideanDistance = staticmethod(nd.euclideanDistance)
    manhattanDistance = staticmethod(nd.manhattanDistance)

    @staticmethod
    def hammingDistance(a, b):
        return float((as_jax(a).ravel() != as_jax(b).ravel()).sum())

    @staticmethod
    def allEuclideanDistances(a, b, dim=1):
        """Pairwise vector distances (≡ Transforms.allEuclideanDistances):
        dim is the FEATURE axis of the 2-D inputs (nd4j semantics) —
        dim=1 compares rows, dim=0 compares columns."""
        a, b = as_jax(a), as_jax(b)
        if dim == 0:
            a, b = a.T, b.T
        d = (jnp.sum(a * a, 1, keepdims=True)
             + jnp.sum(b * b, 1, keepdims=True).T
             - 2 * a @ b.T)
        return NDArray(jnp.sqrt(jnp.maximum(d, 0.0)))

    @staticmethod
    def dot(a, b):
        return NDArray(as_jax(a) @ as_jax(b))

    @staticmethod
    def cross(a, b):
        return NDArray(jnp.cross(as_jax(a), as_jax(b)))

    # comparisons (≡ Transforms.eps/greaterThanOrEqual/...)
    eq = staticmethod(_b(lambda a, b: (a == b)))
    neq = staticmethod(_b(lambda a, b: (a != b)))
    greaterThan = staticmethod(_b(lambda a, b: (a > b)))
    lessThan = staticmethod(_b(lambda a, b: (a < b)))
    greaterThanOrEqual = staticmethod(_b(lambda a, b: (a >= b)))
    lessThanOrEqual = staticmethod(_b(lambda a, b: (a <= b)))

    @staticmethod
    def isMax(x, axis=None):
        a = as_jax(x)
        if axis is None:
            return NDArray((a == a.max()).astype(a.dtype))
        return NDArray(
            (a == a.max(axis=axis, keepdims=True)).astype(a.dtype))


class BooleanIndexing:
    """≡ org.nd4j.linalg.indexing.BooleanIndexing + Conditions."""

    @staticmethod
    def replaceWhere(arr, value, condition):
        a = as_jax(arr)
        return NDArray(jnp.where(condition(a), as_jax(value), a))

    @staticmethod
    def applyWhere(arr, condition, fn):
        a = as_jax(arr)
        return NDArray(jnp.where(condition(a), fn(a), a))

    @staticmethod
    def countWhere(arr, condition):
        return int(condition(as_jax(arr)).sum())

    @staticmethod
    def anyWhere(arr, condition):
        return bool(condition(as_jax(arr)).any())

    @staticmethod
    def allWhere(arr, condition):
        return bool(condition(as_jax(arr)).all())


class Conditions:
    """≡ indexing.conditions.Conditions factory."""

    @staticmethod
    def greaterThan(v):
        return lambda a: a > v

    @staticmethod
    def lessThan(v):
        return lambda a: a < v

    @staticmethod
    def greaterThanOrEqual(v):
        return lambda a: a >= v

    @staticmethod
    def lessThanOrEqual(v):
        return lambda a: a <= v

    @staticmethod
    def equals(v):
        return lambda a: a == v

    @staticmethod
    def notEquals(v):
        return lambda a: a != v

    @staticmethod
    def isNan():
        return lambda a: jnp.isnan(a)

    @staticmethod
    def isInfinite():
        return lambda a: jnp.isinf(a)

    @staticmethod
    def absGreaterThan(v):
        return lambda a: jnp.abs(a) > v

    @staticmethod
    def absLessThan(v):
        return lambda a: jnp.abs(a) < v
