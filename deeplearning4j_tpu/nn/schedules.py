"""Learning-rate schedules (≡ nd4j-api :: schedule.ISchedule impls:
StepSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
SigmoidSchedule, MapSchedule, CycleSchedule).

Each schedule is a callable step->lr usable directly as an optax schedule.
ScheduleType ITERATION is the native unit; EPOCH schedules take
iterations_per_epoch at lowering time.
"""
from __future__ import annotations

import jax.numpy as jnp


class Schedule:
    def __call__(self, step):
        raise NotImplementedError

    def value(self, step):
        return float(self(step))


class FixedSchedule(Schedule):
    def __init__(self, value):
        self.v = float(value)

    def __call__(self, step):
        return jnp.asarray(self.v, dtype=jnp.float32)


class StepSchedule(Schedule):
    """lr = init * decayRate^floor(iter/step)"""

    def __init__(self, initial_value, decay_rate, step):
        self.init, self.rate, self.step = float(initial_value), float(decay_rate), float(step)

    def __call__(self, step):
        return self.init * self.rate ** jnp.floor(step / self.step)


class ExponentialSchedule(Schedule):
    """lr = init * gamma^iter"""

    def __init__(self, initial_value, gamma):
        self.init, self.gamma = float(initial_value), float(gamma)

    def __call__(self, step):
        return self.init * self.gamma ** jnp.asarray(step, jnp.float32)


class InverseSchedule(Schedule):
    """lr = init / (1 + gamma*iter)^power"""

    def __init__(self, initial_value, gamma, power):
        self.init, self.gamma, self.power = float(initial_value), float(gamma), float(power)

    def __call__(self, step):
        return self.init / (1.0 + self.gamma * step) ** self.power


class PolySchedule(Schedule):
    """lr = init * (1 - iter/maxIter)^power"""

    def __init__(self, initial_value, power, max_iter):
        self.init, self.power, self.max_iter = float(initial_value), float(power), float(max_iter)

    def __call__(self, step):
        frac = jnp.clip(step / self.max_iter, 0.0, 1.0)
        return self.init * (1.0 - frac) ** self.power


class SigmoidSchedule(Schedule):
    """lr = init / (1 + exp(gamma*(iter-stepSize)))"""

    def __init__(self, initial_value, gamma, step_size):
        self.init, self.gamma, self.step_size = float(initial_value), float(gamma), float(step_size)

    def __call__(self, step):
        return self.init / (1.0 + jnp.exp(self.gamma * (step - self.step_size)))


class MapSchedule(Schedule):
    """Piecewise-constant mapping iteration -> lr."""

    def __init__(self, values: dict):
        items = sorted((int(k), float(v)) for k, v in values.items())
        if not items or items[0][0] != 0:
            raise ValueError("MapSchedule requires a value for iteration 0")
        self.boundaries = jnp.asarray([k for k, _ in items], jnp.float32)
        self.values = jnp.asarray([v for _, v in items], jnp.float32)

    def __call__(self, step):
        idx = jnp.sum(self.boundaries <= step) - 1
        return self.values[idx]


class CycleSchedule(Schedule):
    """1cycle: ramp up to max then down, with final annihilation phase."""

    def __init__(self, initial_value, max_value, cycle_length,
                 annealing_length=None, annealing_decay=0.1):
        self.init, self.max = float(initial_value), float(max_value)
        self.cycle = float(cycle_length)
        self.ann_len = float(annealing_length if annealing_length is not None else 0.1 * cycle_length)
        self.ann_decay = float(annealing_decay)

    def __call__(self, step):
        up = self.cycle / 2.0
        pos = jnp.asarray(step, jnp.float32)
        ramp_up = self.init + (self.max - self.init) * (pos / up)
        ramp_dn = self.max - (self.max - self.init) * ((pos - up) / up)
        ann = self.init * (self.ann_decay +
                           (1 - self.ann_decay) * jnp.clip(1 - (pos - self.cycle) / jnp.maximum(self.ann_len, 1.0), 0, 1))
        return jnp.where(pos < up, ramp_up, jnp.where(pos < self.cycle, ramp_dn, ann))


def as_schedule(value):
    if isinstance(value, Schedule):
        return value
    if callable(value):
        return value
    return FixedSchedule(value)
