"""Loss functions (≡ nd4j-api :: lossfunctions.LossFunctions.LossFunction).

Each loss takes (labels, preact, activation, mask) where `preact` is the
layer pre-activation; the loss applies the activation itself so that
softmax+MCXENT / sigmoid+XENT lower to numerically-stable fused
log-softmax / log-sigmoid forms (the reference fuses these the same way in
its loss implementations). `mask` broadcasts over trailing dims; per-example
losses are returned by `*_per_example`, the scalar loss is the masked mean
over examples (ND4J "score by example" averaged).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation


def _apply_mask_mean(per_elem, mask):
    """per_elem: (batch, ...) per-element loss; returns scalar masked mean
    over examples (sum over feature dims, mean over batch/time elements)."""
    # Reduce feature dims -> per-example score
    reduce_axes = tuple(range(1, per_elem.ndim))
    per_example = jnp.sum(per_elem, axis=reduce_axes) if reduce_axes else per_elem
    if mask is None:
        return jnp.mean(per_example)
    m = mask.reshape(per_example.shape).astype(per_elem.dtype)
    return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)


def _flatten_time(labels, preact, mask):
    """Fold time dim of rank-3 (batch, time, feat) into batch so losses are
    uniform; mask (batch, time) flattens alongside."""
    if preact.ndim == 3:
        b, t, f = preact.shape
        preact = preact.reshape(b * t, f)
        labels = labels.reshape(b * t, -1)
        if mask is not None:
            mask = mask.reshape(b * t)
    return labels, preact, mask


def mcxent(labels, preact, activation="softmax", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    if activation in ("softmax", "logsoftmax"):
        logp = jax.nn.log_softmax(preact, axis=-1)
    elif activation == "sigmoid":
        logp = jnp.log(jnp.clip(jax.nn.sigmoid(preact), 1e-10, 1.0))
    else:
        logp = jnp.log(jnp.clip(get_activation(activation)(preact), 1e-10, 1.0))
    return _apply_mask_mean(-(labels * logp), mask)


def xent(labels, preact, activation="sigmoid", mask=None):
    """Binary cross entropy (ND4J LossFunction.XENT)."""
    labels, preact, mask = _flatten_time(labels, preact, mask)
    if activation == "sigmoid":
        # stable: max(x,0) - x*z + log(1+exp(-|x|))
        x, z = preact, labels
        per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        p = jnp.clip(get_activation(activation)(preact), 1e-10, 1 - 1e-10)
        per = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    return _apply_mask_mean(per, mask)


def mse(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    # ND4J MSE averages over the output dimension as well.
    per = (out - labels) ** 2 / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def l2(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean((out - labels) ** 2, mask)


def mae(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(jnp.abs(out - labels) / labels.shape[-1], mask)


def l1(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(jnp.abs(out - labels), mask)


def hinge(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    y = 2.0 * labels - 1.0  # {0,1} -> {-1,1}
    return _apply_mask_mean(jnp.maximum(0.0, 1.0 - y * out), mask)


def squared_hinge(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    y = 2.0 * labels - 1.0
    return _apply_mask_mean(jnp.maximum(0.0, 1.0 - y * out) ** 2, mask)


def kl_divergence(labels, preact, activation="softmax", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = jnp.clip(get_activation(activation)(preact), 1e-10, 1.0)
    lab = jnp.clip(labels, 1e-10, 1.0)
    return _apply_mask_mean(labels * (jnp.log(lab) - jnp.log(out)), mask)


def poisson(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(out - labels * jnp.log(jnp.clip(out, 1e-10, None)), mask)


def cosine_proximity(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1) + 1e-10
    return _apply_mask_mean((-num / den)[..., None], mask)


def mape(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    per = 100.0 * jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), 1e-10, None)) / labels.shape[-1]
    return _apply_mask_mean(per, mask)


LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": mcxent,  # ND4J aliases NLL to MCXENT semantics
    "xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "mean_absolute_percentage_error": mape,
    "mape": mape,
}


def get_loss(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(LOSSES)}")
    return LOSSES[key]


class LossFunction:
    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    XENT = "xent"
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mape"


# -- configurable loss objects (≡ nd4j lossfunctions.impl.LossMCXENT /
# LossBinaryXENT / LossMSE with weights + label smoothing) ---------------
class _WeightedLoss:
    """Callable loss config: per-output weights and label smoothing.
    Instances pass straight through get_loss (callables are accepted) and
    survive config JSON via __dict__ round-trip."""

    def __init__(self, weights=None, labelSmoothing=0.0):
        self.weights = None if weights is None else [float(w)
                                                     for w in weights]
        self.labelSmoothing = float(labelSmoothing)

    def _smooth(self, labels):
        s = self.labelSmoothing
        if not s:
            return labels
        k = labels.shape[-1]
        return labels * (1.0 - s) + s / k

    def _w(self, dtype):
        return None if self.weights is None else jnp.asarray(self.weights,
                                                             dtype)


class LossMCXENT(_WeightedLoss):
    """Weights scale the labels — valid here because CE is linear in the
    label vector, so label-scaling == per-element loss scaling."""

    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        w = self._w(labels.dtype)
        if w is not None:
            labels = labels * w
        return mcxent(labels, preact, activation=activation or "softmax",
                      mask=mask)


class LossNegativeLogLikelihood(LossMCXENT):
    pass


class LossBinaryXENT(_WeightedLoss):
    def _smooth(self, labels):
        s = self.labelSmoothing
        # binary smoothing: y*(1-s) + 0.5*s (reference LossBinaryXENT)
        return labels if not s else labels * (1.0 - s) + 0.5 * s

    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        w = self._w(preact.dtype)
        if w is None:
            return xent(labels, preact,
                        activation=activation or "sigmoid", mask=mask)
        # weights must scale the PER-ELEMENT loss: BCE is not linear in
        # the labels, label-scaling would make the loss unbounded below
        labels2, preact2, mask2 = _flatten_time(labels, preact, mask)
        if (activation or "sigmoid") == "sigmoid":
            x, z = preact2, labels2
            per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            p = jnp.clip(get_activation(activation)(preact2), 1e-7, 1 - 1e-7)
            per = -(labels2 * jnp.log(p) + (1 - labels2) * jnp.log(1 - p))
        return _apply_mask_mean(w * per, mask2)


class LossMSE(_WeightedLoss):
    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        if self.weights is None:
            return mse(labels, preact, activation=activation or "identity",
                       mask=mask)
        w = self._w(preact.dtype)
        out = get_activation(activation or "identity")(preact)
        labels2, out2, mask2 = _flatten_time(labels, out, mask)
        # same /nOut normalization as unweighted mse(): identity weights
        # must be a no-op
        per = w * (labels2 - out2) ** 2 / labels2.shape[-1]
        return _apply_mask_mean(per, mask2)
