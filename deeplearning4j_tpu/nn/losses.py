"""Loss functions (≡ nd4j-api :: lossfunctions.LossFunctions.LossFunction).

Each loss takes (labels, preact, activation, mask) where `preact` is the
layer pre-activation; the loss applies the activation itself so that
softmax+MCXENT / sigmoid+XENT lower to numerically-stable fused
log-softmax / log-sigmoid forms (the reference fuses these the same way in
its loss implementations). `mask` broadcasts over trailing dims; per-example
losses are returned by `*_per_example`, the scalar loss is the masked mean
over examples (ND4J "score by example" averaged).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation


def _apply_mask_mean(per_elem, mask):
    """per_elem: (batch, ...) per-element loss; returns scalar masked mean
    over examples (sum over feature dims, mean over batch/time elements)."""
    # Reduce feature dims -> per-example score
    reduce_axes = tuple(range(1, per_elem.ndim))
    per_example = jnp.sum(per_elem, axis=reduce_axes) if reduce_axes else per_elem
    if mask is None:
        return jnp.mean(per_example)
    m = mask.reshape(per_example.shape).astype(per_elem.dtype)
    return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)


def _flatten_time(labels, preact, mask):
    """Fold time dim of rank-3 (batch, time, feat) into batch so losses are
    uniform; mask (batch, time) flattens alongside."""
    if preact.ndim == 3:
        b, t, f = preact.shape
        preact = preact.reshape(b * t, f)
        labels = labels.reshape(b * t, -1)
        if mask is not None:
            mask = mask.reshape(b * t)
    return labels, preact, mask


def mcxent(labels, preact, activation="softmax", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    if activation in ("softmax", "logsoftmax"):
        logp = jax.nn.log_softmax(preact, axis=-1)
    elif activation == "sigmoid":
        logp = jnp.log(jnp.clip(jax.nn.sigmoid(preact), 1e-10, 1.0))
    else:
        logp = jnp.log(jnp.clip(get_activation(activation)(preact), 1e-10, 1.0))
    return _apply_mask_mean(-(labels * logp), mask)


def xent(labels, preact, activation="sigmoid", mask=None):
    """Binary cross entropy (ND4J LossFunction.XENT)."""
    labels, preact, mask = _flatten_time(labels, preact, mask)
    if activation == "sigmoid":
        # stable: max(x,0) - x*z + log(1+exp(-|x|))
        x, z = preact, labels
        per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        p = jnp.clip(get_activation(activation)(preact), 1e-10, 1 - 1e-10)
        per = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    return _apply_mask_mean(per, mask)


def mse(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    # ND4J MSE averages over the output dimension as well.
    per = (out - labels) ** 2 / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def l2(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean((out - labels) ** 2, mask)


def mae(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(jnp.abs(out - labels) / labels.shape[-1], mask)


def l1(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(jnp.abs(out - labels), mask)


def hinge(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    y = 2.0 * labels - 1.0  # {0,1} -> {-1,1}
    return _apply_mask_mean(jnp.maximum(0.0, 1.0 - y * out), mask)


def squared_hinge(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    y = 2.0 * labels - 1.0
    return _apply_mask_mean(jnp.maximum(0.0, 1.0 - y * out) ** 2, mask)


def kl_divergence(labels, preact, activation="softmax", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = jnp.clip(get_activation(activation)(preact), 1e-10, 1.0)
    lab = jnp.clip(labels, 1e-10, 1.0)
    return _apply_mask_mean(labels * (jnp.log(lab) - jnp.log(out)), mask)


def poisson(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(out - labels * jnp.log(jnp.clip(out, 1e-10, None)), mask)


def cosine_proximity(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    num = jnp.sum(labels * out, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1) + 1e-10
    return _apply_mask_mean((-num / den)[..., None], mask)


def mape(labels, preact, activation="identity", mask=None):
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    per = 100.0 * jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), 1e-10, None)) / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def wasserstein(labels, preact, activation="identity", mask=None):
    """≡ lossfunctions.impl.LossWasserstein — critic loss y·f(x) (labels
    are ±1 for real/generated in the WGAN recipe)."""
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    return _apply_mask_mean(labels * out / labels.shape[-1], mask)


def multilabel(labels, preact, activation="identity", mask=None):
    """≡ lossfunctions.impl.LossMultiLabel — BP-MLL pairwise ranking
    loss (Zhang & Zhou): per example, mean over (positive, negative)
    label pairs of exp(-(o_p - o_n)). Vectorized over the P×N pair grid
    — no per-pair host loop; examples lacking a positive or a negative
    contribute zero, as in the reference."""
    labels, preact, mask = _flatten_time(labels, preact, mask)
    out = get_activation(activation)(preact)
    pos = (labels > 0.5).astype(out.dtype)                      # (B, L)
    neg = 1.0 - pos
    # exp(o_n - o_p) summed over the pair grid = (Σ_n e^{o_n} w_n)(Σ_p
    # e^{-o_p} w_p) — O(L) instead of O(L²) via the product factorization
    e_neg = jnp.sum(jnp.exp(out) * neg, axis=-1)
    e_pos = jnp.sum(jnp.exp(-out) * pos, axis=-1)
    n_pairs = pos.sum(-1) * neg.sum(-1)
    per_ex = jnp.where(n_pairs > 0, e_neg * e_pos
                       / jnp.maximum(n_pairs, 1.0), 0.0)
    return _apply_mask_mean(per_ex[..., None], mask)


class LossFMeasure:
    """≡ lossfunctions.impl.LossFMeasure — differentiable 1 − F_β over
    the WHOLE minibatch (soft TP/FP/FN from probabilities). Binary only:
    one sigmoid column, or two softmax columns (positive = column 1)."""

    def __init__(self, beta=1.0):
        if beta <= 0:
            raise ValueError(f"LossFMeasure: beta must be > 0, got {beta}")
        self.beta = float(beta)

    def __call__(self, labels, preact, activation=None, mask=None):
        labels, preact, mask = _flatten_time(labels, preact, mask)
        n_col = preact.shape[-1]
        if n_col == 1:
            p = get_activation(activation or "sigmoid")(preact)[..., 0]
            y = labels[..., 0]
        elif n_col == 2:
            p = get_activation(activation or "softmax")(preact)[..., 1]
            y = labels[..., 1]
        else:
            raise ValueError(
                f"LossFMeasure supports 1 or 2 output columns, got {n_col}")
        if mask is not None:
            m = mask.reshape(y.shape).astype(p.dtype)
            p, y = p * m, y * m
        tp = jnp.sum(y * p)
        fp = jnp.sum((1.0 - y) * p)
        fn = jnp.sum(y * (1.0 - p))
        b2 = self.beta ** 2
        f = (1.0 + b2) * tp / jnp.maximum((1.0 + b2) * tp + b2 * fn + fp,
                                          1e-8)
        return 1.0 - f


class LossMixtureDensity:
    """≡ lossfunctions.impl.LossMixtureDensity — mixture-density-network
    NLL (Bishop 1994). Network output layout per example:
    [mixture logits (K) | log σ (K) | means (K·labelWidth)], i.e.
    nOut = K·(labelWidth + 2); isotropic σ per component. The whole
    K-component log-likelihood lowers to one logsumexp — no per-component
    branching."""

    def __init__(self, gaussians, labelWidth):
        self.gaussians = int(gaussians)
        self.labelWidth = int(labelWidth)

    def nOut(self):
        return self.gaussians * (self.labelWidth + 2)

    def _split(self, preact):
        k, d = self.gaussians, self.labelWidth
        if preact.shape[-1] != k * (d + 2):
            raise ValueError(
                f"LossMixtureDensity: expected nOut = K(d+2) = {k * (d + 2)}"
                f" (K={k} gaussians, labelWidth={d}), got "
                f"{preact.shape[-1]}")
        log_alpha = jax.nn.log_softmax(preact[..., :k], axis=-1)
        log_sigma = jnp.clip(preact[..., k:2 * k], -10.0, 10.0)
        mu = preact[..., 2 * k:].reshape(*preact.shape[:-1], k, d)
        return log_alpha, log_sigma, mu

    def log_prob(self, labels, preact):
        """Per-example log p(y) under the mixture; (B,)."""
        d = self.labelWidth
        log_alpha, log_sigma, mu = self._split(preact)
        sq = ((labels[..., None, :] - mu) ** 2).sum(-1)       # (B, K)
        log_n = (-0.5 * sq / jnp.exp(2.0 * log_sigma)
                 - d * log_sigma - 0.5 * d * jnp.log(2 * jnp.pi))
        return jax.scipy.special.logsumexp(log_alpha + log_n, axis=-1)

    def __call__(self, labels, preact, activation=None, mask=None):
        # activation must stay identity: the loss owns its own
        # softmax/exp parameterization of the mixture
        labels, preact, mask = _flatten_time(labels, preact, mask)
        return _apply_mask_mean(-self.log_prob(labels, preact)[..., None],
                                mask)

    def sample(self, preact, rng):
        """Draw one y per example from the predicted mixture."""
        log_alpha, log_sigma, mu = self._split(jnp.asarray(preact))
        k_comp, k_eps = jax.random.split(rng)
        comp = jax.random.categorical(k_comp, log_alpha, axis=-1)  # (B,)
        sel = jnp.take_along_axis(
            mu, comp[..., None, None].astype(jnp.int32), axis=-2)[..., 0, :]
        sig = jnp.take_along_axis(jnp.exp(log_sigma),
                                  comp[..., None].astype(jnp.int32),
                                  axis=-1)
        eps = jax.random.normal(k_eps, sel.shape, sel.dtype)
        return sel + sig * eps


class LossWasserstein:
    """Object form of `wasserstein` (name parity with
    lossfunctions.impl.LossWasserstein)."""

    def __call__(self, labels, preact, activation=None, mask=None):
        return wasserstein(labels, preact, activation or "identity", mask)


class LossMultiLabel:
    """Object form of `multilabel` (name parity with
    lossfunctions.impl.LossMultiLabel)."""

    def __call__(self, labels, preact, activation=None, mask=None):
        return multilabel(labels, preact, activation or "identity", mask)


LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": mcxent,  # ND4J aliases NLL to MCXENT semantics
    "xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "mean_absolute_percentage_error": mape,
    "mape": mape,
    "wasserstein": wasserstein,
    "multilabel": multilabel,
    "fmeasure": LossFMeasure(),       # β=1; use LossFMeasure(beta=…) to tune
}


def get_loss(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(LOSSES)}")
    return LOSSES[key]


class LossFunction:
    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    XENT = "xent"
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MAE = "mae"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mape"
    WASSERSTEIN = "wasserstein"
    MULTILABEL = "multilabel"
    FMEASURE = "fmeasure"


# -- configurable loss objects (≡ nd4j lossfunctions.impl.LossMCXENT /
# LossBinaryXENT / LossMSE with weights + label smoothing) ---------------
class _WeightedLoss:
    """Callable loss config: per-output weights and label smoothing.
    Instances pass straight through get_loss (callables are accepted) and
    survive config JSON via __dict__ round-trip."""

    def __init__(self, weights=None, labelSmoothing=0.0):
        self.weights = None if weights is None else [float(w)
                                                     for w in weights]
        self.labelSmoothing = float(labelSmoothing)

    def _smooth(self, labels):
        s = self.labelSmoothing
        if not s:
            return labels
        k = labels.shape[-1]
        return labels * (1.0 - s) + s / k

    def _w(self, dtype):
        return None if self.weights is None else jnp.asarray(self.weights,
                                                             dtype)


class LossMCXENT(_WeightedLoss):
    """Weights scale the labels — valid here because CE is linear in the
    label vector, so label-scaling == per-element loss scaling."""

    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        w = self._w(labels.dtype)
        if w is not None:
            labels = labels * w
        return mcxent(labels, preact, activation=activation or "softmax",
                      mask=mask)


class LossNegativeLogLikelihood(LossMCXENT):
    pass


class LossBinaryXENT(_WeightedLoss):
    def _smooth(self, labels):
        s = self.labelSmoothing
        # binary smoothing: y*(1-s) + 0.5*s (reference LossBinaryXENT)
        return labels if not s else labels * (1.0 - s) + 0.5 * s

    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        w = self._w(preact.dtype)
        if w is None:
            return xent(labels, preact,
                        activation=activation or "sigmoid", mask=mask)
        # weights must scale the PER-ELEMENT loss: BCE is not linear in
        # the labels, label-scaling would make the loss unbounded below
        labels2, preact2, mask2 = _flatten_time(labels, preact, mask)
        if (activation or "sigmoid") == "sigmoid":
            x, z = preact2, labels2
            per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            p = jnp.clip(get_activation(activation)(preact2), 1e-7, 1 - 1e-7)
            per = -(labels2 * jnp.log(p) + (1 - labels2) * jnp.log(1 - p))
        return _apply_mask_mean(w * per, mask2)


class LossMSE(_WeightedLoss):
    def __call__(self, labels, preact, activation=None, mask=None):
        labels = self._smooth(labels)
        if self.weights is None:
            return mse(labels, preact, activation=activation or "identity",
                       mask=mask)
        w = self._w(preact.dtype)
        out = get_activation(activation or "identity")(preact)
        labels2, out2, mask2 = _flatten_time(labels, out, mask)
        # same /nOut normalization as unweighted mse(): identity weights
        # must be a no-op
        per = w * (labels2 - out2) ** 2 / labels2.shape[-1]
        return _apply_mask_mean(per, mask2)
