"""Parameter constraints (≡ org.deeplearning4j.nn.conf.constraint ::
MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
UnitNormConstraint).

The reference applies constraints in-place after each parameter update
(BaseConstraint.applyConstraint called from the updater step).  Here they
are pure functions folded into the SAME jitted train step, immediately
after ``optax.apply_updates`` — no extra device round-trip.

Norms are taken per output unit (over all axes except the last), matching
the reference's default dimension handling for dense/conv weights.
"""
from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12

#: parameter-dict keys treated as "weights" (the reference's default
#: constraint target — biases are excluded unless constrainBias is used)
WEIGHT_KEYS = ("W", "U", "dW", "pW")


class BaseConstraint:
    """Applies to weight params by default (≡ BaseConstraint.paramNames)."""

    applies_to = WEIGHT_KEYS

    def apply(self, w):
        raise NotImplementedError

    def apply_to_params(self, layer_params):
        return {k: (self.apply(v) if k in self.applies_to else v)
                for k, v in layer_params.items()}

    @staticmethod
    def _norm(w):
        axes = tuple(range(w.ndim - 1)) or (0,)
        return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


class MaxNormConstraint(BaseConstraint):
    """Rescale any output unit whose L2 norm exceeds maxNorm."""

    def __init__(self, maxNorm):
        self.maxNorm = float(maxNorm)

    def apply(self, w):
        norm = self._norm(w)
        return w * jnp.minimum(1.0, self.maxNorm / (norm + _EPS)
                               ).astype(w.dtype)


class MinMaxNormConstraint(BaseConstraint):
    """Project each output unit's norm into [min, max]; `rate` interpolates
    between no-op (0) and full projection (1) like the reference."""

    def __init__(self, minNorm, maxNorm, rate=1.0):
        self.minNorm = float(minNorm)
        self.maxNorm = float(maxNorm)
        self.rate = float(rate)

    def apply(self, w):
        norm = self._norm(w)
        target = jnp.clip(norm, self.minNorm, self.maxNorm)
        scale = self.rate * (target / (norm + _EPS)) + (1.0 - self.rate)
        return w * scale.astype(w.dtype)


class UnitNormConstraint(BaseConstraint):
    """Force each output unit onto the unit sphere."""

    def apply(self, w):
        return w / (self._norm(w) + _EPS).astype(w.dtype)


class NonNegativeConstraint(BaseConstraint):
    """Clamp negative entries to zero (elementwise)."""

    def apply(self, w):
        return jnp.maximum(w, 0)


def apply_layer_constraints(layers, params):
    """Fold each layer's constraints over its param dict.  `params` is the
    network-level {layer_key: {param_name: array}} pytree; layer keys are
    stringified indices (MultiLayerNetwork) or names (ComputationGraph)."""
    out = dict(params)
    for key, layer in layers:
        cs = getattr(layer, "constraints", None)
        if not cs or key not in out:
            continue
        lp = out[key]
        for c in cs:
            lp = c.apply_to_params(lp)
        out[key] = lp
    return out
