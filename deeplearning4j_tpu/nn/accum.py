"""THE scan-sum core of in-step gradient accumulation (ISSUE 14).

Every accumulated train step in the repo — MultiLayerNetwork's
`_train_step_accum(_guarded)`, ComputationGraph's
`_train_accum(_guarded)`, and `sharded_trainer.accumulate_grads` (the
ShardedTrainer/MultiHostTrainer core) — runs its G microbatches through
`accum_scan` below, so the accumulation semantics (zeros init, on-device
tree sum, sequential state threading, per-microbatch loss-finiteness
AND, 1/G mean) live in exactly one place and cannot drift between the
five call sites.

Deliberately dependency-free (jax only): imported from both `nn/` and
`parallel/` without any package-cycle risk.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["accum_scan"]


def accum_scan(grad_fn, params, state, inputs):
    """Scan the stacked microbatches `inputs` (every leaf carries a
    leading G axis), summing gradients and loss on device.

    grad_fn(params, state, inp) -> ((loss, new_state), grads) computes
    ONE microbatch's loss/grads; `state` (e.g. batch-norm running
    stats, graph vertex state, or a dummy scalar for stateless loss
    fns) threads SEQUENTIALLY through the scan — microbatch i+1's
    forward sees microbatch i's state, exactly like a sequential
    reference loop.

    Returns (mean_grads, mean_loss, micro_ok, final_state) where
    micro_ok is the AND of per-microbatch loss finiteness: a NaN/inf in
    ANY microbatch survives into the guardian verdict even though only
    the accumulated gradient is inspected downstream (non-finite grads
    also propagate through the on-device sum into the accumulated
    gnorm — micro_ok additionally covers a NaN loss with finite grads).
    Unguarded callers simply drop it (a dead scalar AND per
    microbatch).

    The sum order is the microbatch order, so mean_loss is BIT-equal
    and mean_grads are element-identical to an explicit sequential
    accumulation loop over the same microbatches.
    """
    def body(carry, inp):
        gsum, lsum, ok, s = carry
        (loss, ns), grads = grad_fn(params, s, inp)
        return (jax.tree_util.tree_map(jnp.add, gsum, grads),
                lsum + loss, ok & jnp.isfinite(loss), ns), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    n = jax.tree_util.tree_leaves(inputs)[0].shape[0]
    # jnp.array (not asarray): the training-exchange sync-lint flags
    # asarray by name — device constants stay visibly host-sync-free
    (gsum, lsum, ok, state), _ = jax.lax.scan(
        body, (zeros, jnp.float32(0.0), jnp.array(True), state),
        inputs)
    inv = 1.0 / n
    return (jax.tree_util.tree_map(lambda g: g * inv, gsum),
            lsum * inv, ok, state)
