"""Activation catalog (≡ nd4j-api :: activations.Activation enum + impls).

Reference surface: IActivation implementations under
org.nd4j.linalg.activations.impl (reference mount empty; reconstructed).
All are jnp-pure so XLA fuses them into the surrounding matmul/conv.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _rationaltanh(x):
    # ND4J's ActivationRationalTanh: 1.7159 * softsign-style rational approx.
    a = jnp.abs(x)
    return jnp.sign(x) * 1.7159 * (1 - 1 / (1 + a + a * a + 1.41645 * a ** 4))


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _cube(x):
    return x ** 3


def _mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def _thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS = {
    "identity": lambda x: x,
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "mish": _mish,
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": _hardsigmoid,
    "tanh": jnp.tanh,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "hardtanh": _hardtanh,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "cube": _cube,
    "thresholdedrelu": _thresholdedrelu,
}


def get_activation(name):
    """Resolve an activation by ND4J enum name (case-insensitive) or
    callable. Parameterized spellings stay JSON-serializable strings:
    'leakyrelu:<alpha>', 'thresholdedrelu:<theta>', 'relucap:<max>'
    (relu clipped to [0, max])."""
    if callable(name):
        return name
    key = str(name).lower()
    if ":" in key:
        base, _, arg = key.partition(":")
        try:
            v = float(arg)
        except ValueError:
            raise ValueError(f"Bad activation parameter in '{name}'")
        if base == "leakyrelu":
            return lambda x: jax.nn.leaky_relu(x, v)
        if base == "thresholdedrelu":
            return lambda x: _thresholdedrelu(x, v)
        if base == "relucap":
            return lambda x: jnp.clip(x, 0.0, v)
        raise ValueError(
            f"Activation '{base}' does not take a ':{arg}' parameter")
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]


class Activation:
    """Enum-style accessors: Activation.RELU etc. (≡ nd4j Activation enum)."""
    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SWISH = "swish"
    MISH = "mish"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    TANH = "tanh"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    HARDTANH = "hardtanh"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    CUBE = "cube"
    THRESHOLDEDRELU = "thresholdedrelu"
