"""Input preprocessors (≡ deeplearning4j-nn :: conf.preprocessor.*).

Pure reshape/transpose adapters between layer families. NHWC throughout.
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalType, FeedForwardType, InputType, RecurrentType)


class InputPreProcessor:
    def preProcess(self, x):
        raise NotImplementedError

    def getOutputType(self, input_type):
        raise NotImplementedError


class FeedForwardToCnnPreProcessor(InputPreProcessor):
    def __init__(self, height, width, channels):
        self.height, self.width, self.channels = int(height), int(width), int(channels)

    def preProcess(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def getOutputType(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


class CnnToFeedForwardPreProcessor(InputPreProcessor):
    def __init__(self, height=None, width=None, channels=None):
        self.height, self.width, self.channels = height, width, channels

    def preProcess(self, x):
        return x.reshape(x.shape[0], -1)

    def getOutputType(self, input_type):
        if isinstance(input_type, ConvolutionalType):
            return InputType.feedForward(input_type.arrayElementsPerExample())
        return input_type


class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """(B*T, F) -> (B, T, F) is impossible without T; here the DL4J semantic
    is: treat FF activations as single-timestep sequences."""

    def preProcess(self, x):
        return x[:, None, :]

    def getOutputType(self, input_type):
        return InputType.recurrent(input_type.size, 1)


class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """(B, T, F) -> (B*T, F) (the reference folds time into batch)."""

    def preProcess(self, x):
        b, t, f = x.shape
        return x.reshape(b * t, f)

    def getOutputType(self, input_type):
        return InputType.feedForward(input_type.size)


class RnnToCnnPreProcessor(InputPreProcessor):
    def __init__(self, height, width, channels):
        self.height, self.width, self.channels = int(height), int(width), int(channels)

    def preProcess(self, x):
        b, t, f = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)

    def getOutputType(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


class CnnToRnnPreProcessor(InputPreProcessor):
    """(B, H, W, C) -> (B, 1, H*W*C)."""

    def preProcess(self, x):
        return x.reshape(x.shape[0], 1, -1)

    def getOutputType(self, input_type):
        return InputType.recurrent(input_type.arrayElementsPerExample(), 1)


class Cnn3DToFeedForwardPreProcessor(InputPreProcessor):
    """≡ preprocessor.Cnn3DToFeedForwardPreProcessor — flatten NDHWC."""

    def __init__(self, depth=None, height=None, width=None, channels=None):
        self.depth, self.height = depth, height
        self.width, self.channels = width, channels

    def preProcess(self, x):
        return x.reshape(x.shape[0], -1)

    def getOutputType(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import Convolutional3DType
        if isinstance(input_type, Convolutional3DType):
            return InputType.feedForward(input_type.arrayElementsPerExample())
        return input_type
