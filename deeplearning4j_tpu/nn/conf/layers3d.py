"""3D CNN layer family (≡ deeplearning4j-nn :: conf.layers.Convolution3D /
Subsampling3DLayer / Upsampling3D / Cropping3D / ZeroPadding3DLayer /
Cnn3DLossLayer).

TPU-native volumetric convs: NDHWC activations / DHWIO kernels through
`lax.conv_general_dilated` (the reference is NCDHW + per-kernel CUDA
dispatch); XLA lowers the 3-D conv onto the MXU by collapsing spatial dims
into the contraction. Pooling is one fused `lax.reduce_window` over
(D, H, W)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import Convolutional3DType, InputType
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer, Layer
from deeplearning4j_tpu.nn.weights_init import init_weight


def _triple(v):
    if isinstance(v, (tuple, list)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _out_dim(size, k, s, p, dilation, same):
    if same:
        return -(-size // s)
    return (size + 2 * p - ((k - 1) * dilation + 1)) // s + 1


class Convolution3D(Layer):
    """≡ conf.layers.Convolution3D — NDHWC in, DHWIO kernel."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(3, 3, 3),
                 stride=(1, 1, 1), padding=(0, 0, 0), dilation=(1, 1, 1),
                 convolutionMode="truncate", hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = _triple(kernelSize), _triple(stride)
        self.padding, self.dilation = _triple(padding), _triple(dilation)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias

    def _same(self):
        return str(self.convolutionMode).lower() == "same"

    def _check_input(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs convolutional3D "
                f"(D,H,W,C) input, got {input_type}")

    def output_type(self, input_type):
        self._check_input(input_type)
        if self.nOut is None:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nOut is required")
        dims = [_out_dim(s, k, st, p, d, self._same()) for s, k, st, p, d in
                zip(input_type.shape()[:3], self.kernelSize, self.stride,
                    self.padding, self.dilation)]
        return InputType.convolutional3D(*dims, self.nOut)

    def initialize(self, key, input_type):
        self._check_input(input_type)
        if self.nIn is None:
            self.nIn = input_type.channels
        kd, kh, kw = self.kernelSize
        w = init_weight(key, (kd, kh, kw, int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit),
                                   jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        if self._same():
            pad = "SAME"
        else:
            pad = [(p, p) for p in self.padding]
        y = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype),
            window_strides=self.stride,
            padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return get_activation(self.activation)(self.pre_activation(params, x)), state


class Subsampling3DLayer(Layer):
    """≡ conf.layers.Subsampling3DLayer — max/avg pooling over (D, H, W)."""

    def __init__(self, poolingType="max", kernelSize=(2, 2, 2),
                 stride=(2, 2, 2), padding=(0, 0, 0),
                 convolutionMode="truncate", **kw):
        super().__init__(**kw)
        self.poolingType = str(poolingType).lower()
        self.kernelSize, self.stride = _triple(kernelSize), _triple(stride)
        self.padding = _triple(padding)
        self.convolutionMode = convolutionMode

    def _same(self):
        return str(self.convolutionMode).lower() == "same"

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs convolutional3D "
                f"input, got {input_type}")
        dims = [_out_dim(s, k, st, p, 1, self._same()) for s, k, st, p in
                zip(input_type.shape()[:3], self.kernelSize, self.stride,
                    self.padding)]
        return InputType.convolutional3D(*dims, input_type.channels)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        kd, kh, kw = self.kernelSize
        sd, sh, sw = self.stride
        if self._same():
            pad = "SAME"
        else:
            pad = [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)]
        dims, strides = (1, kd, kh, kw, 1), (1, sd, sh, sw, 1)
        if self.poolingType == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif self.poolingType in ("avg", "mean"):
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims,
                                    strides, pad)
            y = s / cnt
        else:
            raise ValueError(f"Unknown poolingType {self.poolingType}")
        return y, state


class Upsampling3D(Layer):
    """≡ conf.layers.Upsampling3D — nearest-neighbour repeat over D/H/W."""

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.size = _triple(size)

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs convolutional3D "
                f"input, got {input_type}")
        d, h, w, c = input_type.shape()
        return InputType.convolutional3D(d * self.size[0], h * self.size[1],
                                         w * self.size[2], c)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        for axis, rep in zip((1, 2, 3), self.size):
            x = jnp.repeat(x, rep, axis=axis)
        return x, state


class Cropping3D(Layer):
    """≡ conf.layers.Cropping3D — crop (front, back) per spatial dim."""

    def __init__(self, cropping=(0, 0, 0, 0, 0, 0), **kw):
        super().__init__(**kw)
        c = cropping
        if isinstance(c, int):
            c = (c,) * 6
        elif len(c) == 3 and all(isinstance(v, (tuple, list)) for v in c):
            c = tuple(int(x) for pair in c for x in pair)
        elif len(c) == 3:
            c = tuple(int(v) for v in c for _ in (0, 1))
        self.cropping = tuple(int(v) for v in c)  # (d0,d1,h0,h1,w0,w1)

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs convolutional3D "
                f"input, got {input_type}")
        d0, d1, h0, h1, w0, w1 = self.cropping
        d, h, w, c = input_type.shape()
        return InputType.convolutional3D(d - d0 - d1, h - h0 - h1,
                                         w - w0 - w1, c)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        d0, d1, h0, h1, w0, w1 = self.cropping
        D, H, W = x.shape[1], x.shape[2], x.shape[3]
        return x[:, d0:D - d1, h0:H - h1, w0:W - w1, :], state


class ZeroPadding3DLayer(Layer):
    """≡ conf.layers.ZeroPadding3DLayer."""

    def __init__(self, padding=(1, 1, 1, 1, 1, 1), **kw):
        super().__init__(**kw)
        p = padding
        if isinstance(p, int):
            p = (p,) * 6
        elif len(p) == 3 and all(isinstance(v, (tuple, list)) for v in p):
            p = tuple(int(x) for pair in p for x in pair)
        elif len(p) == 3:
            p = tuple(int(v) for v in p for _ in (0, 1))
        self.padding = tuple(int(v) for v in p)

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs convolutional3D "
                f"input, got {input_type}")
        d0, d1, h0, h1, w0, w1 = self.padding
        d, h, w, c = input_type.shape()
        return InputType.convolutional3D(d + d0 + d1, h + h0 + h1,
                                         w + w0 + w1, c)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        d0, d1, h0, h1, w0, w1 = self.padding
        widths = [(0, 0), (d0, d1), (h0, h1), (w0, w1), (0, 0)]
        return jnp.pad(x, widths), state


class Cnn3DLossLayer(BaseOutputLayer):
    """≡ conf.layers.Cnn3DLossLayer — per-voxel loss over NDHWC output,
    no parameters (the head conv supplies the channel logits)."""

    def pre_activation(self, params, x):
        return x

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"Cnn3DLossLayer '{self.name}' needs convolutional3D "
                f"(D,H,W,C) input, got {input_type} (use CnnLossLayer "
                "for 2-D feature maps)")
        return input_type


class Deconvolution3D(Layer):
    """≡ conf.layers.Deconvolution3D — transposed volumetric conv
    (learned 3-D upsampling), NDHWC/DHWIO via lax.conv_transpose (the 2-D
    twin is layers.Deconvolution2D)."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(2, 2, 2),
                 stride=(2, 2, 2), padding=(0, 0, 0),
                 convolutionMode="truncate", hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = _triple(kernelSize), _triple(stride)
        self.padding = _triple(padding)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias

    def _same(self):
        return str(self.convolutionMode).lower() == "same"

    def _padding_arg(self):
        if self._same():
            return "SAME"
        pd, ph, pw = self.padding
        return ([(pd, pd), (ph, ph), (pw, pw)]
                if (pd or ph or pw) else "VALID")

    def output_type(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"Deconvolution3D '{self.name}' needs convolutional3D "
                f"(D,H,W,C) input, got {input_type}")
        if self.nOut is None:
            raise ValueError(
                f"Deconvolution3D '{self.name}': nOut is required")
        kd, kh, kw = self.kernelSize
        sd, sh, sw = self.stride
        if self._same():
            od = input_type.depth * sd
            oh = input_type.height * sh
            ow = input_type.width * sw
        else:
            pd, ph, pw = self.padding
            od = sd * (input_type.depth - 1) + kd - 2 * pd
            oh = sh * (input_type.height - 1) + kh - 2 * ph
            ow = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional3D(od, oh, ow, self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        kd, kh, kw = self.kernelSize
        w = init_weight(key, (kd, kh, kw, int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit),
                                   jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        y = lax.conv_transpose(
            x, params["W"].astype(x.dtype),
            strides=self.stride,
            padding=self._padding_arg(),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return get_activation(self.activation)(
            self.pre_activation(params, x)), state
