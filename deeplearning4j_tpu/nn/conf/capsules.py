"""Capsule-network layers (≡ deeplearning4j-nn :: conf.layers.CapsuleLayer /
PrimaryCapsules / CapsuleStrengthLayer, Sabour et al. 2017) and the
one-class OCNNOutputLayer (≡ conf.ocnn.OCNNOutputLayer, Chalapathy et al.).

TPU-first shapes: capsule sets are (B, N, D) arrays (N capsules of
dimension D), reusing the package's recurrent InputType (size=D, T=N);
dynamic routing unrolls its fixed `routings` iterations at trace time —
three einsums per iteration, all MXU work, no host loop."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType, InputType,
                                               RecurrentType)
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer, Layer
from deeplearning4j_tpu.nn.weights_init import init_weight


def _squash(s, axis=-1, eps=1e-8):
    """v = (|s|²/(1+|s|²)) · s/|s| — capsule nonlinearity."""
    n2 = jnp.sum(jnp.square(s), axis=axis, keepdims=True)
    return (n2 / (1.0 + n2)) * s * jax.lax.rsqrt(n2 + eps)


class PrimaryCapsules(Layer):
    """≡ conf.layers.PrimaryCapsules — conv → capsule groups → squash.
    (B, H, W, C) → (B, N, capsuleDimensions) with
    N = H'·W'·channels (conv output positions × capsule channels)."""

    def __init__(self, capsuleDimensions=8, channels=8, kernelSize=(9, 9),
                 stride=(2, 2), hasBias=True, **kw):
        super().__init__(**kw)
        self.capsuleDimensions = int(capsuleDimensions)
        self.channels = int(channels)
        self.kernelSize = (kernelSize if isinstance(kernelSize, (tuple, list))
                           else (kernelSize, kernelSize))
        self.stride = (stride if isinstance(stride, (tuple, list))
                       else (stride, stride))
        self.hasBias = hasBias

    def _out_hw(self, input_type):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        oh = (input_type.height - kh) // sh + 1
        ow = (input_type.width - kw) // sw + 1
        return oh, ow

    def output_type(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"PrimaryCapsules '{self.name}' needs convolutional input, "
                f"got {input_type}")
        oh, ow = self._out_hw(input_type)
        n = oh * ow * self.channels
        return InputType.recurrent(self.capsuleDimensions, n)

    def initialize(self, key, input_type):
        kh, kw = self.kernelSize
        c_out = self.channels * self.capsuleDimensions
        w = init_weight(key, (kh, kw, input_type.channels, c_out),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.zeros((c_out,), jnp.float32)
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        y = jax.lax.conv_general_dilated(
            x, params["W"].astype(x.dtype), self.stride, "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        b = y.shape[0]
        caps = y.reshape(b, -1, self.capsuleDimensions)
        return _squash(caps), state


class CapsuleLayer(Layer):
    """≡ conf.layers.CapsuleLayer — fully-connected capsules with dynamic
    routing-by-agreement: (B, N_in, D_in) → (B, capsules,
    capsuleDimensions); `routings` fixed iterations unrolled at trace."""

    def __init__(self, capsules=10, capsuleDimensions=16, routings=3, **kw):
        super().__init__(**kw)
        self.capsules = int(capsules)
        self.capsuleDimensions = int(capsuleDimensions)
        self.routings = int(routings)

    def output_type(self, input_type):
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                f"CapsuleLayer '{self.name}' needs capsule (B, N, D) input "
                f"(recurrent InputType), got {input_type}")
        return InputType.recurrent(self.capsuleDimensions, self.capsules)

    def initialize(self, key, input_type):
        n_in = int(input_type.timeSeriesLength)
        d_in = int(input_type.size)
        w = init_weight(key,
                        (n_in, self.capsules, d_in, self.capsuleDimensions),
                        self.weightInit, self.dist)
        return {"W": w}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        dt = x.dtype
        w = params["W"].astype(dt)
        # predictions û_{j|i}: (B, N_in, N_out, D_out)
        u_hat = jnp.einsum("bnd,nmde->bnme", x, w)
        logits = jnp.zeros(u_hat.shape[:3], jnp.float32)  # (B, N_in, N_out)
        v = None
        for it in range(self.routings):
            c = jax.nn.softmax(logits, axis=2).astype(dt)
            s = jnp.einsum("bnm,bnme->bme", c, u_hat)
            v = _squash(s)                                # (B, N_out, D_out)
            if it + 1 < self.routings:
                # agreement: only the coupling logits update (the standard
                # no-gradient-through-routing formulation)
                agree = jnp.einsum("bnme,bme->bnm", u_hat,
                                   jax.lax.stop_gradient(v))
                logits = logits + agree.astype(jnp.float32)
        return v, state


class CapsuleStrengthLayer(Layer):
    """≡ conf.layers.CapsuleStrengthLayer — capsule lengths:
    (B, N, D) → (B, N) (the class-probability readout)."""

    def output_type(self, input_type):
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                f"CapsuleStrengthLayer '{self.name}' needs capsule input, "
                f"got {input_type}")
        return InputType.feedForward(input_type.timeSeriesLength)

    def feed_forward_mask(self, mask):
        return None

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-12), state


class OCNNOutputLayer(BaseOutputLayer):
    """≡ conf.ocnn.OCNNOutputLayer — one-class NN for anomaly detection:
    score(x) = sigmoid(x·V)·w, trained with the OC-NN objective
        L = (1/ν)·mean(relu(r − score)) − r
    where r is a TRAINABLE scalar whose gradient (1 − fraction(score < r)/ν)
    drives it to the ν-quantile of the score distribution. Labels are
    ignored (one-class); output() returns the anomaly score (higher =
    more normal under the training distribution)."""

    #: feature-dependent-loss protocol — the loss needs params (for r)
    needs_features = True

    def __init__(self, hiddenLayerSize=10, nu=0.04, initialRValue=0.1, **kw):
        kw.setdefault("lossFunction", "mcxent")  # unused; protocol filler
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        self.hiddenLayerSize = int(hiddenLayerSize)
        self.nu = float(nu)
        self.initialRValue = float(initialRValue)
        self.nIn = kw.get("nIn")
        self.nOut = 1

    def validate(self):
        Layer.validate(self)

    def apply_defaults(self, defaults):
        Layer.apply_defaults(self, defaults)
        if self.activation is None:
            self.activation = "identity"
        return self

    def output_type(self, input_type):
        return InputType.feedForward(1)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        k1, k2 = jax.random.split(key)
        return ({"V": init_weight(k1, (int(self.nIn), self.hiddenLayerSize),
                                  self.weightInit, self.dist),
                 "w": init_weight(k2, (self.hiddenLayerSize, 1),
                                  self.weightInit, self.dist),
                 "r": jnp.asarray(self.initialRValue, jnp.float32)},
                {}, self.output_type(input_type))

    def pre_activation(self, params, x):
        h = jax.nn.sigmoid(x @ params["V"].astype(x.dtype))
        return h @ params["w"].astype(x.dtype)            # (B, 1) score

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return self.pre_activation(params, x), state

    def compute_loss_with_features(self, params, labels, preact, feats,
                                   mask=None):
        r = params["r"]
        score = preact[:, 0]
        return jnp.mean(jax.nn.relu(r - score)) / self.nu - r