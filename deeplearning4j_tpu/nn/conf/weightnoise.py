"""Weight-space noise (≡ deeplearning4j-nn :: conf.weightnoise.
{WeightNoise, DropConnect, IWeightNoise}).

Unlike dropout (activation-space), these perturb the PARAMETERS each
training step, inside the jitted train step: the noise sample is a pure
function of the step rng, so the whole thing stays one compiled program
— no host round-trip per step, no recompiles. Test-time forward uses the
clean weights (inverted scaling for DropConnect keeps the train-time
expectation equal to the clean weights, as the reference's inverted
dropout on params does).

Usage: layer kwarg or builder default `weightNoise=DropConnect(0.5)` /
`WeightNoise({"type": "normal", "std": 0.01}, additive=True)`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _sample(distribution, rng, shape, dtype):
    kind = (distribution or {}).get("type", "normal")
    if kind == "normal":
        return (distribution.get("mean", 0.0)
                + distribution.get("std", 1.0)
                * jax.random.normal(rng, shape, dtype))
    if kind == "uniform":
        return jax.random.uniform(rng, shape, dtype,
                                  distribution.get("lower", -1.0),
                                  distribution.get("upper", 1.0))
    raise ValueError(f"Unknown weight-noise distribution type '{kind}'")


class IWeightNoise:
    """Contract: map a layer's params pytree to a noised pytree (train
    only; the caller gates on `train`)."""

    def apply_to_params(self, params, rng):
        raise NotImplementedError


def _is_bias(name):
    return name == "b" or name.endswith("b") or "bias" in name.lower()


class WeightNoise(IWeightNoise):
    """≡ conf.weightnoise.WeightNoise — additive (W + ε) or
    multiplicative (W · ε) noise from a distribution spec dict."""

    def __init__(self, distribution=None, applyToBias=False, additive=True):
        self.distribution = dict(distribution
                                 or {"type": "normal", "std": 0.01})
        self.applyToBias = bool(applyToBias)
        self.additive = bool(additive)

    def apply_to_params(self, params, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if _is_bias(k) and not self.applyToBias:
                out[k] = v
                continue
            eps = _sample(self.distribution, jax.random.fold_in(rng, i),
                          v.shape, v.dtype)
            out[k] = v + eps if self.additive else v * eps
        return out


class DropConnect(IWeightNoise):
    """≡ conf.weightnoise.DropConnect — inverted dropout on the weights:
    W' = W · Bernoulli(p) / p with retain probability p (test time uses
    the clean W, expectation preserved)."""

    def __init__(self, weightRetainProb=0.5, applyToBias=False):
        p = float(weightRetainProb)
        if not 0.0 < p <= 1.0:
            raise ValueError(
                f"DropConnect: weightRetainProb must be in (0, 1], got {p}")
        self.weightRetainProb = p
        self.applyToBias = bool(applyToBias)

    def apply_to_params(self, params, rng):
        p = self.weightRetainProb
        if p == 1.0:
            return params
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if _is_bias(k) and not self.applyToBias:
                out[k] = v
                continue
            keep = jax.random.bernoulli(
                jax.random.fold_in(rng, i), p, v.shape)
            out[k] = jnp.where(keep, v / p, 0.0).astype(v.dtype)
        return out
