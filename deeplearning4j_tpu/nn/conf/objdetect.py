"""Object detection head (≡ deeplearning4j-nn ::
conf.layers.objdetect.Yolo2OutputLayer + util.YoloUtils).

YOLOv2 loss, fully vectorized for XLA (no per-box host loops):
predictions (B, H, W, A·(5+C)) reshape to (B, H, W, A, 5+C) =
(tx, ty, tw, th, to, class logits). Cell-relative box decode uses
sigmoid(tx,ty) and anchor-scaled exp(tw,th); the anchor "responsible" for
a ground-truth box is the best shape-prior IoU (argmax over A), computed
batched. Loss = λcoord·coord MSE (responsible anchors) +
confidence MSE toward the live decoded IoU (matching the reference's
predictedWH-based confidence target) + λnoobj·conf² elsewhere +
per-cell class cross-entropy.

Labels are NHWC: (B, H, W, 4+C) — (x, y, w, h) in GRID units (center
xy ∈ [0, W)/[0, H), wh in cells) followed by a one-hot class vector;
all-zero class vector ⇒ no object in that cell (one gt box per cell,
as the reference's label rasterization produces).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalType, InputType
from deeplearning4j_tpu.nn.conf.layers import Layer


class DetectedObject:
    """One final detection (≡ deeplearning4j :: nn.layers.objdetect.
    DetectedObject): center/size in GRID units plus the class
    distribution. `exampleNumber` is the row in the minibatch."""

    __slots__ = ("exampleNumber", "centerX", "centerY", "width", "height",
                 "confidence", "classPredictions")

    def __init__(self, exampleNumber, centerX, centerY, width, height,
                 confidence, classPredictions):
        self.exampleNumber = int(exampleNumber)
        self.centerX = float(centerX)
        self.centerY = float(centerY)
        self.width = float(width)
        self.height = float(height)
        self.confidence = float(confidence)
        self.classPredictions = np.asarray(classPredictions, np.float32)

    def getPredictedClass(self):
        return int(np.argmax(self.classPredictions))

    def getCenterXY(self):
        return (self.centerX, self.centerY)

    def getTopLeftXY(self):
        return (self.centerX - self.width / 2, self.centerY - self.height / 2)

    def getBottomRightXY(self):
        return (self.centerX + self.width / 2,
                self.centerY + self.height / 2)

    def getConfidence(self):
        return self.confidence

    def __repr__(self):
        return (f"DetectedObject(example={self.exampleNumber}, "
                f"xy=({self.centerX:.2f},{self.centerY:.2f}), "
                f"wh=({self.width:.2f},{self.height:.2f}), "
                f"conf={self.confidence:.3f}, "
                f"cls={self.getPredictedClass()})")


@jax.jit
def _nms_keep(xy, wh, conf, cls_id, conf_threshold, iou_threshold):
    """Greedy per-class NMS keep-mask, one example. xy/wh: (N, 2) in grid
    units, conf: (N,), cls_id: (N,) int. Entirely inside jit: the O(N²)
    IoU matrix is one fused elementwise block and the greedy sweep is a
    `fori_loop` over score-sorted candidates — no host round-trips."""
    iou = Yolo2OutputLayer._iou_xywh(xy[:, None, :], wh[:, None, :],
                                     xy[None, :, :], wh[None, :, :])
    suppress = (iou > iou_threshold) & (cls_id[:, None] == cls_id[None, :])
    valid = conf >= conf_threshold
    order = jnp.argsort(-conf)

    def body(i, state):
        keep, alive = state
        idx = order[i]
        take = alive[idx] & valid[idx]
        keep = keep.at[idx].set(take)
        # a taken box kills every lower-scored same-class overlap
        # (including itself — already recorded in `keep`)
        alive = alive & ~(take & suppress[idx])
        return keep, alive

    keep, _ = jax.lax.fori_loop(
        0, xy.shape[0], body,
        (jnp.zeros_like(valid), jnp.ones_like(valid)))
    return keep


class YoloUtils:
    """≡ deeplearning4j :: nn.layers.objdetect.YoloUtils — final
    detection extraction: confidence threshold + per-class greedy NMS."""

    @staticmethod
    def getPredictedObjects(boundingBoxPriors, networkOutput,
                            confThreshold=0.5, nmsThreshold=0.4):
        """Decode raw head output (B, H, W, A*(5+C)) to a list of
        `DetectedObject` per example. The decode + threshold + NMS all run
        batched on device; only the surviving boxes cross to host."""
        layer = Yolo2OutputLayer(
            boundingBoxes=[list(map(float, b)) for b in
                           np.asarray(boundingBoxPriors, np.float32)])
        return layer.getPredictedObjects(networkOutput, confThreshold,
                                         nmsThreshold)

    @staticmethod
    def nms(objects, iouThreshold=0.4):
        """Greedy per-class NMS over an existing DetectedObject list
        (host-side convenience mirroring the reference's List API)."""
        kept = []
        for o in sorted(objects, key=lambda d: -d.confidence):
            c = o.getPredictedClass()
            if all(k.exampleNumber != o.exampleNumber
                   or k.getPredictedClass() != c
                   or _iou_np(k, o) <= iouThreshold for k in kept):
                kept.append(o)
        return kept


def _iou_np(a, b):
    ax1, ay1 = a.getTopLeftXY()
    ax2, ay2 = a.getBottomRightXY()
    bx1, by1 = b.getTopLeftXY()
    bx2, by2 = b.getBottomRightXY()
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    ua = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
    return inter / max(ua, 1e-9)


class Yolo2OutputLayer(Layer):
    """Loss-only head (like the reference: no parameters; sits after the
    1×1 conv that produces A·(5+C) channels)."""

    def __init__(self, boundingBoxes=None, lambdaCoord=5.0, lambdaNoObj=0.5,
                 **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)
        # anchors (A, 2) in grid units (≡ boundingBoxPriors)
        self.boundingBoxes = [list(map(float, b)) for b in (
            boundingBoxes or [[1.0, 1.0], [2.0, 2.0], [3.3, 3.3]])]
        self.lambdaCoord = float(lambdaCoord)
        self.lambdaNoObj = float(lambdaNoObj)

    @property
    def numBoxes(self):
        return len(self.boundingBoxes)

    def output_type(self, input_type):
        return input_type

    def initialize(self, key, input_type):
        if isinstance(input_type, ConvolutionalType):
            a = self.numBoxes
            if input_type.channels % a:
                raise ValueError(
                    f"Yolo2OutputLayer: input channels {input_type.channels}"
                    f" not divisible by {a} anchors")
            self._num_classes = input_type.channels // a - 5
            if self._num_classes < 0:
                raise ValueError("Yolo2OutputLayer: need A*(5+C) channels")
        return {}, {}, input_type

    def pre_activation(self, params, x):
        return x

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return x, state

    # -- decode (≡ YoloUtils.getPredictedObjects, batched) ---------------
    def decode(self, preact):
        """(B,H,W,A*(5+C)) → dict of decoded tensors in grid units."""
        b, h, w, _ = preact.shape
        a = self.numBoxes
        p = preact.reshape(b, h, w, a, -1)
        anchors = jnp.asarray(self.boundingBoxes, preact.dtype)
        cx = jax.lax.broadcasted_iota(preact.dtype, (b, h, w, a), 2)
        cy = jax.lax.broadcasted_iota(preact.dtype, (b, h, w, a), 1)
        x = jax.nn.sigmoid(p[..., 0]) + cx
        y = jax.nn.sigmoid(p[..., 1]) + cy
        bw = anchors[:, 0] * jnp.exp(jnp.clip(p[..., 2], -8, 8))
        bh = anchors[:, 1] * jnp.exp(jnp.clip(p[..., 3], -8, 8))
        conf = jax.nn.sigmoid(p[..., 4])
        cls = jax.nn.softmax(p[..., 5:], axis=-1)
        return {"xy": jnp.stack([x, y], -1), "wh": jnp.stack([bw, bh], -1),
                "confidence": conf, "classes": cls}

    def getPredictedObjects(self, networkOutput, confThreshold=0.5,
                            nmsThreshold=0.4):
        """≡ YoloUtils.getPredictedObjects: decode → confidence threshold
        → per-class greedy NMS → List[List[DetectedObject]] (one inner
        list per minibatch example). All heavy work (decode, O(N²) IoU,
        greedy sweep) runs in ONE jitted vmapped program; the decoded
        tensors then cross to host once to build the per-box objects."""
        pre = jnp.asarray(networkOutput, jnp.float32)
        b, h, w, _ = pre.shape
        dec = self.decode(pre)
        n = h * w * self.numBoxes
        xy = dec["xy"].reshape(b, n, 2)
        wh = dec["wh"].reshape(b, n, 2)
        conf = dec["confidence"].reshape(b, n)
        cls = dec["classes"].reshape(b, n, -1)
        cls_id = jnp.argmax(cls, -1)
        keep = jax.vmap(_nms_keep, in_axes=(0, 0, 0, 0, None, None))(
            xy, wh, conf, cls_id,
            jnp.float32(confThreshold), jnp.float32(nmsThreshold))
        keep, xy, wh, conf, cls = (np.asarray(t) for t in
                                   (keep, xy, wh, conf, cls))
        out = []
        for i in range(b):
            idx = np.nonzero(keep[i])[0]
            idx = idx[np.argsort(-conf[i][idx])]
            out.append([DetectedObject(i, xy[i, j, 0], xy[i, j, 1],
                                       wh[i, j, 0], wh[i, j, 1],
                                       conf[i, j], cls[i, j])
                        for j in idx])
        return out

    @staticmethod
    def _iou_xywh(xy1, wh1, xy2, wh2):
        """IoU of center-format boxes; broadcasts over leading dims."""
        lo1, hi1 = xy1 - wh1 / 2, xy1 + wh1 / 2
        lo2, hi2 = xy2 - wh2 / 2, xy2 + wh2 / 2
        inter = jnp.clip(jnp.minimum(hi1, hi2) - jnp.maximum(lo1, lo2),
                         0.0, None)
        ia = inter[..., 0] * inter[..., 1]
        a1 = jnp.clip(wh1[..., 0] * wh1[..., 1], 1e-9, None)
        a2 = jnp.clip(wh2[..., 0] * wh2[..., 1], 1e-9, None)
        return ia / (a1 + a2 - ia + 1e-9)

    def compute_loss(self, labels, preact, mask=None):
        b, h, w, _ = preact.shape
        a = self.numBoxes
        p = preact.astype(jnp.float32).reshape(b, h, w, a, -1)
        anchors = jnp.asarray(self.boundingBoxes, jnp.float32)  # (A, 2)
        labels = labels.astype(jnp.float32)
        gt_xy = labels[..., 0:2]                      # (B,H,W,2) grid units
        gt_wh = labels[..., 2:4]
        gt_cls = labels[..., 4:]
        obj = (gt_cls.sum(-1) > 0).astype(jnp.float32)  # (B,H,W)

        # responsible anchor: best shape-prior IoU (wh only, origin-aligned)
        inter = (jnp.minimum(gt_wh[..., None, 0], anchors[:, 0])
                 * jnp.minimum(gt_wh[..., None, 1], anchors[:, 1]))
        union = (gt_wh[..., 0:1] * gt_wh[..., 1:2]
                 + anchors[:, 0] * anchors[:, 1] - inter)
        prior_iou = inter / jnp.clip(union, 1e-9, None)   # (B,H,W,A)
        resp = jax.nn.one_hot(jnp.argmax(prior_iou, -1), a) \
            * obj[..., None]                              # (B,H,W,A)

        # decode predictions — the same decode inference uses, so the
        # training target can never drift from the deployed box decode
        dec = self.decode(preact.astype(jnp.float32))
        pred_xy, pred_wh, pred_conf = (dec["xy"], dec["wh"],
                                       dec["confidence"])

        n_obj = jnp.maximum(obj.sum(), 1.0)
        # coordinate loss (sqrt-wh as in the paper/reference)
        d_xy = ((pred_xy - gt_xy[..., None, :]) ** 2).sum(-1)
        d_wh = ((jnp.sqrt(jnp.clip(pred_wh, 1e-9, None))
                 - jnp.sqrt(jnp.clip(gt_wh[..., None, :], 1e-9, None))) ** 2
                ).sum(-1)
        coord = self.lambdaCoord * (resp * (d_xy + d_wh)).sum() / n_obj

        # confidence: target is live decoded IoU for responsible anchors
        live_iou = jax.lax.stop_gradient(self._iou_xywh(
            pred_xy, pred_wh, gt_xy[..., None, :], gt_wh[..., None, :]))
        conf_obj = (resp * (pred_conf - live_iou) ** 2).sum() / n_obj
        conf_noobj = self.lambdaNoObj * (
            (1.0 - resp) * pred_conf ** 2).sum() / (b * h * w * a)

        # class loss at object cells (softmax CE over the responsible
        # anchor's class logits)
        logp = jax.nn.log_softmax(p[..., 5:], axis=-1)
        ce = -(gt_cls[..., None, :] * logp).sum(-1)       # (B,H,W,A)
        cls_loss = (resp * ce).sum() / n_obj

        return coord + conf_obj + conf_noobj + cls_loss
