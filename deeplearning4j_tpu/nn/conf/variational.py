"""VAE reconstruction distributions (≡ deeplearning4j-nn ::
conf.layers.variational.{GaussianReconstructionDistribution,
BernoulliReconstructionDistribution, ExponentialReconstructionDistribution,
CompositeReconstructionDistribution}).

A distribution maps the decoder head's pre-activation block of
`num_params(n)` units to a log-likelihood of the `n` observed features,
and to a mean reconstruction. Everything is a pure jnp function of the
pre-activation so the whole ELBO stays inside one jitted step; the
composite simply partitions the feature/param axes and sums block
log-probs (the reference iterates component distributions the same way —
here the blocks fuse into one program).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation

_LOG_2PI = 1.8378770664093453


class ReconstructionDistribution:
    """Base contract: parameter layout along the last axis."""

    def num_params(self, n_features):
        raise NotImplementedError

    def log_prob(self, x, pre):
        """Sum of per-feature log p(x | params) over the last axis.
        x: (..., n), pre: (..., num_params(n)) → (...,)."""
        raise NotImplementedError

    def mean(self, pre):
        """Mean reconstruction from the params. (..., P) → (..., n)."""
        raise NotImplementedError


class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Params [mean | log(var)], activation applied to the mean block."""

    def __init__(self, activation="identity"):
        self.activation = activation

    def num_params(self, n_features):
        return 2 * n_features

    def _split(self, pre):
        if pre.shape[-1] % 2:
            raise ValueError(
                f"Gaussian reconstruction params must have even width "
                f"[mean | logvar], got {pre.shape[-1]}")
        n = pre.shape[-1] // 2
        mu = get_activation(self.activation)(pre[..., :n])
        logvar = pre[..., n:]
        return mu, logvar

    def log_prob(self, x, pre):
        if pre.shape[-1] != 2 * x.shape[-1]:
            raise ValueError(
                f"Gaussian reconstruction: params width {pre.shape[-1]} != "
                f"2 x features {x.shape[-1]}")
        mu, logvar = self._split(pre)
        return -0.5 * (logvar + (x - mu) ** 2 / jnp.exp(logvar)
                       + _LOG_2PI).sum(-1)

    def mean(self, pre):
        return self._split(pre)[0]


class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Params are logits (sigmoid activation, applied inside a stable
    log-sigmoid form when computing the likelihood)."""

    def __init__(self, activation="sigmoid"):
        self.activation = activation

    def num_params(self, n_features):
        return n_features

    def log_prob(self, x, pre):
        if self.activation == "sigmoid":
            # stable BCE on logits
            per = jnp.maximum(pre, 0) - pre * x \
                + jnp.log1p(jnp.exp(-jnp.abs(pre)))
            return -per.sum(-1)
        p = jnp.clip(get_activation(self.activation)(pre), 1e-7, 1 - 1e-7)
        return (x * jnp.log(p) + (1 - x) * jnp.log1p(-p)).sum(-1)

    def mean(self, pre):
        return get_activation(self.activation)(pre)


class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Params γ with rate λ = exp(γ): log p(x) = γ − exp(γ)·x  (x ≥ 0);
    mean reconstruction 1/λ = exp(−γ)."""

    def __init__(self, activation="identity"):
        self.activation = activation

    def num_params(self, n_features):
        return n_features

    def log_prob(self, x, pre):
        gamma = get_activation(self.activation)(pre)
        gamma = jnp.clip(gamma, -20.0, 20.0)
        return (gamma - jnp.exp(gamma) * x).sum(-1)

    def mean(self, pre):
        gamma = jnp.clip(get_activation(self.activation)(pre), -20.0, 20.0)
        return jnp.exp(-gamma)


class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Per-feature-block composition: block i models `size_i` features
    with its own distribution. Feature axis is partitioned in order;
    the param axis is partitioned by each block's num_params."""

    def __init__(self, blocks=None):
        # blocks: [(size, ReconstructionDistribution), ...]
        self.blocks = [(int(s), d) for s, d in (blocks or [])]
        if not self.blocks:
            raise ValueError(
                "CompositeReconstructionDistribution needs >=1 block — use "
                ".Builder().addDistribution(size, dist).build()")

    class Builder:
        def __init__(self):
            self._blocks = []

        def addDistribution(self, size, distribution):
            self._blocks.append((int(size), distribution))
            return self

        def build(self):
            return CompositeReconstructionDistribution(self._blocks)

    def num_params(self, n_features):
        total_feat = sum(s for s, _ in self.blocks)
        if total_feat != n_features:
            raise ValueError(
                f"Composite blocks cover {total_feat} features but input "
                f"has {n_features}")
        return sum(d.num_params(s) for s, d in self.blocks)

    def _spans(self):
        f = p = 0
        for s, d in self.blocks:
            np_ = d.num_params(s)
            yield (f, f + s), (p, p + np_), d
            f += s
            p += np_

    def log_prob(self, x, pre):
        total = 0.0
        for (f0, f1), (p0, p1), d in self._spans():
            total = total + d.log_prob(x[..., f0:f1], pre[..., p0:p1])
        return total

    def mean(self, pre):
        outs = [d.mean(pre[..., p0:p1])
                for (_, _), (p0, p1), d in self._spans()]
        return jnp.concatenate(outs, axis=-1)


_NAMED = {
    "gaussian": GaussianReconstructionDistribution,
    "bernoulli": BernoulliReconstructionDistribution,
    "exponential": ExponentialReconstructionDistribution,
}


def resolve_reconstruction_distribution(spec):
    """str name | ReconstructionDistribution instance → instance."""
    if isinstance(spec, ReconstructionDistribution):
        return spec
    key = str(spec).lower()
    if key not in _NAMED:
        raise ValueError(
            f"Unknown reconstruction distribution '{spec}'. Available: "
            f"{sorted(_NAMED)} or a ReconstructionDistribution instance")
    return _NAMED[key]()
