"""First-class attention layers for the config DSL (≡ deeplearning4j-nn ::
conf.layers.SelfAttentionLayer / LearnedSelfAttentionLayer /
RecurrentAttentionLayer and conf.graph.AttentionVertex).

The reference builds these on SameDiff dot-product-attention graph ops; the
TPU-native build routes the scaled-dot-product core through the Pallas
flash-attention kernel on TPU (O(T) HBM traffic, online softmax in VMEM;
the q/k tilings are independent, so CROSS-length attention — learned
queries, AttentionVertex with separate query/key inputs — uses the same
kernels with a separate kv-side mask) and a dense XLA einsum path
elsewhere. All four are mask-aware: a (B, T) feature mask excludes padded
positions as both keys and queries, matching the reference's mask
semantics for attention layers.

Layout: batch-major (B, T, F) sequences like the rest of the package;
heads are split/merged around the kernel as (B, H, T, Dh).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType, RecurrentType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.weights_init import init_weight


def _dense_attention(q, k, v, mask=None, q_mask=None):
    """softmax(QKᵀ/√d)V over (B, H, T, Dh); mask is key-validity (B, Tk),
    q_mask query-validity (B, Tq) — invalid query rows come back zeroed
    (same semantics as the flash kernel's masked path)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / (d ** 0.5)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    if q_mask is not None:
        o = jnp.where(q_mask[:, None, :, None] > 0, o, 0.0)
    return o.astype(q.dtype)


def _attend(q, k, v, mask=None, kv_mask=None):
    """Attention core: flash kernel on TPU, dense einsum elsewhere.
    q/k/v: (B, H, Tq/Tk, Dh). Self-attention: pass `mask` (B, T) gating
    both sides. Cross-attention (Tq != Tk or separate sequences): pass
    `kv_mask` (B, Tk) for key validity and optionally `mask` (B, Tq) for
    query rows."""
    if jax.default_backend() == "tpu":
        from deeplearning4j_tpu.kernels import flash_attention
        return flash_attention(q, k, v, mask=mask, kv_mask=kv_mask)
    if kv_mask is None:
        if mask is not None and q.shape[2] != k.shape[2]:
            # same contract as the flash path: a lone (B, T) mask is
            # ambiguous across lengths
            raise ValueError(
                "a single (B, T) mask implies self-attention (Tq == Tk); "
                f"got Tq={q.shape[2]}, Tk={k.shape[2]} — pass kv_mask for "
                "cross-attention")
        return _dense_attention(q, k, v, mask=mask, q_mask=mask)
    return _dense_attention(q, k, v, mask=kv_mask, q_mask=mask)


def _split_heads(x, n_heads):
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    y = x.transpose(0, 2, 1, 3)                  # (B, T, H, Dh)
    return y.reshape(y.shape[0], y.shape[1], -1)


class SelfAttentionLayer(Layer):
    """≡ conf.layers.SelfAttentionLayer — multi-head dot-product
    self-attention over the sequence: (B, T, nIn) → (B, T, nOut).

    projectInput=True (required when nHeads > 1 or nIn != nOut) adds
    learned Q/K/V projections plus the output projection Wo; with
    projectInput=False the raw input is used as queries, keys and values
    (nHeads must be 1 and nOut == nIn), exactly the reference's contract.
    """

    is_recurrent_compatible = True

    def __init__(self, nIn=None, nOut=None, nHeads=1, projectInput=True,
                 **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.nHeads = int(nHeads)
        self.projectInput = bool(projectInput)

    def validate(self):
        super().validate()
        if not self.projectInput and self.nHeads != 1:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': projectInput=False "
                "requires nHeads == 1")

    def output_type(self, input_type):
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs recurrent "
                f"(B, T, F) input, got {input_type}")
        n_out = self.nOut if self.projectInput else input_type.size
        return InputType.recurrent(n_out, input_type.timeSeriesLength)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if not self.projectInput:
            if self.nOut is not None and int(self.nOut) != int(self.nIn):
                raise ValueError(
                    f"{type(self).__name__} '{self.name}': "
                    "projectInput=False requires nOut == nIn")
            self.nOut = self.nIn
            return {}, {}, self.output_type(input_type)
        n_in, n_out = int(self.nIn), int(self.nOut)
        if n_out % self.nHeads:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nOut={n_out} not "
                f"divisible by nHeads={self.nHeads}")
        ks = jax.random.split(key, 4)
        params = {
            "Wq": init_weight(ks[0], (n_in, n_out), self.weightInit, self.dist),
            "Wk": init_weight(ks[1], (n_in, n_out), self.weightInit, self.dist),
            "Wv": init_weight(ks[2], (n_in, n_out), self.weightInit, self.dist),
            "Wo": init_weight(ks[3], (n_out, n_out), self.weightInit,
                              self.dist),
        }
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        dt = x.dtype
        if self.projectInput:
            q = x @ params["Wq"].astype(dt)
            k = x @ params["Wk"].astype(dt)
            v = x @ params["Wv"].astype(dt)
        else:
            q = k = v = x
        o = _attend(_split_heads(q, self.nHeads),
                    _split_heads(k, self.nHeads),
                    _split_heads(v, self.nHeads), mask)
        y = _merge_heads(o)
        if self.projectInput:
            y = y @ params["Wo"].astype(dt)
        y = get_activation(self.activation)(y)
        if mask is not None:
            # mask AFTER the activation so padded rows stay exactly zero
            # even for non-zero-preserving activations (sigmoid(0) = 0.5)
            y = jnp.where(mask[:, :, None] > 0, y, 0).astype(dt)
        return y, state


class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """≡ conf.layers.LearnedSelfAttentionLayer — attention with nQueries
    LEARNED query vectors instead of per-position queries: the sequence is
    summarised into a fixed-length (B, nQueries, nOut) output regardless of
    input length (the reference uses it as a trainable sequence pooler)."""

    def __init__(self, nQueries=None, **kw):
        super().__init__(**kw)
        self.nQueries = None if nQueries is None else int(nQueries)

    def validate(self):
        super().validate()  # includes projectInput/nHeads compatibility
        if not self.nQueries:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nQueries is required")

    def output_type(self, input_type):
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs recurrent "
                f"(B, T, F) input, got {input_type}")
        n_out = self.nOut if self.projectInput else input_type.size
        return InputType.recurrent(n_out, self.nQueries)

    def initialize(self, key, input_type):
        kq, rest = jax.random.split(key)
        params, state, out = super().initialize(rest, input_type)
        q_dim = int(self.nOut) if self.projectInput else int(self.nIn)
        # learned queries live in the ATTENTION space: with projectInput
        # they are post-Wq queries directly (the reference learns Q in the
        # projected space too)
        params = dict(params)
        params.pop("Wq", None)
        params["Q"] = init_weight(kq, (int(self.nQueries), q_dim),
                                  self.weightInit, self.dist)
        return params, state, out

    def feed_forward_mask(self, mask):
        # output length is nQueries and every learned query is valid
        return None

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        dt = x.dtype
        b = x.shape[0]
        if self.projectInput:
            k = x @ params["Wk"].astype(dt)
            v = x @ params["Wv"].astype(dt)
        else:
            k = v = x
        q = jnp.broadcast_to(params["Q"].astype(dt)[None],
                             (b,) + params["Q"].shape)
        # learned queries are always valid; mask only gates the keys —
        # cross-length (Tq = nQueries != Tk in general), flash-backed on
        # TPU via the separate kv-side mask
        o = _attend(_split_heads(q, self.nHeads),
                    _split_heads(k, self.nHeads),
                    _split_heads(v, self.nHeads), kv_mask=mask)
        y = _merge_heads(o)
        if self.projectInput:
            y = y @ params["Wo"].astype(dt)
        return get_activation(self.activation)(y), state


class RecurrentAttentionLayer(Layer):
    """≡ conf.layers.RecurrentAttentionLayer — a recurrent cell whose step
    input is augmented with attention over the whole input sequence, the
    attention query being the previous hidden state:

        a_t = MHA(q = h_{t-1}·Wq, K = x·Wk, V = x·Wv)
        h_t = act(x_t·W + h_{t-1}·R + a_t·Wo + b)

    The unroll is one `lax.scan` (single compiled loop); the x·W and x·Wk /
    x·Wv projections for ALL timesteps are hoisted out of the scan onto one
    big MXU matmul each. Masked steps hold the carry and emit zeros, like
    the package's other recurrent layers."""

    is_recurrent = True

    def __init__(self, nIn=None, nOut=None, nHeads=1, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.nHeads = int(nHeads)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.activation == "identity":
            self.activation = "tanh"
        return self

    def output_type(self, input_type):
        if not isinstance(input_type, RecurrentType):
            raise ValueError(
                f"{type(self).__name__} '{self.name}' needs recurrent "
                f"(B, T, F) input, got {input_type}")
        return InputType.recurrent(self.nOut, input_type.timeSeriesLength)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        n_in, n_out = int(self.nIn), int(self.nOut)
        if n_out % self.nHeads:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nOut={n_out} not "
                f"divisible by nHeads={self.nHeads}")
        ks = jax.random.split(key, 6)
        params = {
            "W": init_weight(ks[0], (n_in, n_out), self.weightInit, self.dist),
            "R": init_weight(ks[1], (n_out, n_out), self.weightInit,
                             self.dist),
            "Wq": init_weight(ks[2], (n_out, n_out), self.weightInit,
                              self.dist),
            "Wk": init_weight(ks[3], (n_in, n_out), self.weightInit,
                              self.dist),
            "Wv": init_weight(ks[4], (n_in, n_out), self.weightInit,
                              self.dist),
            "Wo": init_weight(ks[5], (n_out, n_out), self.weightInit,
                              self.dist),
            "b": jnp.zeros((n_out,), jnp.float32),
        }
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        dt = x.dtype
        b, t, _ = x.shape
        n_out = int(self.nOut)
        h_dim = n_out // self.nHeads
        scale = 1.0 / (h_dim ** 0.5)
        act = get_activation(self.activation)

        # hoisted whole-sequence projections (MXU-shaped)
        xw = x @ params["W"].astype(dt)                      # (B,T,nOut)
        keys = _split_heads(x @ params["Wk"].astype(dt), self.nHeads)
        vals = _split_heads(x @ params["Wv"].astype(dt), self.nHeads)
        kmask = None if mask is None else (mask > 0)         # (B,T)

        R = params["R"].astype(dt)
        Wq = params["Wq"].astype(dt)
        Wo = params["Wo"].astype(dt)
        bias = params["b"].astype(dt)

        def step(h, inputs):
            xw_t, m_t = inputs                               # (B,nOut), (B,)
            q = (h @ Wq).reshape(b, self.nHeads, h_dim)
            s = jnp.einsum("bhd,bhkd->bhk", q, keys).astype(jnp.float32)
            s = s * scale
            if kmask is not None:
                s = jnp.where(kmask[:, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            a = jnp.einsum("bhk,bhkd->bhd", p,
                           vals.astype(jnp.float32)).astype(dt)
            a = a.reshape(b, n_out) @ Wo
            h_new = act(xw_t + h @ R + a + bias)
            if m_t is not None:
                keep = m_t[:, None] > 0
                h_new = jnp.where(keep, h_new, h)
                out = jnp.where(keep, h_new, 0)
            else:
                out = h_new
            return h_new, out

        h0 = jnp.zeros((b, n_out), dt)
        xs = jnp.swapaxes(xw, 0, 1)                          # (T,B,nOut)
        ms = (None if mask is None
              else jnp.swapaxes(jnp.asarray(mask), 0, 1))    # (T,B)
        if ms is None:
            _, ys = jax.lax.scan(lambda h, xt: step(h, (xt, None)), h0, xs)
        else:
            _, ys = jax.lax.scan(step, h0, (xs, ms))
        return jnp.swapaxes(ys, 0, 1), state


class AttentionVertex(GraphVertex):
    """≡ conf.graph.AttentionVertex — parameterized multi-head dot-product
    attention as a ComputationGraph vertex. Inputs: (queries, keys, values)
    or (queries, keysAndValues) or a single input (self-attention). All
    sequences are batch-major (B, T, F); output (B, Tq, nOut).

    Unlike the package's other vertices this one CARRIES PARAMETERS (the
    reference implements it as a SameDiffVertex for the same reason); the
    ComputationGraph initializes/threads them exactly like layer params.
    """

    def __init__(self, nInQueries=None, nInKeys=None, nInValues=None,
                 nOut=None, nHeads=1, projectInput=True, weightInit="xavier",
                 name=None):
        self.nInQueries, self.nInKeys, self.nInValues = (nInQueries, nInKeys,
                                                         nInValues)
        self.nOut, self.nHeads = nOut, int(nHeads)
        self.projectInput = bool(projectInput)
        self.weightInit = weightInit
        self.name = name
        self.updater = None

    def output_type(self, *ts):
        tq = ts[0]
        if not isinstance(tq, RecurrentType):
            raise ValueError(
                f"AttentionVertex '{self.name}' needs recurrent inputs, "
                f"got {tq}")
        n_out = self.nOut if self.projectInput else tq.size
        return InputType.recurrent(n_out, tq.timeSeriesLength)

    def _resolve_nins(self, ts):
        tq = ts[0]
        tk = ts[1] if len(ts) > 1 else tq
        tv = ts[2] if len(ts) > 2 else tk
        if self.nInQueries is None:
            self.nInQueries = tq.size
        if self.nInKeys is None:
            self.nInKeys = tk.size
        if self.nInValues is None:
            self.nInValues = tv.size

    def initialize(self, key, *ts):
        """-> (params, state); input types as inferred at build time."""
        self._resolve_nins(ts)
        if not self.projectInput:
            if self.nHeads != 1:
                raise ValueError(
                    f"AttentionVertex '{self.name}': projectInput=False "
                    "requires nHeads == 1")
            return {}, {}
        n_out = int(self.nOut)
        if n_out % self.nHeads:
            raise ValueError(
                f"AttentionVertex '{self.name}': nOut={n_out} not divisible "
                f"by nHeads={self.nHeads}")
        ks = jax.random.split(key, 4)
        params = {
            "Wq": init_weight(ks[0], (int(self.nInQueries), n_out),
                              self.weightInit, None),
            "Wk": init_weight(ks[1], (int(self.nInKeys), n_out),
                              self.weightInit, None),
            "Wv": init_weight(ks[2], (int(self.nInValues), n_out),
                              self.weightInit, None),
            "Wo": init_weight(ks[3], (n_out, n_out), self.weightInit, None),
        }
        return params, {}

    def apply(self, *xs, params=None, mask=None):
        q_in = xs[0]
        k_in = xs[1] if len(xs) > 1 else q_in
        v_in = xs[2] if len(xs) > 2 else k_in
        dt = q_in.dtype
        params = params or {}
        if self.projectInput:
            q = q_in @ params["Wq"].astype(dt)
            k = k_in @ params["Wk"].astype(dt)
            v = v_in @ params["Wv"].astype(dt)
        else:
            q, k, v = q_in, k_in, v_in
        self_attn = len(xs) == 1 and q.shape == k.shape
        qh = _split_heads(q, self.nHeads)
        kh = _split_heads(k, self.nHeads)
        vh = _split_heads(v, self.nHeads)
        if self_attn:
            o = _attend(qh, kh, vh, mask)
        else:
            # cross attention: the feature mask gates the KEY sequence,
            # passed as the kernel's separate kv-side mask
            kmask = None
            if mask is not None and mask.shape[1] == k.shape[1]:
                kmask = mask
            o = _attend(qh, kh, vh, kv_mask=kmask)
        y = _merge_heads(o)
        if self.projectInput:
            y = y @ params["Wo"].astype(dt)
        return y
