"""ComputationGraphConfiguration + GraphBuilder (≡ deeplearning4j-nn ::
conf.ComputationGraphConfiguration.GraphBuilder).

addInputs/addLayer/addVertex/setOutputs with a topologically-sorted DAG;
shape inference + automatic preprocessor insertion runs at build() exactly
like the MultiLayer path."""
from __future__ import annotations

import json

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.builders import BackpropType, _CNN_LAYERS
from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalFlatType,
                                               ConvolutionalType,
                                               FeedForwardType, InputType)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor)


class GraphNode:
    def __init__(self, name, kind, ref, inputs):
        self.name = name
        self.kind = kind          # "input" | "layer" | "vertex"
        self.ref = ref            # Layer conf or GraphVertex or None
        self.inputs = list(inputs)
        self.preprocessor = None  # auto-inserted for layer nodes


class ComputationGraphConfiguration:
    def __init__(self, defaults, nodes, input_names, output_names,
                 input_types=None, backprop_type=BackpropType.Standard,
                 tbptt_fwd_length=20, tbptt_back_length=20,
                 data_type="float32", seed=0, remat_policy="none"):
        self.defaults = defaults
        self.nodes = nodes                    # dict name -> GraphNode
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self.input_types = list(input_types or [])
        self.backprop_type = backprop_type
        self.tbptt_fwd_length = tbptt_fwd_length
        self.tbptt_back_length = tbptt_back_length
        self.data_type = data_type
        self.seed = seed
        self.remat_policy = remat_policy
        self.topo_order = self._topo_sort()
        self.node_output_types = {}
        if self.input_types:
            self._infer_shapes()

    def consumers(self):
        """{node name: [consumer node names]} over the whole DAG — THE
        consumer map every graph analysis shares (remat segmentation,
        the traffic ledger via remat_plan, conv+BN fusion pairing, the
        quantized chain planner)."""
        consumers = {}
        for name in self.topo_order:
            for p in self.nodes[name].inputs:
                consumers.setdefault(p, []).append(name)
        return consumers

    def remat_segments(self):
        """Per-residual-block recompute segmentation (rematPolicy
        "blocks"): split the topo order at BLOCK BOUNDARIES — nodes
        whose activation is consumed by more than one downstream node
        (in a residual graph that is exactly the block entry/exit: the
        tensor feeding both the main path and the shortcut), plus
        output nodes. Each segment between boundaries re-runs under
        jax.checkpoint in backward, so only boundary activations are
        stored — the cheap conv/BN internals of a block are recomputed
        instead of read back from HBM. Returns a list of [node names],
        one per segment (boundary node last in its segment)."""
        consumers = self.consumers()
        # parents of output nodes stay boundaries too: feature-dependent
        # losses (needs_features heads) read the head's input activation
        # directly from the acts dict
        out_parents = {p for o in self.output_names
                       for p in self.nodes[o].inputs}
        segments, cur = [], []
        for name in self.topo_order:
            if self.nodes[name].kind == "input":
                continue
            cur.append(name)
            boundary = (len(consumers.get(name, ())) != 1
                        or name in self.output_names
                        or name in out_parents)
            if boundary:
                segments.append(cur)
                cur = []
        if cur:
            segments.append(cur)
        return segments

    def remat_plan(self):
        """[(segment, saved_outputs)] — the authoritative statement of
        what block-remat KEEPS: each segment's saved outputs are the
        nodes a later segment, an output head, or the loss reads (on a
        residual chain exactly the block boundary; on interleaved
        branches possibly more). The graph executor saves exactly
        these, and the traffic ledger (quantize/traffic.py) prices
        exactly these — one rule, two consumers, no drift."""
        consumers = self.consumers()
        plan = []
        for seg in self.remat_segments():
            seg_set = set(seg)
            outs = [n for n in seg
                    if n in self.output_names
                    or any(c not in seg_set
                           for c in consumers.get(n, ()))]
            if seg[-1] not in outs:
                outs.append(seg[-1])
            plan.append((seg, outs))
        return plan

    def _topo_sort(self):
        order, seen, visiting = [], set(), set()

        def visit(name):
            if name in seen:
                return
            if name in visiting:
                raise ValueError(f"Cycle in graph at '{name}'")
            visiting.add(name)
            for parent in self.nodes[name].inputs:
                if parent not in self.nodes:
                    raise ValueError(f"Node '{name}' references unknown input "
                                     f"'{parent}'")
                visit(parent)
            visiting.discard(name)
            seen.add(name)
            order.append(name)

        for name in self.nodes:
            visit(name)
        return order

    def _infer_shapes(self):
        if len(self.input_types) != len(self.input_names):
            raise ValueError("setInputTypes arity != addInputs arity")
        types = {}
        for name, t in zip(self.input_names, self.input_types):
            if isinstance(t, ConvolutionalFlatType):
                # keep flat marker for preprocessor insertion
                types[name] = t
            else:
                types[name] = t
        for name in self.topo_order:
            node = self.nodes[name]
            if node.kind == "input":
                self.node_output_types[name] = types[name]
                continue
            in_types = [self.node_output_types[p] for p in node.inputs]
            if node.kind == "vertex":
                if hasattr(node.ref, "initialize"):
                    # parameterized vertex (e.g. AttentionVertex) — keep the
                    # resolved input types for ComputationGraph.init()
                    node.resolved_input_types = in_types
                self.node_output_types[name] = node.ref.output_type(*in_types)
                continue
            layer = node.ref
            layer.apply_defaults(self.defaults)
            cur = in_types[0]
            if node.preprocessor is None:
                node.preprocessor = self._auto_preprocessor(cur, layer)
            if node.preprocessor is not None:
                cur = node.preprocessor.getOutputType(cur)
            if isinstance(cur, ConvolutionalFlatType):
                cur = InputType.feedForward(cur.arrayElementsPerExample())
            if getattr(layer, "nIn", "na") is None:
                layer.nIn = getattr(cur, "channels", None) or cur.size
            node.resolved_input_type = cur
            self.node_output_types[name] = layer.output_type(cur)

    @staticmethod
    def _auto_preprocessor(cur, layer):
        if isinstance(layer, _CNN_LAYERS):
            if isinstance(cur, ConvolutionalFlatType):
                return FeedForwardToCnnPreProcessor(cur.height, cur.width,
                                                    cur.channels)
        elif isinstance(cur, ConvolutionalType) and isinstance(
                layer, (L.DenseLayer, L.EmbeddingLayer)):
            return CnnToFeedForwardPreProcessor(cur.height, cur.width,
                                                cur.channels)
        return None

    def toJson(self):
        from deeplearning4j_tpu.util.serde import encode
        return json.dumps({
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration/v1",
            "defaults": encode(self.defaults),
            "nodes": [
                {"name": n.name, "kind": n.kind, "inputs": n.inputs,
                 "ref": encode(n.ref) if n.ref is not None else None}
                for n in (self.nodes[k] for k in self.topo_order)],
            "input_names": self.input_names,
            "output_names": self.output_names,
            "input_types": [  # encoded separately
                encode(t) for t in self.input_types],
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "data_type": self.data_type,
            "seed": self.seed,
        }, indent=2)

    @staticmethod
    def fromJson(s):
        from deeplearning4j_tpu.util.serde import decode
        d = json.loads(s)
        nodes = {}
        for nd in d["nodes"]:
            ref = decode(nd["ref"]) if nd["ref"] is not None else None
            nodes[nd["name"]] = GraphNode(nd["name"], nd["kind"], ref,
                                          nd["inputs"])
        return ComputationGraphConfiguration(
            decode(d["defaults"]), nodes, d["input_names"], d["output_names"],
            [decode(t) for t in d["input_types"]],
            d.get("backprop_type", "standard"),
            d.get("tbptt_fwd_length", 20), d.get("tbptt_back_length", 20),
            d.get("data_type", "float32"), d.get("seed", 0))


class GraphBuilder:
    def __init__(self, defaults, seed, data_type):
        self._defaults = defaults
        self._seed = seed
        self._data_type = data_type
        self._nodes = {}
        self._inputs = []
        self._outputs = []
        self._input_types = []
        self._backprop_type = BackpropType.Standard
        self._tbptt_fwd = self._tbptt_back = 20
        self._remat_policy = "none"

    def addInputs(self, *names):
        if len(names) == 1 and isinstance(names[0], (list, tuple)):
            names = names[0]
        for n in names:
            self._inputs.append(n)
            self._nodes[n] = GraphNode(n, "input", None, [])
        return self

    def setInputTypes(self, *types):
        if len(types) == 1 and isinstance(types[0], (list, tuple)):
            types = types[0]
        self._input_types = list(types)
        return self

    def addLayer(self, name, layer, *inputs):
        if isinstance(layer, L._Builder):
            layer = layer.build()
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = inputs[0]
        layer.name = name
        self._nodes[name] = GraphNode(name, "layer", layer, inputs)
        return self

    appendLayer = addLayer

    def addVertex(self, name, vertex, *inputs):
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = inputs[0]
        self._nodes[name] = GraphNode(name, "vertex", vertex, inputs)
        return self

    def inputPreProcessor(self, layer_name, pp):
        self._pending_pp = getattr(self, "_pending_pp", {})
        self._pending_pp[layer_name] = pp
        return self

    def setOutputs(self, *names):
        if len(names) == 1 and isinstance(names[0], (list, tuple)):
            names = names[0]
        self._outputs = list(names)
        return self

    def backpropType(self, t):
        self._backprop_type = t
        return self

    def rematPolicy(self, policy):
        """Selective activation recompute. "blocks": save only
        residual-block boundary activations (nodes with >1 consumer —
        the tensors feeding both a block's main path and its shortcut)
        and recompute each block's conv/BN internals in backward via
        jax.checkpoint; the DSL-level byte diet for ResNet-class graphs
        (ROADMAP item 3). "layers" falls back to per-layer remat flags;
        "none" (default) stores everything."""
        from deeplearning4j_tpu.nn.conf.builders import _check_remat_policy
        self._remat_policy = _check_remat_policy(
            policy, ("none", "layers", "blocks"))
        return self

    def tBPTTForwardLength(self, n):
        self._tbptt_fwd = int(n)
        return self

    def tBPTTBackwardLength(self, n):
        self._tbptt_back = int(n)
        return self

    def build(self):
        if not self._inputs:
            raise ValueError("addInputs(...) required")
        if not self._outputs:
            raise ValueError("setOutputs(...) required")
        for name, pp in getattr(self, "_pending_pp", {}).items():
            if name in self._nodes:
                self._nodes[name].preprocessor = pp
        conf = ComputationGraphConfiguration(
            dict(self._defaults), self._nodes, self._inputs, self._outputs,
            self._input_types, self._backprop_type, self._tbptt_fwd,
            self._tbptt_back, self._data_type, self._seed,
            self._remat_policy)
        if self._remat_policy == "layers":
            for name in conf.topo_order:
                node = conf.nodes[name]
                if (node.kind == "layer"
                        and name not in conf.output_names
                        and getattr(node.ref, "remat", None) is None):
                    node.ref.remat = True
        return conf
