"""Layer configurations (≡ deeplearning4j-nn :: conf.layers.*).

Each config class doubles as the reference's `Layer.Builder` surface:
`DenseLayer.Builder().nIn(4).nOut(3).build()` and `DenseLayer(nIn=4, nOut=3)`
are equivalent. A layer config knows how to (a) infer its output InputType,
(b) initialize parameters, (c) apply itself as a pure function — the network
classes compose these into one jitted XLA program (the reference instead
dispatches per-op kernels through its executioner; fusion is XLA's job here).

Conventions: NHWC activations, HWIO conv kernels (TPU/MXU-native; the
reference is NCHW/OIHW), batch-major (B, T, F) sequences. `dropOut(p)`
follows the reference: p = RETAIN probability, inverted dropout at train
time applied to the layer *input*.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalType, FeedForwardType, InputType, RecurrentType)
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights_init import init_weight


class _Builder:
    """Generic fluent builder: any method call records a constructor kwarg."""

    def __init__(self, cls, init_kw=None):
        self._cls = cls
        self._kw = dict(init_kw or {})

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def setter(*args):
            self._kw[name] = args[0] if len(args) == 1 else tuple(args)
            return self

        return setter

    def build(self):
        return self._cls(**self._kw)


class _BuilderFactory:
    """Makes `SomeLayer.Builder(...)` work on every config class, including
    the reference's positional-arg conventions (e.g.
    `OutputLayer.Builder(LossFunction.MCXENT)`,
    `ConvolutionLayer.Builder(5, 5)` = kernel,
    `SubsamplingLayer.Builder(PoolingType.MAX)`)."""

    def __get__(self, obj, objtype=None):
        cls = objtype

        def factory(*args):
            return _Builder(cls, cls._builder_positional(args))

        return factory


def _pair(v):
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class Layer:
    """Base layer config. Fields left None inherit NeuralNetConfiguration
    globals (applied by the builder in nn.conf.builders)."""

    Builder = _BuilderFactory()

    INHERITED = ("activation", "weightInit", "biasInit", "l1", "l2",
                 "dropOut", "updater", "gradientNormalization",
                 "gradientNormalizationThreshold", "weightDecay",
                 "constraints", "weightNoise", "precisionPolicy",
                 "remat")

    @classmethod
    def _builder_positional(cls, args):
        if not args:
            return {}
        raise TypeError(f"{cls.__name__}.Builder takes no positional args")

    def __init__(self, name=None, activation=None, weightInit=None,
                 biasInit=None, l1=None, l2=None, dropOut=None, updater=None,
                 dist=None, gradientNormalization=None,
                 gradientNormalizationThreshold=None, weightDecay=None,
                 constraints=None, **kw):
        self.name = name
        self.activation = activation
        self.weightInit = weightInit
        self.biasInit = biasInit
        self.l1 = l1
        self.l2 = l2
        self.dropOut = dropOut
        self.updater = updater
        self.dist = dist
        self.gradientNormalization = gradientNormalization
        self.gradientNormalizationThreshold = gradientNormalizationThreshold
        self.weightDecay = weightDecay
        self.constraints = constraints
        self.weightNoise = kw.pop("weightNoise", None)
        if "precisionPolicy" in kw and kw["precisionPolicy"] is None:
            # EXPLICIT per-layer opt-out: a literal None would read as
            # "unset" and inherit the global policy right back (None is
            # the INHERITED sentinel) — resolve it to a disabled policy
            # that shadows the inherited one
            from deeplearning4j_tpu.quantize.policy import PrecisionPolicy
            kw["precisionPolicy"] = PrecisionPolicy.off()
        cw = kw.pop("constrainWeights", None)  # builder-method spelling
        if cw is not None:
            self.constraints = (list(cw) if isinstance(cw, (list, tuple))
                                else [cw])
        for k, v in kw.items():
            setattr(self, k, v)

    # -- lifecycle -------------------------------------------------------
    def apply_defaults(self, defaults: dict):
        for field in self.INHERITED:
            if getattr(self, field, None) is None and field in defaults:
                setattr(self, field, defaults[field])
        if self.activation is None:
            self.activation = "identity"
        if self.weightInit is None:
            self.weightInit = "xavier"
        if self.biasInit is None:
            self.biasInit = 0.0
        self.validate()
        return self

    def validate(self):
        """Build-time config validation (≡ the reference failing in
        MultiLayerConfiguration.Builder#build, not mid-training): resolve
        every name now so typos raise actionable ValueErrors at build()."""
        get_activation(self.activation)
        if isinstance(self.weightInit, str):
            init_weight(jax.random.PRNGKey(0), (2, 2), self.weightInit,
                        self.dist)
        loss = getattr(self, "lossFunction", None)
        if isinstance(loss, str):
            get_loss(loss)

    def initialize(self, key, input_type):
        """-> (params dict, state dict, output InputType)"""
        return {}, {}, self.output_type(input_type)

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return x, state

    def feed_forward_mask(self, mask):
        """The feature mask as seen by DOWNSTREAM layers (≡ the reference's
        feedForwardMaskArray): identity by default; layers that reshape or
        drop the time axis override (None = everything valid)."""
        return mask

    # -- helpers ---------------------------------------------------------
    def _dropout_in(self, x, train, rng):
        p = self.dropOut
        if not train or p is None or rng is None:
            return x
        if hasattr(p, "apply"):  # IDropout object (Gaussian/Alpha variants)
            return p.apply(x, rng)
        if p == 0.0 or p == 1.0:
            return x
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / p, 0.0).astype(x.dtype)

    def regularization_terms(self):
        return (self.l1 or 0.0), (self.l2 or 0.0)

    def n_params(self, input_type):
        params, _, _ = self.initialize(jax.random.PRNGKey(0), input_type)
        return sum(int(jnp.size(v)) for v in jax.tree_util.tree_leaves(params))


class DenseLayer(Layer):
    """≡ conf.layers.DenseLayer — y = act(xW + b), W:(nIn,nOut)."""

    def __init__(self, nIn=None, nOut=None, hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut, self.hasBias = nIn, nOut, hasBias

    def output_type(self, input_type):
        if self.nOut is None:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nOut is required "
                "(set .nOut(n) on the builder)")
        if isinstance(input_type, (ConvolutionalType,)):
            raise ValueError(
                f"DenseLayer '{self.name}' got convolutional input {input_type}; "
                "add a CnnToFeedForwardPreProcessor (setInputType does this automatically)")
        if isinstance(input_type, RecurrentType):
            return InputType.recurrent(self.nOut, input_type.timeSeriesLength)
        return InputType.feedForward(self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            raise ValueError(f"DenseLayer '{self.name}': nOut not set")
        w = init_weight(key, (int(self.nIn), int(self.nOut)), self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        w = params["W"]
        qp = getattr(self, "precisionPolicy", None)
        if qp is not None and qp.applies_to(self):
            # QAT fake-quant (STE): weights per-out-channel, input
            # per-tensor — the fp forward simulates the deployed int8
            # lattice so post-training quantization loses ~nothing
            from deeplearning4j_tpu.quantize.core import (fake_quant_act,
                                                          fake_quant_weight)
            if qp.weights:
                w = fake_quant_weight(w, channel_axis=-1)
            if qp.activations:
                x = fake_quant_act(x).astype(x.dtype)
        y = x @ w.astype(x.dtype)
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return get_activation(self.activation)(self.pre_activation(params, x)), state


class EmbeddingLayer(Layer):
    """≡ conf.layers.EmbeddingLayer — int indices (B,) or one-hot (B, nIn)
    to dense vectors via gather (no matmul against one-hot on TPU)."""

    def __init__(self, nIn=None, nOut=None, hasBias=False, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut, self.hasBias = nIn, nOut, hasBias

    def output_type(self, input_type):
        return InputType.feedForward(self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        w = init_weight(key, (int(self.nIn), int(self.nOut)), self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        w = params["W"]
        if jnp.issubdtype(x.dtype, jnp.integer):
            y = jnp.take(w, x.reshape(x.shape[0]).astype(jnp.int32), axis=0)
        elif x.ndim == 2 and x.shape[-1] == w.shape[0]:
            idx = jnp.argmax(x, axis=-1)
            y = jnp.take(w, idx, axis=0)
        else:
            y = jnp.take(w, x.reshape(-1).astype(jnp.int32), axis=0)
        if self.hasBias:
            y = y + params["b"].astype(y.dtype)
        return get_activation(self.activation)(y), state


class EmbeddingSequenceLayer(Layer):
    """≡ EmbeddingSequenceLayer — (B, T) int tokens -> (B, T, nOut)."""

    def __init__(self, nIn=None, nOut=None, inputLength=None, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut, self.inputLength = nIn, nOut, inputLength

    def output_type(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None) or self.inputLength
        return InputType.recurrent(self.nOut, t)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        w = init_weight(key, (int(self.nIn), int(self.nOut)), self.weightInit, self.dist)
        return {"W": w}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        if x.ndim == 3:  # one-hot (B, T, nIn)
            x = jnp.argmax(x, axis=-1)
        y = jnp.take(params["W"], x.astype(jnp.int32), axis=0)
        return get_activation(self.activation)(y), state


def _s2d_dim(k, s, lo, hi, size, b):
    """Block-space conv geometry for one spatial dim under space-to-depth
    factor b. Returns (r, Kb, sb, plb, phb): front zero-pad of the kernel,
    block-kernel size, block stride, block pad lo/hi. Derivation: output i
    reads rows n..n+k-1, n = i*s - lo; with s % b == 0, n mod b is the
    constant r = (-lo) mod b, so tap t lands in relative block (r+t)//b at
    phase (r+t) mod b — a conv over blocks with kernel ceil((r+k)/b)."""
    r = (-lo) % b
    Kb = -(-(r + k) // b)
    sb = s // b
    out = (size + lo + hi - k) // s + 1
    plb = (lo + r) // b
    phb = (out - 1) * sb + Kb - size // b - plb
    return r, Kb, sb, plb, phb


def _space_to_depth_conv(x, w, stride, padding, b):
    """conv(x, w) (NHWC/HWIO, explicit padding) computed in space-to-depth
    form: x folded to (B, H/b, W/b, b·b·C) and w zero-padded/regrouped to
    match. Mathematically identical to the plain conv, but each MXU
    contraction sees b·b·C input channels instead of C — the standard TPU
    conv0 trick for tiny-C stems (ResNet: C=3 → 12). Requires H, W and the
    strides divisible by b, dilation 1."""
    B, H, W_, C = x.shape
    kh, kw, _, O = w.shape
    (lo_h, hi_h), (lo_w, hi_w) = padding
    rh, Kh, sh, plh, phh = _s2d_dim(kh, stride[0], lo_h, hi_h, H, b)
    rw, Kw, sw, plw, phw = _s2d_dim(kw, stride[1], lo_w, hi_w, W_, b)
    if phh < 0 or phw < 0:
        return None
    wp = jnp.zeros((Kh * b, Kw * b, C, O), w.dtype)
    wp = wp.at[rh:rh + kh, rw:rw + kw].set(w)
    wp = wp.reshape(Kh, b, Kw, b, C, O).transpose(0, 2, 1, 3, 4, 5)
    wp = wp.reshape(Kh, Kw, b * b * C, O)
    xb = x.reshape(B, H // b, b, W_ // b, b, C).transpose(0, 1, 3, 2, 4, 5)
    xb = xb.reshape(B, H // b, W_ // b, b * b * C)
    return lax.conv_general_dilated(
        xb, wp, window_strides=(sh, sw),
        padding=((plh, phh), (plw, phw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ConvolutionLayer(Layer):
    """≡ conf.layers.ConvolutionLayer (2D). NHWC/HWIO, lax.conv lowering
    straight onto the MXU (replaces CudnnConvolutionHelper algo selection —
    XLA picks the tiling). spaceToDepth=b computes the same conv in
    block-folded form (see _space_to_depth_conv) — parameters stay in the
    canonical HWIO shape, so serialization/import are unaffected."""

    @classmethod
    def _builder_positional(cls, args):
        if not args:
            return {}
        if len(args) == 1:
            return {"kernelSize": args[0]}
        return {"kernelSize": tuple(args)}

    def __init__(self, nIn=None, nOut=None, kernelSize=(3, 3), stride=(1, 1),
                 padding=(0, 0), dilation=(1, 1), convolutionMode="truncate",
                 hasBias=True, spaceToDepth=1, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = _pair(kernelSize), _pair(stride)
        self.padding, self.dilation = _pair(padding), _pair(dilation)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias
        self.spaceToDepth = int(spaceToDepth or 1)

    def _padding_arg(self):
        if str(self.convolutionMode).lower() == "same":
            return "SAME"
        return [(self.padding[0], self.padding[0]), (self.padding[1], self.padding[1])]

    def _explicit_padding(self, h, w):
        """Resolve 'SAME' to concrete (lo, hi) pairs (TF convention: the
        extra pad goes on the high side)."""
        if str(self.convolutionMode).lower() != "same":
            return ((self.padding[0], self.padding[0]),
                    (self.padding[1], self.padding[1]))
        pads = []
        for size, k, s, d in zip((h, w), self.kernelSize, self.stride,
                                 self.dilation):
            ke = (k - 1) * d + 1
            out = -(-size // s)
            total = max((out - 1) * s + ke - size, 0)
            pads.append((total // 2, total - total // 2))
        return tuple(pads)

    def output_type(self, input_type):
        if self.nOut is None:
            raise ValueError(
                f"{type(self).__name__} '{self.name}': nOut is required "
                "(set .nOut(n) on the builder)")
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"ConvolutionLayer '{self.name}' needs convolutional input, got {input_type}")
        kh, kw = self.kernelSize
        sh, sw = self.stride
        if str(self.convolutionMode).lower() == "same":
            oh = -(-input_type.height // sh)
            ow = -(-input_type.width // sw)
        else:
            ph, pw = self.padding
            oh = (input_type.height + 2 * ph - ((kh - 1) * self.dilation[0] + 1)) // sh + 1
            ow = (input_type.width + 2 * pw - ((kw - 1) * self.dilation[1] + 1)) // sw + 1
        return InputType.convolutional(oh, ow, self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        kh, kw = self.kernelSize
        w = init_weight(key, (kh, kw, int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        w = params["W"]
        qp = getattr(self, "precisionPolicy", None)
        if qp is not None and qp.applies_to(self):
            # QAT fake-quant (STE) — see DenseLayer.pre_activation;
            # per-out-channel weight scales over the HWIO kernel
            from deeplearning4j_tpu.quantize.core import (fake_quant_act,
                                                          fake_quant_weight)
            if qp.weights:
                w = fake_quant_weight(w, channel_axis=-1)
            if qp.activations:
                x = fake_quant_act(x).astype(x.dtype)
        w = w.astype(x.dtype)
        b = getattr(self, "spaceToDepth", 1)
        y = None
        if (b > 1 and self.dilation == (1, 1)
                and self.stride[0] % b == 0 and self.stride[1] % b == 0
                and x.shape[1] % b == 0 and x.shape[2] % b == 0):
            y = _space_to_depth_conv(x, w, self.stride,
                                     self._explicit_padding(x.shape[1],
                                                            x.shape[2]), b)
        if y is None:
            y = lax.conv_general_dilated(
                x, w,
                window_strides=self.stride,
                padding=self._padding_arg(),
                rhs_dilation=self.dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return get_activation(self.activation)(self.pre_activation(params, x)), state


class DepthwiseConvolution2D(ConvolutionLayer):
    """≡ conf.layers.DepthwiseConvolution2D — per-channel conv, no
    cross-channel mixing (feature_group_count = nIn on the MXU path).
    nOut = nIn * depthMultiplier (fixed by the op; nOut need not be set)."""

    def __init__(self, depthMultiplier=1, **kw):
        super().__init__(**kw)
        self.depthMultiplier = int(depthMultiplier)

    def output_type(self, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        self.nOut = int(self.nIn) * self.depthMultiplier
        return super().output_type(input_type)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        self.nOut = int(self.nIn) * self.depthMultiplier
        kh, kw = self.kernelSize
        w = init_weight(key, (kh, kw, 1, int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit),
                                   jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        y = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype),
            window_strides=self.stride,
            padding=self._padding_arg(),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=int(self.nIn))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y


class Cropping2D(Layer):
    """≡ conf.layers.convolutional.Cropping2D — crop (top, bottom, left,
    right) off the spatial dims, NHWC."""

    def __init__(self, cropping=(0, 0, 0, 0), **kw):
        super().__init__(**kw)
        c = cropping
        if isinstance(c, int):
            c = (c, c, c, c)
        elif len(c) == 2:
            if isinstance(c[0], (tuple, list)):  # keras ((t,b),(l,r))
                c = (c[0][0], c[0][1], c[1][0], c[1][1])
            else:
                c = (c[0], c[0], c[1], c[1])
        self.crop = tuple(int(v) for v in c)  # (top, bottom, left, right)

    def output_type(self, input_type):
        t, b, l, r = self.crop
        oh = input_type.height - t - b
        ow = input_type.width - l - r
        if oh <= 0 or ow <= 0:
            raise ValueError(
                f"Cropping2D '{self.name}': crop {self.crop} consumes the "
                f"whole {input_type.height}x{input_type.width} input")
        return InputType.convolutional(oh, ow, input_type.channels)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        t, b, l, r = self.crop
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b, l:w - r, :], state


class SeparableConvolution2D(ConvolutionLayer):
    """≡ conf.layers.SeparableConvolution2D — depthwise + pointwise."""

    def __init__(self, depthMultiplier=1, **kw):
        super().__init__(**kw)
        self.depthMultiplier = int(depthMultiplier)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        kh, kw = self.kernelSize
        k1, k2 = jax.random.split(key)
        dw = init_weight(k1, (kh, kw, 1, int(self.nIn) * self.depthMultiplier),
                         self.weightInit, self.dist)
        pw = init_weight(k2, (1, 1, int(self.nIn) * self.depthMultiplier, int(self.nOut)),
                         self.weightInit, self.dist)
        params = {"dW": dw, "pW": pw}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        y = lax.conv_general_dilated(
            x, params["dW"].astype(x.dtype),
            window_strides=self.stride,
            padding=self._padding_arg(),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=int(self.nIn))
        y = lax.conv_general_dilated(
            y, params["pW"].astype(x.dtype),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y


class LocalResponseNormalization(Layer):
    """≡ conf.layers.LocalResponseNormalization — Krizhevsky-style
    cross-channel LRN (AlexNet era): y = x / (k + α·Σ_{window} x²)^β over
    a window of n adjacent channels, NHWC."""

    def __init__(self, k=2.0, n=5, alpha=1e-4, beta=0.75, **kw):
        super().__init__(**kw)
        self.k, self.n = float(k), int(n)
        self.alpha, self.beta = float(alpha), float(beta)

    def output_type(self, input_type):
        return input_type

    def initialize(self, key, input_type):
        return {}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        sq = jnp.square(x.astype(jnp.float32))
        half = self.n // 2
        # sliding channel-window sum of squares: reduce_window over C
        win = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, self.n),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, self.n - 1 - half)))
        denom = jnp.power(self.k + self.alpha * win, self.beta)
        return (x.astype(jnp.float32) / denom).astype(x.dtype), state


class Deconvolution2D(Layer):
    """≡ conf.layers.Deconvolution2D — transposed conv (learned
    upsampling), NHWC/HWIO via lax.conv_transpose."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(2, 2), stride=(2, 2),
                 padding=(0, 0), convolutionMode="truncate", hasBias=True,
                 **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = _pair(kernelSize), _pair(stride)
        self.padding = _pair(padding)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias

    def _padding_arg(self):
        if str(self.convolutionMode).lower() == "same":
            return "SAME"
        ph, pw = self.padding
        return [(ph, ph), (pw, pw)] if (ph or pw) else "VALID"

    def output_type(self, input_type):
        if self.nOut is None:
            raise ValueError(
                f"Deconvolution2D '{self.name}': nOut is required")
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"Deconvolution2D '{self.name}' needs convolutional input, "
                f"got {input_type}")
        kh, kw = self.kernelSize
        sh, sw = self.stride
        if str(self.convolutionMode).lower() == "same":
            oh, ow = input_type.height * sh, input_type.width * sw
        else:
            ph, pw = self.padding
            oh = sh * (input_type.height - 1) + kh - 2 * ph
            ow = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        kh, kw = self.kernelSize
        w = init_weight(key, (kh, kw, int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit),
                                   jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        y = lax.conv_transpose(
            x, params["W"].astype(x.dtype),
            strides=self.stride,
            padding=self._padding_arg(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return get_activation(self.activation)(
            self.pre_activation(params, x)), state


class RepeatVector(Layer):
    """≡ conf.layers.misc.RepeatVector — (B, F) -> (B, n, F)."""

    def __init__(self, repetitionFactor=1, **kw):
        super().__init__(**kw)
        self.n = int(repetitionFactor)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.size, self.n)

    def initialize(self, key, input_type):
        return {}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state


class ZeroPadding1DLayer(Layer):
    """≡ conf.layers.ZeroPadding1DLayer — pads the time axis of (B,T,F)."""

    def __init__(self, padding=1, **kw):
        super().__init__(**kw)
        p = padding
        self.pad = (int(p), int(p)) if isinstance(p, int) else \
            (int(p[0]), int(p[1]))

    def output_type(self, input_type):
        return InputType.recurrent(
            input_type.size,
            None if getattr(input_type, "timeSeriesLength", None) is None
            else input_type.timeSeriesLength + sum(self.pad))

    def initialize(self, key, input_type):
        return {}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return jnp.pad(x, ((0, 0), self.pad, (0, 0))), state


class Cropping1D(Layer):
    """≡ conf.layers.convolutional.Cropping1D — crops the time axis."""

    def __init__(self, cropping=1, **kw):
        super().__init__(**kw)
        c = cropping
        self.crop = (int(c), int(c)) if isinstance(c, int) else \
            (int(c[0]), int(c[1]))

    def output_type(self, input_type):
        return InputType.recurrent(
            input_type.size,
            None if getattr(input_type, "timeSeriesLength", None) is None
            else input_type.timeSeriesLength - sum(self.crop))

    def initialize(self, key, input_type):
        return {}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        lo, hi = self.crop
        return x[:, lo:x.shape[1] - hi, :], state


class SubsamplingLayer(Layer):
    """≡ conf.layers.SubsamplingLayer — max/avg pooling, NHWC."""

    MAX, AVG = "max", "avg"

    @classmethod
    def _builder_positional(cls, args):
        if not args:
            return {}
        if isinstance(args[0], str):
            out = {"poolingType": args[0]}
            if len(args) > 1:
                out["kernelSize"] = args[1]
            if len(args) > 2:
                out["stride"] = args[2]
            return out
        out = {"kernelSize": args[0]}
        if len(args) > 1:
            out["stride"] = args[1]
        return out

    def __init__(self, poolingType="max", kernelSize=(2, 2), stride=(2, 2),
                 padding=(0, 0), convolutionMode="truncate", **kw):
        super().__init__(**kw)
        self.poolingType = str(poolingType).lower()
        self.kernelSize, self.stride, self.padding = _pair(kernelSize), _pair(stride), _pair(padding)
        self.convolutionMode = convolutionMode

    def output_type(self, input_type):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        if str(self.convolutionMode).lower() == "same":
            oh, ow = -(-input_type.height // sh), -(-input_type.width // sw)
        else:
            ph, pw = self.padding
            oh = (input_type.height + 2 * ph - kh) // sh + 1
            ow = (input_type.width + 2 * pw - kw) // sw + 1
        return InputType.convolutional(oh, ow, input_type.channels)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        if str(self.convolutionMode).lower() == "same":
            pad = "SAME"
        else:
            ph, pw = self.padding
            pad = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
        if self.poolingType == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif self.poolingType in ("avg", "mean"):
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        else:
            raise ValueError(f"Unknown poolingType {self.poolingType}")
        return y, state


def _bn_stats(x):
    """Per-channel mean/var in ONE fused read of x: XLA fuses E[x] and
    E[x²] into a single pass (jnp.var would re-read x for the deviations),
    halving the forward stats bandwidth — BN is pure HBM traffic on TPU."""
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(jnp.float32)
    s1 = jnp.mean(xf, axes)
    s2 = jnp.mean(xf * xf, axes)
    return s1, jnp.maximum(s2 - s1 * s1, 0.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    y, _ = _bn_train_fwd(x, gamma, beta, eps)
    return y


def _bn_train_fwd(x, gamma, beta, eps):
    mu, var = _bn_stats(x)
    r = lax.rsqrt(var + eps)
    a = (gamma * r).astype(x.dtype)
    b = (beta - gamma * mu * r).astype(x.dtype)
    return x * a + b, (x, mu, r, gamma)


def _bn_train_bwd(eps, res, dy):
    """Closed-form BN backward in two passes over (x, dy) instead of the
    3-4 reduction passes jax autodiff emits for the mean/var chain:
      dβ = Σdy, dγ = Σdy·x̂  (one fused reduce reading x, dy)
      dx = γr·dy − γr²·dγ/n·(x−μ) − γr·dβ/n  (one elementwise pass)
    ~10% step-time win on the ResNet-50 TPU bench."""
    x, mu, r, gamma = res
    axes = tuple(range(x.ndim - 1))
    n = 1
    for d in axes:
        n *= x.shape[d]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mu) * r
    dbeta = jnp.sum(dyf, axes)
    dgamma = jnp.sum(dyf * xhat, axes)
    k1 = (gamma * r).astype(x.dtype)
    k2 = (gamma * r * r * dgamma / n).astype(x.dtype)
    c = (gamma * r * (dbeta / n)).astype(x.dtype)
    dx = k1 * dy - (x - mu.astype(x.dtype)) * k2 - c
    return dx, dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class BatchNormalization(Layer):
    """≡ conf.layers.BatchNormalization — channel-last batch norm (replaces
    CudnnBatchNormalizationHelper). Training mode runs a custom-VJP fused
    kernel: single-pass E[x]/E[x²] stats and a closed-form two-pass
    backward (see _bn_train_bwd) — BN is bandwidth-bound on TPU and the
    autodiff'd mean/var chain wastes full passes over the activations.
    State carries running mean/var; `decay` follows the reference default."""

    def __init__(self, nOut=None, decay=0.9, eps=1e-5, gamma=1.0, beta=0.0,
                 lockGammaBeta=False, **kw):
        super().__init__(**kw)
        self.nOut, self.decay, self.eps = nOut, float(decay), float(eps)
        self.gammaInit, self.betaInit = float(gamma), float(beta)
        self.lockGammaBeta = lockGammaBeta

    def output_type(self, input_type):
        return input_type

    def _nfeat(self, input_type):
        # channel count for 2D/3D conv types (channel-last), size otherwise
        c = getattr(input_type, "channels", None)
        return c if c is not None else input_type.size

    def initialize(self, key, input_type):
        n = int(self.nOut or self._nfeat(input_type))
        self.nOut = n
        params = {} if self.lockGammaBeta else {
            "gamma": jnp.full((n,), self.gammaInit, jnp.float32),
            "beta": jnp.full((n,), self.betaInit, jnp.float32)}
        state = {"mean": jnp.zeros((n,), jnp.float32),
                 "var": jnp.ones((n,), jnp.float32)}
        return params, state, input_type

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        if train:
            mean, var = _bn_stats(x)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var}
            gamma = params.get("gamma", jnp.ones_like(state["mean"]))
            beta = params.get("beta", jnp.zeros_like(state["mean"]))
            y = _bn_train(x, gamma, beta, self.eps)
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            inv = lax.rsqrt(var + self.eps)
            gamma = params.get("gamma", jnp.ones_like(mean))
            beta = params.get("beta", jnp.zeros_like(mean))
            # inference: fold into one affine pass y = x·a + b
            a = (gamma * inv).astype(x.dtype)
            b = (beta - gamma * mean * inv).astype(x.dtype)
            y = x * a + b
        return get_activation(self.activation)(y), new_state


class ActivationLayer(Layer):
    """≡ conf.layers.ActivationLayer."""

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state


class DropoutLayer(Layer):
    """≡ conf.layers.DropoutLayer — dropOut is the RETAIN probability."""

    def __init__(self, dropOut=0.5, **kw):
        super().__init__(dropOut=dropOut, **kw)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return self._dropout_in(x, train, rng), state


class ZeroPaddingLayer(Layer):
    """≡ conf.layers.ZeroPaddingLayer (2D, NHWC)."""

    def __init__(self, padding=(1, 1), **kw):
        super().__init__(**kw)
        p = padding
        if isinstance(p, int):
            p = (p, p, p, p)
        elif len(p) == 2:
            p = (p[0], p[0], p[1], p[1])
        self.pad = tuple(int(v) for v in p)  # (top, bottom, left, right)

    def output_type(self, input_type):
        t, b, l, r = self.pad
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        t, b, l, r = self.pad
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


class Upsampling2D(Layer):
    """≡ conf.layers.Upsampling2D — nearest-neighbour repeat."""

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.size = _pair(size)

    def output_type(self, input_type):
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1), self.size[1], axis=2)
        return y, state


class GlobalPoolingLayer(Layer):
    """≡ conf.layers.GlobalPoolingLayer — pools CNN (H,W) or RNN (T) dims.
    poolingType: MAX | AVG | SUM | PNORM."""

    @classmethod
    def _builder_positional(cls, args):
        return {"poolingType": args[0]} if args else {}

    def __init__(self, poolingType="max", pnorm=2, collapseDimensions=True, **kw):
        super().__init__(**kw)
        self.poolingType = str(poolingType).lower()
        self.pnorm = pnorm
        self.collapseDimensions = collapseDimensions

    def output_type(self, input_type):
        if isinstance(input_type, ConvolutionalType):
            return InputType.feedForward(input_type.channels)
        if isinstance(input_type, RecurrentType):
            return InputType.feedForward(input_type.size)
        return input_type

    def feed_forward_mask(self, mask):
        return None  # pooled output has no time axis

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        axes = (1, 2) if x.ndim == 4 else (1,)
        if self.poolingType == "max":
            if mask is not None and x.ndim == 3:
                x = jnp.where(mask[..., None] > 0, x, -jnp.inf)
            y = jnp.max(x, axis=axes)
        elif self.poolingType in ("avg", "mean"):
            if mask is not None and x.ndim == 3:
                m = mask[..., None].astype(x.dtype)
                y = jnp.sum(x * m, axis=axes) / jnp.maximum(jnp.sum(m, axis=axes), 1.0)
            else:
                y = jnp.mean(x, axis=axes)
        elif self.poolingType == "sum":
            y = jnp.sum(x, axis=axes)
        elif self.poolingType == "pnorm":
            y = jnp.sum(jnp.abs(x) ** self.pnorm, axis=axes) ** (1.0 / self.pnorm)
        else:
            raise ValueError(f"Unknown poolingType {self.poolingType}")
        return y, state


class PReLULayer(Layer):
    """≡ conf.layers.PReLULayer — learned per-channel negative slope."""

    def __init__(self, alphaInit=0.0, **kw):
        super().__init__(**kw)
        self.alphaInit = float(alphaInit)

    def initialize(self, key, input_type):
        n = input_type.shape()[-1]
        return ({"alpha": jnp.full((n,), self.alphaInit, jnp.float32)},
                {}, input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        a = params["alpha"].astype(x.dtype)
        return jnp.where(x >= 0, x, a * x), state


class BaseOutputLayer(Layer):
    @classmethod
    def _builder_positional(cls, args):
        return {"lossFunction": args[0]} if args else {}

    def __init__(self, lossFunction="mcxent", **kw):
        kw.setdefault("activation", None)
        super().__init__(**kw)
        self.lossFunction = lossFunction

    def apply_defaults(self, defaults):
        # classification default: an output layer whose activation was set
        # NOWHERE (not on the layer, not in builder defaults) gets softmax.
        # An EXPLICIT activation — including "identity" — always sticks:
        # regression/MDN/Wasserstein heads need raw preactivations, and
        # coercing identity to softmax would silently change the model.
        if self.activation is None and "activation" not in defaults:
            self.activation = "softmax"
        super().apply_defaults(defaults)
        return self

    def compute_loss(self, labels, preact, mask=None):
        return get_loss(self.lossFunction)(labels, preact, self.activation, mask)


class OutputLayer(BaseOutputLayer, DenseLayer):
    """≡ conf.layers.OutputLayer — dense + loss head."""

    def __init__(self, lossFunction="mcxent", **kw):
        DenseLayer.__init__(self, **{k: v for k, v in kw.items()})
        self.lossFunction = lossFunction
        if kw.get("activation") is None:
            self.activation = None

    def apply_defaults(self, defaults):
        # same rule as BaseOutputLayer: softmax only when activation was
        # never set; explicit identity survives
        if self.activation is None and "activation" not in defaults:
            self.activation = "softmax"
        Layer.apply_defaults(self, defaults)
        return self


class LossLayer(BaseOutputLayer):
    """≡ conf.layers.LossLayer — loss only, no parameters."""

    def pre_activation(self, params, x):
        return x

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state


class Convolution1DLayer(Layer):
    """≡ conf.layers.Convolution1DLayer — (B, T, F) temporal conv."""

    def __init__(self, nIn=None, nOut=None, kernelSize=3, stride=1, padding=0,
                 dilation=1, convolutionMode="same", hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = int(kernelSize), int(stride)
        self.padding, self.dilation = int(padding), int(dilation)
        self.convolutionMode, self.hasBias = convolutionMode, hasBias

    def feed_forward_mask(self, mask):
        if mask is None or self.stride == 1 and \
                str(self.convolutionMode).lower() == "same":
            return mask
        m = mask[:, ::self.stride]
        if str(self.convolutionMode).lower() != "same":
            t = mask.shape[1]
            out_t = (t + 2 * self.padding
                     - ((self.kernelSize - 1) * self.dilation + 1)) \
                // self.stride + 1
            m = m[:, :out_t]
        return m

    def output_type(self, input_type):
        t = input_type.timeSeriesLength
        if t is not None:
            if str(self.convolutionMode).lower() == "same":
                t = -(-t // self.stride)
            else:
                t = (t + 2 * self.padding - ((self.kernelSize - 1) * self.dilation + 1)) // self.stride + 1
        return InputType.recurrent(self.nOut, t)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        w = init_weight(key, (self.kernelSize, int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((int(self.nOut),), float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        pad = ("SAME" if str(self.convolutionMode).lower() == "same"
               else [(self.padding, self.padding)])
        y = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype), window_strides=(self.stride,),
            padding=pad, rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return get_activation(self.activation)(y), state


class Upsampling1D(Layer):
    """≡ conf.layers.Upsampling1D — nearest-neighbour repeat along time,
    (B, T, F) convention like the other 1D layers here."""

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.size = int(size if not isinstance(size, (list, tuple))
                        else size[0])

    def output_type(self, input_type):
        t = input_type.timeSeriesLength
        return InputType.recurrent(input_type.size,
                                   None if t is None else t * self.size)

    def feed_forward_mask(self, mask):
        return None if mask is None else jnp.repeat(mask, self.size, axis=1)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state


class TimeDistributed(Layer):
    """≡ conf.layers.recurrent.TimeDistributed — applies a feed-forward
    layer independently at every timestep of (B, T, F) input by folding
    time into the batch (the reference reshapes NCW↔NW the same way; no
    per-step python loop, one big batched op for the MXU)."""

    @classmethod
    def _builder_positional(cls, args):
        return {"underlying": args[0]} if args else {}

    def __init__(self, underlying=None, **kw):
        super().__init__(**kw)
        if underlying is None:
            raise ValueError("TimeDistributed needs an underlying layer")
        self.underlying = underlying

    def apply_defaults(self, defaults):
        # dropout is applied ONCE, by the inner layer (same elements either
        # side of the time fold); forward an explicitly-set wrapper dropOut
        if self.dropOut is not None and self.underlying.dropOut is None:
            self.underlying.dropOut = self.dropOut
        self.underlying.apply_defaults(defaults)
        out = super().apply_defaults(defaults)
        # the network reads training knobs from the OUTER layer while the
        # params belong to the inner one — mirror every per-layer hook the
        # two network classes consult, or the wrapped layer's configured
        # l1/l2/weight-noise/frozen/constraints silently stop applying
        u = self.underlying
        if self.constraints is None:
            self.constraints = getattr(u, "constraints", None)
        if getattr(self, "weightNoise", None) is None:
            self.weightNoise = getattr(u, "weightNoise", None)
        if getattr(u, "frozen_params", False):
            self.frozen_params = True
        return out

    def regularization_terms(self):
        return self.underlying.regularization_terms()

    def output_type(self, input_type):
        inner = self.underlying.output_type(
            InputType.feedForward(input_type.size))
        return InputType.recurrent(inner.size, input_type.timeSeriesLength)

    def initialize(self, key, input_type):
        params, state, inner_out = self.underlying.initialize(
            key, InputType.feedForward(input_type.size))
        return params, state, InputType.recurrent(
            inner_out.size, input_type.timeSeriesLength)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        b, t = x.shape[0], x.shape[1]
        y, state = self.underlying.apply(
            params, state, x.reshape((b * t,) + x.shape[2:]), train=train,
            rng=rng)
        return y.reshape((b, t) + y.shape[1:]), state


class Subsampling1DLayer(Layer):
    """≡ conf.layers.Subsampling1DLayer — (B, T, F) pooling."""

    def __init__(self, poolingType="max", kernelSize=2, stride=2, padding=0, **kw):
        super().__init__(**kw)
        self.poolingType = str(poolingType).lower()
        self.kernelSize, self.stride, self.padding = int(kernelSize), int(stride), int(padding)

    def output_type(self, input_type):
        t = input_type.timeSeriesLength
        if t is not None:
            t = (t + 2 * self.padding - self.kernelSize) // self.stride + 1
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        dims, strides = (1, self.kernelSize, 1), (1, self.stride, 1)
        pad = [(0, 0), (self.padding, self.padding), (0, 0)]
        if self.poolingType == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides, pad)
            y = s / c
        return y, state


class CnnLossLayer(BaseOutputLayer):
    """≡ conf.layers.CnnLossLayer — per-pixel loss over NHWC output, no
    parameters (a preceding 1×1 conv supplies the channel logits; the 3D
    twin is layers3d.Cnn3DLossLayer). Labels are (B, H, W, C); losses are
    rank-agnostic so the per-pixel terms reduce in the standard masked
    mean."""

    def pre_activation(self, params, x):
        return x

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def output_type(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"CnnLossLayer '{self.name}' needs convolutional input, "
                f"got {input_type} (use Cnn3DLossLayer for 5-D volumes)")
        return input_type


class ElementWiseMultiplicationLayer(Layer):
    """≡ conf.layers.misc.ElementWiseMultiplicationLayer —
    y = act(x ⊙ w + b) with a LEARNED per-feature scale w and bias b
    (nOut == nIn). One fused elementwise op on TPU."""

    def __init__(self, nIn=None, nOut=None, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut

    def output_type(self, input_type):
        if (self.nOut is not None and self.nIn is not None
                and int(self.nOut) != int(self.nIn)):
            raise ValueError(
                f"ElementWiseMultiplicationLayer '{self.name}': nIn "
                f"({self.nIn}) must equal nOut ({self.nOut}) — it scales "
                "features elementwise, it cannot resize")
        n = self.nOut or self.nIn
        if isinstance(input_type, RecurrentType):
            return InputType.recurrent(n, input_type.timeSeriesLength)
        return InputType.feedForward(n)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            self.nOut = self.nIn
        out = self.output_type(input_type)
        n = int(self.nIn)
        params = {"W": jnp.ones((n,), jnp.float32),
                  "b": jnp.full((n,), float(self.biasInit), jnp.float32)}
        return params, {}, out

    def pre_activation(self, params, x):
        return x * params["W"].astype(x.dtype) + params["b"].astype(x.dtype)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return (get_activation(self.activation)(
            self.pre_activation(params, x)), state)


def FrozenLayer(layer):
    """≡ conf.layers.misc.FrozenLayer — freeze a layer conf: parameters
    get NoOp updates and the layer always runs in INFERENCE mode during
    training (dropout off, BN running stats pinned). Implemented by
    flagging a deep copy (the flags ride the existing frozen machinery in
    MultiLayerNetwork / transfer learning), so isinstance checks and
    preprocessor inference still see the wrapped layer's real type."""
    import copy

    from deeplearning4j_tpu.nn.updaters import NoOp
    if isinstance(layer, _Builder):
        layer = layer.build()
    layer = copy.deepcopy(layer)
    layer.frozen = True
    layer.updater = NoOp()
    layer.l1 = 0.0
    layer.l2 = 0.0
    layer.weightDecay = 0.0
    return layer


def FrozenLayerWithBackprop(layer):
    """≡ conf.layers.misc.FrozenLayerWithBackprop — parameters frozen
    (NoOp updates + stop_gradient, so not even regularization moves
    them) but, unlike FrozenLayer, the layer keeps its TRAIN-time
    stochastic behavior (dropout stays active) and gradients still flow
    through its outputs to everything upstream."""
    import copy

    from deeplearning4j_tpu.nn.updaters import NoOp
    if isinstance(layer, _Builder):
        layer = layer.build()
    layer = copy.deepcopy(layer)
    layer.frozen_params = True
    layer.updater = NoOp()
    layer.l1 = 0.0
    layer.l2 = 0.0
    layer.weightDecay = 0.0
    return layer
