"""Special layers (≡ deeplearning4j-nn :: conf.layers.LocallyConnected2D,
conf.layers.variational.VariationalAutoencoder, conf.layers.misc.
CenterLossOutputLayer).

LocallyConnected2D keeps the whole unshared-weights contraction as one
einsum — an MXU-shaped batched matmul per output position instead of the
reference's per-position im2col loop. The VAE trains by ELBO through
`MultiLayerNetwork.pretrainLayer` (≡ the reference's layerwise
pretrain(iterator) path); its supervised activate() is the latent mean,
matching the reference's behaviour when a VAE sits mid-network.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalType, InputType
from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer, DenseLayer,
                                               Layer, _pair)
from deeplearning4j_tpu.nn.weights_init import init_weight


class LocallyConnected2D(Layer):
    """≡ conf.layers.LocallyConnected2D — convolution with UNSHARED
    weights: each output position owns its own filter bank."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(3, 3), stride=(1, 1),
                 convolutionMode="truncate", hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = _pair(kernelSize), _pair(stride)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias

    def _out_hw(self, input_type):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        if str(self.convolutionMode).lower() == "same":
            return -(-input_type.height // sh), -(-input_type.width // sw)
        return ((input_type.height - kh) // sh + 1,
                (input_type.width - kw) // sw + 1)

    def output_type(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"LocallyConnected2D '{self.name}' needs convolutional "
                f"input, got {input_type}")
        oh, ow = self._out_hw(input_type)
        return InputType.convolutional(oh, ow, self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.channels
        if self.nOut is None:
            raise ValueError(f"LocallyConnected2D '{self.name}': nOut not set")
        self._in_hw = (input_type.height, input_type.width)
        oh, ow = self._out_hw(input_type)
        kh, kw = self.kernelSize
        w = init_weight(key, (oh, ow, kh * kw * int(self.nIn),
                              int(self.nOut)), self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((oh, ow, int(self.nOut)),
                                   float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def pre_activation(self, params, x):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        oh, ow = params["W"].shape[:2]
        if str(self.convolutionMode).lower() == "same":
            ph = max(0, (oh - 1) * sh + kh - x.shape[1])
            pw = max(0, (ow - 1) * sw + kw - x.shape[2])
            x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                            (pw // 2, pw - pw // 2), (0, 0)))
        # static unrolled patch extraction: (B, oh, ow, kh*kw*C)
        patches = [x[:, di:di + oh * sh:sh, dj:dj + ow * sw:sw, :]
                   for di in range(kh) for dj in range(kw)]
        xp = jnp.concatenate(patches, axis=-1)
        y = jnp.einsum("bhwp,hwpo->bhwo", xp,
                       params["W"].astype(x.dtype))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return y

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return (get_activation(self.activation)(
            self.pre_activation(params, x)), state)


class LocallyConnected1D(Layer):
    """≡ conf.layers.LocallyConnected1D — temporal convolution with
    UNSHARED weights: each output time position owns its own filter.
    Input is the internal (B, T, F) sequence layout; the contraction is
    one einsum (a batched matmul per position), like the 2D variant.
    Needs a static timeSeriesLength on the input type."""

    def __init__(self, nIn=None, nOut=None, kernelSize=3, stride=1,
                 convolutionMode="truncate", hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.kernelSize, self.stride = int(kernelSize), int(stride)
        self.convolutionMode = convolutionMode
        self.hasBias = hasBias

    def _out_t(self, t):
        if str(self.convolutionMode).lower() == "same":
            return -(-t // self.stride)
        return (t - self.kernelSize) // self.stride + 1

    def output_type(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        if t is None:
            raise ValueError(
                f"LocallyConnected1D '{self.name}' needs recurrent input "
                f"with a known timeSeriesLength, got {input_type}")
        return InputType.recurrent(self.nOut, self._out_t(t))

    def feed_forward_mask(self, mask):
        if mask is None:
            return None
        m = mask[:, ::self.stride]
        return m[:, : self._out_t(mask.shape[1])]

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            raise ValueError(f"LocallyConnected1D '{self.name}': nOut not set")
        ot = self._out_t(input_type.timeSeriesLength)
        w = init_weight(key, (ot, self.kernelSize * int(self.nIn),
                              int(self.nOut)), self.weightInit, self.dist)
        params = {"W": w}
        if self.hasBias:
            params["b"] = jnp.full((ot, int(self.nOut)),
                                   float(self.biasInit), jnp.float32)
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        k, s = self.kernelSize, self.stride
        ot = params["W"].shape[0]
        if str(self.convolutionMode).lower() == "same":
            pad = max(0, (ot - 1) * s + k - x.shape[1])
            x = jnp.pad(x, ((0, 0), (pad // 2, pad - pad // 2), (0, 0)))
        # static unrolled patch extraction: (B, ot, k*F)
        patches = [x[:, d:d + ot * s:s, :] for d in range(k)]
        xp = jnp.concatenate(patches, axis=-1)
        y = jnp.einsum("btp,tpo->bto", xp, params["W"].astype(x.dtype))
        if self.hasBias:
            y = y + params["b"].astype(x.dtype)
        return get_activation(self.activation)(y), state


class VariationalAutoencoder(Layer):
    """≡ conf.layers.variational.VariationalAutoencoder.

    Gaussian q(z|x); `reconstructionDistribution` is a name ('gaussian',
    'bernoulli', 'exponential') or a ReconstructionDistribution object —
    including CompositeReconstructionDistribution for per-feature-block
    likelihoods (see nn.conf.variational). Supervised activate() returns
    the latent mean (≡ reference's VAE activate); unsupervised training
    goes through MultiLayerNetwork.pretrain/pretrainLayer maximizing the
    ELBO as one jitted step.
    """

    def __init__(self, nIn=None, nOut=None, encoderLayerSizes=(256,),
                 decoderLayerSizes=(256,),
                 reconstructionDistribution="gaussian",
                 pzxActivationFunction="identity", numSamples=1, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.encoderLayerSizes = tuple(int(s) for s in encoderLayerSizes)
        self.decoderLayerSizes = tuple(int(s) for s in decoderLayerSizes)
        self.reconstructionDistribution = reconstructionDistribution
        self.pzxActivationFunction = pzxActivationFunction
        self.numSamples = int(numSamples)

    def output_type(self, input_type):
        return InputType.feedForward(self.nOut)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            raise ValueError(
                f"VariationalAutoencoder '{self.name}': nOut not set")
        params = {}
        sizes_e = (int(self.nIn),) + self.encoderLayerSizes
        for i, (a, b) in enumerate(zip(sizes_e[:-1], sizes_e[1:])):
            key, k = jax.random.split(key)
            params[f"eW{i}"] = init_weight(k, (a, b), self.weightInit,
                                           self.dist)
            params[f"eb{i}"] = jnp.zeros((b,), jnp.float32)
        key, k1, k2 = jax.random.split(key, 3)
        h = sizes_e[-1]
        params["muW"] = init_weight(k1, (h, int(self.nOut)),
                                    self.weightInit, self.dist)
        params["mub"] = jnp.zeros((int(self.nOut),), jnp.float32)
        params["lvW"] = init_weight(k2, (h, int(self.nOut)),
                                    self.weightInit, self.dist)
        params["lvb"] = jnp.zeros((int(self.nOut),), jnp.float32)
        sizes_d = (int(self.nOut),) + self.decoderLayerSizes
        for i, (a, b) in enumerate(zip(sizes_d[:-1], sizes_d[1:])):
            key, k = jax.random.split(key)
            params[f"dW{i}"] = init_weight(k, (a, b), self.weightInit,
                                           self.dist)
            params[f"db{i}"] = jnp.zeros((b,), jnp.float32)
        key, k1 = jax.random.split(key)
        hd = sizes_d[-1]
        n_params = self._distribution().num_params(int(self.nIn))
        params["rW"] = init_weight(k1, (hd, n_params),
                                   self.weightInit, self.dist)
        params["rb"] = jnp.zeros((n_params,), jnp.float32)
        return params, {}, self.output_type(input_type)

    def _distribution(self):
        from deeplearning4j_tpu.nn.conf.variational import \
            resolve_reconstruction_distribution
        return resolve_reconstruction_distribution(
            self.reconstructionDistribution)

    # -- encoder/decoder pieces ------------------------------------------
    def _encode(self, params, x):
        act = get_activation(self.activation)
        h = x
        for i in range(len(self.encoderLayerSizes)):
            h = act(h @ params[f"eW{i}"].astype(x.dtype)
                    + params[f"eb{i}"].astype(x.dtype))
        pzx = get_activation(self.pzxActivationFunction)
        mu = pzx(h @ params["muW"].astype(x.dtype)
                 + params["mub"].astype(x.dtype))
        logvar = h @ params["lvW"].astype(x.dtype) \
            + params["lvb"].astype(x.dtype)
        return mu, logvar

    def _decode(self, params, z):
        act = get_activation(self.activation)
        h = z
        for i in range(len(self.decoderLayerSizes)):
            h = act(h @ params[f"dW{i}"].astype(z.dtype)
                    + params[f"db{i}"].astype(z.dtype))
        return h

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        mu, _ = self._encode(params, x)
        return mu, state

    def _recon_params(self, params, h):
        """Decoder head → the reconstruction distribution's params."""
        expect = self._distribution().num_params(int(self.nIn))
        got = params["rW"].shape[-1]
        if got != expect:
            raise ValueError(
                f"VariationalAutoencoder '{self.name}': reconstruction "
                f"head has {got} params but distribution "
                f"'{self.reconstructionDistribution}' needs {expect} for "
                f"nIn={self.nIn}. A checkpoint saved before the "
                "distribution-object layout (separate rW/rlvW heads) "
                "cannot be loaded into this layer — re-train or "
                "concatenate the old rW|rlvW into one head.")
        return h @ params["rW"].astype(h.dtype) \
            + params["rb"].astype(h.dtype)

    def reconstruct(self, params, x):
        """Mean reconstruction through the latent mean (≡ reference
        reconstructionProbability-style usage, deterministic form)."""
        mu, _ = self._encode(params, x)
        h = self._decode(params, mu)
        return self._distribution().mean(self._recon_params(params, h))

    def generateAtMeanGivenZ(self, params, z):
        h = self._decode(params, jnp.asarray(z))
        return self._distribution().mean(self._recon_params(params, h))

    def reconstructionLogProbability(self, params, x, rng=None,
                                     numSamples=None):
        """≡ VariationalAutoencoder.reconstructionLogProbability — MC
        estimate of log p(x) via importance sampling from q(z|x):
        log(1/S · Σ p(x|z_s)p(z_s)/q(z_s|x)). Per-example (B,)."""
        dist = self._distribution()
        mu, logvar = self._encode(params, x)
        rng = jax.random.PRNGKey(0) if rng is None else rng
        s_count = int(numSamples or self.numSamples)
        log_ws = []
        for s in range(s_count):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            h = self._decode(params, z)
            log_px_z = dist.log_prob(x, self._recon_params(params, h))
            log_pz = -0.5 * (z ** 2 + jnp.log(2 * jnp.pi)).sum(-1)
            log_qz = -0.5 * (logvar + eps ** 2
                             + jnp.log(2 * jnp.pi)).sum(-1)
            log_ws.append(log_px_z + log_pz - log_qz)
        return jax.scipy.special.logsumexp(
            jnp.stack(log_ws), axis=0) - jnp.log(float(s_count))

    def pretrain_loss(self, params, x, rng):
        """-ELBO (one MC sample per numSamples), mean over batch."""
        dist = self._distribution()
        mu, logvar = self._encode(params, x)
        total = 0.0
        for s in range(self.numSamples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape,
                                    mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            h = self._decode(params, z)
            total = total + dist.log_prob(x, self._recon_params(params, h))
        ll = total / self.numSamples
        kl = -0.5 * (1 + logvar - mu ** 2 - jnp.exp(logvar)).sum(-1)
        return jnp.mean(kl - ll)


class AutoEncoder(DenseLayer):
    """≡ conf.layers.AutoEncoder — denoising autoencoder with tied
    weights: encode act(xW + b), decode act(hWᵀ + vb). Supervised
    activate() is the encoder (like the reference mid-network);
    unsupervised training goes through MultiLayerNetwork.pretrain/
    pretrainLayer, reconstructing from a binomially corrupted input
    (``corruptionLevel`` = drop probability, pretrain only) with an
    optional ``sparsity`` penalty on mean hidden activation — one jitted
    step like the VAE's ELBO path.

    Subclasses DenseLayer so the builder treats it as a feed-forward
    layer (auto CnnToFeedForward preprocessor, conv-input validation) —
    the reference's AutoEncoder extends FeedForwardLayer the same way.
    """

    #: lossFunction aliases -> the two implemented reconstruction losses
    _LOSSES = {"mse": "mse", "l2": "mse", "squared_loss": "mse",
               "xent": "xent", "binaryxent": "xent",
               "reconstruction_crossentropy": "xent"}

    def __init__(self, nIn=None, nOut=None, corruptionLevel=0.3,
                 sparsity=0.0, lossFunction="mse", **kw):
        super().__init__(nIn=nIn, nOut=nOut, **kw)
        self.corruptionLevel = float(corruptionLevel)
        self.sparsity = float(sparsity)
        key = str(lossFunction).lower()
        if key not in self._LOSSES:
            raise ValueError(
                f"AutoEncoder lossFunction {lossFunction!r} not supported; "
                f"use one of {sorted(set(self._LOSSES))}")
        self.lossFunction = self._LOSSES[key]

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            raise ValueError(f"AutoEncoder '{self.name}': nOut not set")
        w = init_weight(key, (int(self.nIn), int(self.nOut)),
                        self.weightInit, self.dist)
        params = {"W": w,
                  "b": jnp.zeros((int(self.nOut),), jnp.float32),
                  "vb": jnp.zeros((int(self.nIn),), jnp.float32)}
        return params, {}, self.output_type(input_type)

    def _encode(self, params, x):
        act = get_activation(self.activation)
        return act(x @ params["W"].astype(x.dtype)
                   + params["b"].astype(x.dtype))

    def _decode(self, params, h):
        act = get_activation(self.activation)
        return act(h @ params["W"].astype(h.dtype).T
                   + params["vb"].astype(h.dtype))

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self._encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        if self.corruptionLevel > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruptionLevel,
                                        x.shape)
            x_in = jnp.where(keep, x, 0.0).astype(x.dtype)
        else:
            x_in = x
        h = self._encode(params, x_in)
        recon = self._decode(params, h)
        if self.lossFunction in ("xent", "binaryxent"):
            eps = 1e-7
            r = jnp.clip(recon, eps, 1.0 - eps)
            loss = -(x * jnp.log(r) + (1.0 - x) * jnp.log(1.0 - r)).sum(-1)
        else:   # mse / squared loss
            loss = ((recon - x) ** 2).sum(-1)
        if self.sparsity > 0.0:
            loss = loss + self.sparsity * jnp.abs(h).mean(-1)
        return jnp.mean(loss)


class CenterLossOutputLayer(BaseOutputLayer, DenseLayer):
    """≡ conf.layers.CenterLossOutputLayer — softmax loss plus
    0.5·λ·||f−c_y||² pulling features toward per-class centers (the
    FaceNetNN4Small2 training head).

    Centers are parameters updated by the network's own optimizer. The
    loss splits into two stop-gradient halves so λ and α act
    independently, as in the reference: λ scales the pull of FEATURES
    toward (frozen) centers, α scales the pull of CENTERS toward the
    (frozen) batch feature means — per optimizer step the center movement
    is lr·α·(c_y − f̄)."""

    needs_features = True

    def __init__(self, alpha=0.05, lambda_=2e-4, **kw):
        kw.setdefault("lossFunction", "mcxent")
        self.alpha = float(alpha)
        self.lambda_ = float(lambda_)
        super().__init__(**kw)

    def initialize(self, key, input_type):
        params, state, out = super().initialize(key, input_type)
        params = dict(params)
        params["centers"] = jnp.zeros((int(self.nOut), int(self.nIn)),
                                      jnp.float32)
        return params, state, out

    def compute_loss_with_features(self, params, labels, preact, features,
                                   mask=None):
        from deeplearning4j_tpu.nn.losses import get_loss
        base = get_loss(self.lossFunction)(labels, preact, self.activation,
                                           mask)
        centers = params["centers"].astype(features.dtype)
        cy = labels @ centers                                  # (B, nIn)
        sg = jax.lax.stop_gradient
        feat_pull = 0.5 * self.lambda_ * jnp.mean(
            ((features - sg(cy)) ** 2).sum(-1))
        center_pull = 0.5 * self.alpha * jnp.mean(
            ((sg(features) - cy) ** 2).sum(-1))
        return base + feat_pull + center_pull


class PermuteLayer(Layer):
    """≡ Keras Permute (imported via KerasModelImport) / nd4j Permute as a
    layer: reorders the NON-batch dimensions. ``dims`` is 1-indexed over
    the non-batch axes, Keras-style — PermuteLayer(dims=(2, 1)) swaps the
    two non-batch axes of a (B, T, F) sequence. No parameters.

    Note: permuting a sequence's time axis de-aligns any feature mask;
    masks are intentionally not propagated through a non-identity
    permute."""

    @classmethod
    def _builder_positional(cls, args):
        if len(args) == 1:
            return {"dims": args[0]}
        return {}

    def __init__(self, dims=None, **kw):
        super().__init__(**kw)
        if dims is None:
            raise ValueError("PermuteLayer requires dims, e.g. dims=(2, 1)")
        self.dims = tuple(int(d) for d in dims)
        if sorted(self.dims) != list(range(1, len(self.dims) + 1)):
            raise ValueError(
                f"PermuteLayer dims must be a permutation of "
                f"1..{len(self.dims)} (1-indexed, batch excluded), "
                f"got {self.dims}")

    def output_type(self, input_type):
        shp = input_type.shape()
        if len(self.dims) != len(shp):
            raise ValueError(
                f"PermuteLayer '{self.name}': dims {self.dims} has "
                f"{len(self.dims)} axes but the input has {len(shp)} "
                f"non-batch axes ({input_type})")
        new = tuple(shp[d - 1] for d in self.dims)
        from deeplearning4j_tpu.nn.conf.inputs import (Convolutional3DType,
                                                       RecurrentType)
        if isinstance(input_type, RecurrentType):
            if new[1] is None:
                # time moved into the feature axis: downstream nIn
                # inference needs a static length
                raise ValueError(
                    f"PermuteLayer '{self.name}': permuting the time axis "
                    "into the feature position needs a known "
                    "timeSeriesLength — use "
                    "InputType.recurrent(size, timeSeriesLength)")
            return InputType.recurrent(new[1], new[0])
        if isinstance(input_type, Convolutional3DType):
            return InputType.convolutional3D(new[0], new[1], new[2], new[3])
        if isinstance(input_type, ConvolutionalType):
            return InputType.convolutional(new[0], new[1], new[2])
        return input_type   # feedForward: dims == (1,), identity

    def feed_forward_mask(self, mask):
        return None if self.dims != tuple(
            range(1, len(self.dims) + 1)) else mask

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return jnp.transpose(x, (0,) + self.dims), state
