"""Sequence utility layers (≡ deeplearning4j-nn :: conf.layers.util.MaskLayer
/ conf.layers.recurrent.MaskZeroLayer / conf.layers.RnnLossLayer).

Mask semantics follow the package convention: feature masks are (B, T)
with 1 = valid; masked steps emit zeros and recurrent carries hold."""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer, Layer


class MaskLayer(Layer):
    """≡ conf.layers.util.MaskLayer — applies the current feature mask to
    the activations (zeroes padded timesteps), passing everything else
    through. No parameters."""

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        if mask is not None:
            x = x * mask.astype(x.dtype)[:, :, None]
        return x, state


class MaskZeroLayer(Layer):
    """≡ conf.layers.recurrent.MaskZeroLayer — wraps a recurrent layer and
    DERIVES the time mask from the data itself: a timestep whose every
    feature equals `maskingValue` is treated as padding (the reference's
    trick for datasets that encode padding in-band)."""

    is_recurrent = True

    @classmethod
    def _builder_positional(cls, args):
        if len(args) == 1:
            return {"layer": args[0]}
        if len(args) == 2:
            return {"layer": args[0], "maskingValue": args[1]}
        return {}

    def __init__(self, layer=None, maskingValue=0.0, **kw):
        super().__init__(**kw)
        if layer is None:
            raise ValueError("MaskZeroLayer requires a wrapped layer")
        self.inner = layer
        self.maskingValue = float(maskingValue)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        self.inner.apply_defaults(defaults)
        return self

    @property
    def nOut(self):
        return self.inner.nOut

    @property
    def nIn(self):
        return self.inner.nIn

    @nIn.setter
    def nIn(self, v):
        self.inner.nIn = v

    def output_type(self, input_type):
        return self.inner.output_type(input_type)

    def initialize(self, key, input_type):
        return self.inner.initialize(key, input_type)

    def _derived_mask(self, x):
        return jnp.any(x != self.maskingValue, axis=-1).astype(x.dtype)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        derived = self._derived_mask(x)
        if mask is not None:
            derived = derived * mask.astype(x.dtype)
        return self.inner.apply(params, state, x, train=train, rng=rng,
                                mask=derived)


class RnnLossLayer(BaseOutputLayer):
    """≡ conf.layers.RnnLossLayer — per-timestep loss over (B, T, C) with
    NO parameters (the previous layer supplies per-step logits); honours
    label masks exactly like RnnOutputLayer."""

    def pre_activation(self, params, x):
        return x

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def output_type(self, input_type):
        return input_type
