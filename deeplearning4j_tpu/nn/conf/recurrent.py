"""Recurrent layers (≡ deeplearning4j-nn :: conf.layers.LSTM / GravesLSTM /
recurrent.Bidirectional / RnnOutputLayer / recurrent.LastTimeStep).

TPU-native design: batch-major (B, T, F) sequences, the whole unroll is a
single `lax.scan` (static trip count → one compiled loop on device, the
reference instead launches per-timestep CUDA kernels via CudnnLSTMHelper).
The input projection x·W for ALL timesteps is hoisted out of the scan into
one big (B*T, nIn)×(nIn, 4H) matmul that rides the MXU; only the recurrent
h·U matmul stays inside the loop.

Masking follows the reference: masked steps emit zeros and hold the carry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType, RecurrentType
from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer, DenseLayer,
                                               Layer)
from deeplearning4j_tpu.nn.weights_init import init_weight


class BaseRecurrentLayer(Layer):
    is_recurrent = True

    def __init__(self, nIn=None, nOut=None, forgetGateBiasInit=1.0,
                 gateActivationFn="sigmoid", scanUnroll=1, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut
        self.forgetGateBiasInit = float(forgetGateBiasInit)
        self.gateActivationFn = gateActivationFn
        # lax.scan unroll factor: k step bodies per loop iteration — fewer
        # loop overheads per timestep on TPU, identical numerics
        self.scanUnroll = int(scanUnroll or 1)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.activation == "identity":
            self.activation = "tanh"  # reference default for LSTMs
        return self

    def output_type(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def zero_carry(self, batch, dtype=jnp.float32):
        h = jnp.zeros((batch, int(self.nOut)), dtype)
        c = jnp.zeros((batch, int(self.nOut)), dtype)
        return (h, c)

    def scan_apply(self, params, x, carry0=None, mask=None):
        raise NotImplementedError

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        y, _ = self.scan_apply(params, x, None, mask)
        return y, state


class LSTM(BaseRecurrentLayer):
    """≡ conf.layers.LSTM (no peepholes). Gate order [i, f, o, g]."""

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        if self.nOut is None:
            raise ValueError(f"LSTM '{self.name}': nOut not set")
        n_in, n_out = int(self.nIn), int(self.nOut)
        k1, k2 = jax.random.split(key)
        w = init_weight(k1, (n_in, 4 * n_out), self.weightInit, self.dist)
        u = init_weight(k2, (n_out, 4 * n_out), self.weightInit, self.dist)
        b = jnp.zeros((4, n_out), jnp.float32)
        b = b.at[1].set(self.forgetGateBiasInit)  # forget-gate bias
        return ({"W": w, "U": u, "b": b.reshape(4 * n_out)},
                {}, self.output_type(input_type))

    def _gates(self, z, c_prev, params, dtype):
        n_out = int(self.nOut)
        gate = get_activation(self.gateActivationFn)
        act = get_activation(self.activation)
        i = gate(z[:, 0 * n_out:1 * n_out])
        f = gate(z[:, 1 * n_out:2 * n_out])
        o = gate(z[:, 2 * n_out:3 * n_out])
        g = act(z[:, 3 * n_out:4 * n_out])
        c = f * c_prev + i * g
        h = o * act(c)
        return h, c

    def scan_apply(self, params, x, carry0=None, mask=None):
        b, t, _ = x.shape
        dtype = x.dtype
        if carry0 is None:
            carry0 = self.zero_carry(b, dtype)
        else:
            carry0 = tuple(c.astype(dtype) for c in carry0)
        # hoist input projection out of the scan: one MXU matmul for all T
        xw = (x.reshape(b * t, -1) @ params["W"].astype(dtype)
              + params["b"].astype(dtype)).reshape(b, t, -1)
        xw_t = jnp.swapaxes(xw, 0, 1)  # (T, B, 4H) scan-major
        u = params["U"].astype(dtype)
        mask_t = None if mask is None else jnp.swapaxes(
            mask.astype(dtype), 0, 1)  # (T, B)

        def step(carry, inp):
            h_prev, c_prev = carry
            if mask_t is None:
                zxw = inp
                m = None
            else:
                zxw, m = inp
            z = zxw + h_prev @ u
            h, c = self._gates(z, c_prev, params, dtype)
            if m is not None:
                # exact SELECT, not arithmetic blending: a valid step is
                # bit-identical to the unmasked step (the KV-less decode
                # path's prefill-at-a-bucket == exact-length guarantee)
                # and a garbage padded input can never NaN-poison a held
                # carry (0 * nan would)
                mm = m[:, None] > 0
                h = jnp.where(mm, h, h_prev)
                c = jnp.where(mm, c, c_prev)
                y = jnp.where(mm, h, 0)
            else:
                y = h
            return (h, c), y

        xs = xw_t if mask_t is None else (xw_t, mask_t)
        carryT, ys = lax.scan(step, carry0, xs, unroll=self.scanUnroll)
        return jnp.swapaxes(ys, 0, 1), carryT


class GravesLSTM(LSTM):
    """≡ conf.layers.GravesLSTM — LSTM with peephole connections
    (Graves 2013): i,f peek at c_{t-1}, o peeks at c_t."""

    def initialize(self, key, input_type):
        params, state, out = super().initialize(key, input_type)
        n_out = int(self.nOut)
        params["pI"] = jnp.zeros((n_out,), jnp.float32)
        params["pF"] = jnp.zeros((n_out,), jnp.float32)
        params["pO"] = jnp.zeros((n_out,), jnp.float32)
        return params, state, out

    def _gates(self, z, c_prev, params, dtype):
        n_out = int(self.nOut)
        gate = get_activation(self.gateActivationFn)
        act = get_activation(self.activation)
        i = gate(z[:, 0 * n_out:1 * n_out] + params["pI"].astype(dtype) * c_prev)
        f = gate(z[:, 1 * n_out:2 * n_out] + params["pF"].astype(dtype) * c_prev)
        g = act(z[:, 3 * n_out:4 * n_out])
        c = f * c_prev + i * g
        o = gate(z[:, 2 * n_out:3 * n_out] + params["pO"].astype(dtype) * c)
        h = o * act(c)
        return h, c


class SimpleRnn(BaseRecurrentLayer):
    """≡ conf.layers.recurrent.SimpleRnn — h_t = act(xW + h·U + b)."""

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = input_type.size
        n_in, n_out = int(self.nIn), int(self.nOut)
        k1, k2 = jax.random.split(key)
        return ({"W": init_weight(k1, (n_in, n_out), self.weightInit, self.dist),
                 "U": init_weight(k2, (n_out, n_out), self.weightInit, self.dist),
                 "b": jnp.zeros((n_out,), jnp.float32)},
                {}, self.output_type(input_type))

    def zero_carry(self, batch, dtype=jnp.float32):
        return (jnp.zeros((batch, int(self.nOut)), dtype),)

    def scan_apply(self, params, x, carry0=None, mask=None):
        b, t, _ = x.shape
        dtype = x.dtype
        if carry0 is None:
            carry0 = self.zero_carry(b, dtype)
        else:
            carry0 = tuple(c.astype(dtype) for c in carry0)
        act = get_activation(self.activation)
        xw = (x.reshape(b * t, -1) @ params["W"].astype(dtype)
              + params["b"].astype(dtype)).reshape(b, t, -1)
        xw_t = jnp.swapaxes(xw, 0, 1)
        u = params["U"].astype(dtype)
        mask_t = None if mask is None else jnp.swapaxes(mask.astype(dtype), 0, 1)

        def step(carry, inp):
            (h_prev,) = carry
            if mask_t is None:
                zxw, m = inp, None
            else:
                zxw, m = inp
            h = act(zxw + h_prev @ u)
            if m is not None:
                # exact select — see LSTM.step
                mm = m[:, None] > 0
                h = jnp.where(mm, h, h_prev)
                y = jnp.where(mm, h, 0)
            else:
                y = h
            return (h,), y

        xs = xw_t if mask_t is None else (xw_t, mask_t)
        carryT, ys = lax.scan(step, carry0, xs, unroll=self.scanUnroll)
        return jnp.swapaxes(ys, 0, 1), carryT


class Bidirectional(Layer):
    """≡ recurrent.Bidirectional(mode, layer) — wraps any recurrent layer;
    merge modes CONCAT/ADD/MUL/AVERAGE."""

    CONCAT, ADD, MUL, AVERAGE = "concat", "add", "mul", "average"
    is_recurrent = True

    @classmethod
    def _builder_positional(cls, args):
        if len(args) == 1:
            return {"layer": args[0]}
        if len(args) == 2:
            return {"mode": args[0], "layer": args[1]}
        return {}

    def __init__(self, layer=None, mode="concat", **kw):
        super().__init__(**kw)
        if layer is None:
            raise ValueError("Bidirectional requires a wrapped recurrent layer")
        import copy
        self.mode = str(mode).lower()
        self.fwd = layer
        self.bwd = copy.deepcopy(layer)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        self.fwd.apply_defaults(defaults)
        self.bwd.apply_defaults(defaults)
        return self

    @property
    def nOut(self):
        n = int(self.fwd.nOut)
        return 2 * n if self.mode == "concat" else n

    def output_type(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def initialize(self, key, input_type):
        k1, k2 = jax.random.split(key)
        pf, _, _ = self.fwd.initialize(k1, input_type)
        pb, _, _ = self.bwd.initialize(k2, input_type)
        return {"fwd": pf, "bwd": pb}, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        yf, _ = self.fwd.scan_apply(params["fwd"], x, None, mask)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.bwd.scan_apply(params["bwd"], xr, None, mr)
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "add":
            y = yf + yb
        elif self.mode == "mul":
            y = yf * yb
        elif self.mode == "average":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"Unknown Bidirectional mode {self.mode}")
        return y, state


class RnnOutputLayer(BaseOutputLayer, DenseLayer):
    """≡ conf.layers.RnnOutputLayer — per-timestep dense + loss over
    (B, T, C) with label masks."""

    def __init__(self, lossFunction="mcxent", **kw):
        DenseLayer.__init__(self, **kw)
        self.lossFunction = lossFunction
        if kw.get("activation") is None:
            self.activation = None

    def apply_defaults(self, defaults):
        Layer.apply_defaults(self, defaults)
        if self.activation == "identity":
            self.activation = "softmax"
        return self

    def output_type(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)


class LastTimeStep(Layer):
    """≡ recurrent.LastTimeStep(layer) — wraps a recurrent layer, emits the
    last (mask-aware) timestep as FF activations."""

    @classmethod
    def _builder_positional(cls, args):
        return {"layer": args[0]} if args else {}

    def __init__(self, layer=None, **kw):
        super().__init__(**kw)
        if layer is None:
            raise ValueError("LastTimeStep requires a wrapped layer")
        self.inner = layer

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        self.inner.apply_defaults(defaults)
        return self

    def feed_forward_mask(self, mask):
        return None  # emits a single (feed-forward) step

    def output_type(self, input_type):
        inner_out = self.inner.output_type(input_type)
        return InputType.feedForward(inner_out.size)

    def initialize(self, key, input_type):
        p, s, _ = self.inner.initialize(key, input_type)
        return p, s, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        y, new_state = self.inner.apply(params, state, x, train=train,
                                        rng=rng, mask=mask)
        if mask is None:
            out = y[:, -1, :]
        else:
            idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
            out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0, :]
        return out, new_state


class GravesBidirectionalLSTM(Bidirectional):
    """≡ conf.layers.GravesBidirectionalLSTM — a single-layer bidirectional
    peephole LSTM: independent forward/backward GravesLSTM passes whose
    activations are combined (the reference sums the directional
    contributions so the layer's output width stays nOut; pass
    mode='concat' for the Keras-style 2·nOut concat instead)."""

    def __init__(self, nIn=None, nOut=None, mode="add", **kw):
        inner = GravesLSTM(nIn=nIn, nOut=nOut,
                           **{k: v for k, v in kw.items()
                              if k in ("forgetGateBiasInit",
                                       "gateActivationFn", "activation",
                                       "weightInit", "scanUnroll")})
        outer_kw = {k: v for k, v in kw.items()
                    if k not in ("forgetGateBiasInit", "gateActivationFn",
                                 "scanUnroll")}
        super().__init__(layer=inner, mode=mode, **outer_kw)

    @property
    def nIn(self):
        return self.fwd.nIn

    @nIn.setter
    def nIn(self, v):
        self.fwd.nIn = v
        self.bwd.nIn = v
