"""NeuralNetConfiguration builder DSL (≡ deeplearning4j-nn ::
conf.NeuralNetConfiguration.Builder / ListBuilder / MultiLayerConfiguration,
conf.ComputationGraphConfiguration.GraphBuilder).

The fluent surface mirrors the reference; `build()` runs the reference's
config-validation + shape-inference pass (nIn inference from InputType,
automatic preprocessor insertion between layer families).
"""
from __future__ import annotations

import json

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalFlatType, ConvolutionalType, FeedForwardType, InputType,
    RecurrentType)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor)
from deeplearning4j_tpu.nn.updaters import Sgd


_CNN_LAYERS = (L.ConvolutionLayer, L.SubsamplingLayer, L.ZeroPaddingLayer,
               L.Upsampling2D, L.SeparableConvolution2D)


class BackpropType:
    Standard = "standard"
    TruncatedBPTT = "truncated_bptt"


def _check_remat_policy(policy, allowed):
    p = "none" if policy in (None, False) else \
        ("layers" if policy is True else str(policy))
    if p not in allowed:
        raise ValueError(
            f"rematPolicy must be one of {allowed}, got {policy!r}")
    return p


class WorkspaceMode:
    """Accepted for API parity; buffer reuse is XLA's job (donated buffers)."""
    ENABLED = "enabled"
    NONE = "none"
    SINGLE = "single"
    SEPARATE = "separate"


class MultiLayerConfiguration:
    def __init__(self, defaults, layer_confs, input_type=None,
                 preprocessors=None, backprop_type=BackpropType.Standard,
                 tbptt_fwd_length=20, tbptt_back_length=20, data_type="float32",
                 seed=0, remat_policy="none"):
        self.defaults = defaults
        self.layers = layer_confs
        self.input_type = input_type
        self.preprocessors = dict(preprocessors or {})
        self.backprop_type = backprop_type
        self.tbptt_fwd_length = tbptt_fwd_length
        self.tbptt_back_length = tbptt_back_length
        self.data_type = data_type
        self.seed = seed
        self.remat_policy = remat_policy
        for i, l in enumerate(self.layers):
            if getattr(l, "name", None) is None:
                l.name = f"layer{i}"  # addressable default (h5 import etc.)
        self._infer_shapes()
        if remat_policy == "layers":
            # every hidden layer recomputes its internals in backward
            # unless it explicitly opted out with .remat(False); the
            # loss head keeps its activations (it is the backward's
            # starting point anyway)
            for l in self.layers[:-1]:
                if getattr(l, "remat", None) is None:
                    l.remat = True

    def _infer_shapes(self):
        """nIn inference + automatic preprocessor insertion (≡ the
        reference's MultiLayerConfiguration.Builder#build with setInputType)."""
        self.input_types = []  # input type seen by each layer (post-preproc)
        cur = self.input_type
        for i, layer in enumerate(self.layers):
            layer.apply_defaults(self.defaults)
            if cur is None:
                self.input_types.append(None)
                continue
            if i not in self.preprocessors:
                auto = self._auto_preprocessor(cur, layer)
                if auto is not None:
                    self.preprocessors[i] = auto
            if i in self.preprocessors:
                cur = self.preprocessors[i].getOutputType(cur)
            if isinstance(cur, ConvolutionalFlatType):
                cur = InputType.feedForward(cur.arrayElementsPerExample())
            # infer nIn (channels for 2D/3D conv types, size otherwise)
            if getattr(layer, "nIn", "na") is None:
                layer.nIn = getattr(cur, "channels", None) or cur.size
            self.input_types.append(cur)
            cur = layer.output_type(cur)
        self.output_type = cur

    @staticmethod
    def _auto_preprocessor(cur, layer):
        if isinstance(layer, _CNN_LAYERS):
            if isinstance(cur, ConvolutionalFlatType):
                return FeedForwardToCnnPreProcessor(cur.height, cur.width, cur.channels)
            if isinstance(cur, FeedForwardType):
                raise ValueError(
                    "Cannot feed flat FeedForward input into a CNN layer without "
                    "image dimensions; use InputType.convolutionalFlat(h, w, c)")
        elif isinstance(cur, ConvolutionalType) and isinstance(
                layer, (L.DenseLayer, L.EmbeddingLayer)) and not isinstance(layer, L.BatchNormalization):
            return CnnToFeedForwardPreProcessor(cur.height, cur.width, cur.channels)
        else:
            from deeplearning4j_tpu.nn.conf.inputs import Convolutional3DType
            if isinstance(cur, Convolutional3DType) and isinstance(
                    layer, (L.DenseLayer, L.EmbeddingLayer)) and not \
                    isinstance(layer, L.BatchNormalization):
                from deeplearning4j_tpu.nn.conf.preprocessors import \
                    Cnn3DToFeedForwardPreProcessor
                return Cnn3DToFeedForwardPreProcessor(
                    cur.depth, cur.height, cur.width, cur.channels)
        return None

    # -- serialization (≡ MultiLayerConfiguration.toJson/fromJson) -------
    def toJson(self):
        from deeplearning4j_tpu.util.serde import config_to_dict
        return json.dumps(config_to_dict(self), indent=2)

    @staticmethod
    def fromJson(s):
        from deeplearning4j_tpu.util.serde import config_from_dict
        return config_from_dict(json.loads(s))


class ListBuilder:
    def __init__(self, defaults, seed, data_type):
        self._defaults = defaults
        self._seed = seed
        self._data_type = data_type
        self._layers = []
        self._input_type = None
        self._preprocessors = {}
        self._backprop_type = BackpropType.Standard
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._remat_policy = "none"

    def layer(self, *args):
        """layer(conf) or layer(index, conf) — accepts a built config or a
        pending Builder."""
        if len(args) == 2:
            idx, conf = args
        else:
            (conf,) = args
            idx = len(self._layers)
        if isinstance(conf, L._Builder):
            conf = conf.build()
        while len(self._layers) <= idx:
            self._layers.append(None)
        self._layers[idx] = conf
        return self

    def setInputType(self, input_type):
        self._input_type = input_type
        return self

    def inputPreProcessor(self, idx, preprocessor):
        self._preprocessors[int(idx)] = preprocessor
        return self

    def backpropType(self, bp_type):
        self._backprop_type = bp_type
        return self

    def rematPolicy(self, policy):
        """Selective activation recompute for the backward pass.
        "layers" wraps every hidden layer's train-mode apply in
        jax.checkpoint — only layer INPUTS are saved for backward,
        everything inside a layer is recomputed (trades the conv/BN
        FLOPs for the eliminated activation reads; ROADMAP item 3).
        "none" (default) stores every intermediate as usual. Individual
        layers may still opt in/out via .remat(True/False)."""
        self._remat_policy = _check_remat_policy(policy, ("none", "layers"))
        return self

    def tBPTTForwardLength(self, n):
        self._tbptt_fwd = int(n)
        return self

    def tBPTTBackwardLength(self, n):
        self._tbptt_back = int(n)
        return self

    def tBPTTLength(self, n):
        self._tbptt_fwd = self._tbptt_back = int(n)
        return self

    def build(self):
        if any(l is None for l in self._layers):
            raise ValueError("Gaps in layer indices")
        return MultiLayerConfiguration(
            dict(self._defaults), list(self._layers), self._input_type,
            self._preprocessors, self._backprop_type, self._tbptt_fwd,
            self._tbptt_back, self._data_type, self._seed,
            self._remat_policy)


class NeuralNetConfiguration:
    class Builder:
        def __init__(self):
            self._defaults = {}
            self._seed = 0
            self._data_type = "float32"

        # -- global hyperparameters -------------------------------------
        def seed(self, s):
            self._seed = int(s)
            return self

        def updater(self, u):
            self._defaults["updater"] = u
            return self

        def weightInit(self, w):
            self._defaults["weightInit"] = w
            return self

        def dist(self, d):
            self._defaults["dist"] = d
            return self

        def activation(self, a):
            self._defaults["activation"] = a
            return self

        def biasInit(self, b):
            self._defaults["biasInit"] = float(b)
            return self

        def l1(self, v):
            self._defaults["l1"] = float(v)
            return self

        def l2(self, v):
            self._defaults["l2"] = float(v)
            return self

        def weightDecay(self, v):
            self._defaults["weightDecay"] = float(v)
            return self

        def dropOut(self, p):
            self._defaults["dropOut"] = float(p)
            return self

        def weightNoise(self, wn):
            """≡ Builder.weightNoise — weight-space noise (WeightNoise /
            DropConnect) applied to every layer's params at train time."""
            self._defaults["weightNoise"] = wn
            return self

        def constrainWeights(self, *constraints):
            """≡ Builder.constrainWeights — applied post-update to every
            layer's weight params (W/U/dW/pW), inside the jitted step."""
            self._defaults["constraints"] = (
                self._defaults.get("constraints", []) + list(constraints))
            return self

        def constrainBias(self, *constraints):
            import copy
            cs = []
            for c in constraints:
                c = copy.copy(c)
                c.applies_to = ("b",)
                cs.append(c)
            self._defaults["constraints"] = (
                self._defaults.get("constraints", []) + cs)
            return self

        def constrainAllParameters(self, *constraints):
            import copy
            from deeplearning4j_tpu.nn.constraints import WEIGHT_KEYS
            cs = []
            for c in constraints:
                c = copy.copy(c)
                c.applies_to = WEIGHT_KEYS + ("b", "gamma", "beta")
                cs.append(c)
            self._defaults["constraints"] = (
                self._defaults.get("constraints", []) + cs)
            return self

        def precisionPolicy(self, policy):
            """Quantization precision policy inherited by every layer
            (quantize.PrecisionPolicy): training-time QAT fake-quant +
            the eligibility map for the real int8 inference rewrite
            (`quantize.quantize_network`). None = full precision."""
            self._defaults["precisionPolicy"] = policy
            return self

        def gradientAccumulation(self, n):
            """In-step microbatch accumulation: the fit loops group
            every G consecutive same-shape batches into ONE jitted
            optimizer step that lax.scans the G backward passes,
            accumulates gradients on device, and applies a single
            update — one dispatch and one host round-trip per optimizer
            step regardless of G, so effective batch sizes scale past
            what fits device memory at once. Inherited by
            ParallelWrapper (the dp path) and the model fit loops;
            sub-G remainders run as ordinary per-batch steps. TBPTT
            configs ignore it (the segment loop owns the dispatch)."""
            n = int(n)
            if n < 1:
                raise ValueError("gradientAccumulation must be >= 1")
            self._defaults["gradientAccumulation"] = n
            return self

        def gradientNormalization(self, gn):
            self._defaults["gradientNormalization"] = gn
            return self

        def gradientNormalizationThreshold(self, t):
            self._defaults["gradientNormalizationThreshold"] = float(t)
            return self

        def dataType(self, dt):
            self._data_type = str(dt)
            return self

        def convolutionMode(self, mode):
            self._defaults["convolutionMode"] = mode
            return self

        # Accepted for parity; no-ops under XLA (documented):
        def optimizationAlgo(self, algo):
            return self

        def trainingWorkspaceMode(self, mode):
            return self

        def inferenceWorkspaceMode(self, mode):
            return self

        def cacheMode(self, mode):
            return self

        def cudnnAlgoMode(self, mode):
            return self

        def miniBatch(self, flag):
            return self

        # -- terminal builders ------------------------------------------
        def list(self):
            d = dict(self._defaults)
            d.setdefault("updater", Sgd(0.1))
            return ListBuilder(d, self._seed, self._data_type)

        def graphBuilder(self):
            from deeplearning4j_tpu.nn.conf.graph_builder import GraphBuilder
            d = dict(self._defaults)
            d.setdefault("updater", Sgd(0.1))
            return GraphBuilder(d, self._seed, self._data_type)
