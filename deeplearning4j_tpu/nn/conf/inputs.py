"""InputType system (≡ deeplearning4j-nn :: conf.inputs.InputType).

Shapes are *per-example* (no batch dim). CNN activations are NHWC — the
TPU-native layout (the reference is NCHW; we deliberately invert: XLA
tiles NHWC convs onto the MXU without transposes).
"""
from __future__ import annotations

from dataclasses import dataclass


class InputType:
    @staticmethod
    def feedForward(size):
        return FeedForwardType(int(size))

    @staticmethod
    def convolutional(height, width, channels):
        return ConvolutionalType(int(height), int(width), int(channels))

    @staticmethod
    def convolutionalFlat(height, width, channels):
        return ConvolutionalFlatType(int(height), int(width), int(channels))

    @staticmethod
    def convolutional3D(depth, height, width, channels):
        return Convolutional3DType(int(depth), int(height), int(width),
                                   int(channels))

    @staticmethod
    def recurrent(size, timeSeriesLength=None):
        return RecurrentType(int(size), timeSeriesLength)


@dataclass(frozen=True)
class FeedForwardType(InputType):
    size: int

    def arrayElementsPerExample(self):
        return self.size

    def shape(self):
        return (self.size,)


@dataclass(frozen=True)
class ConvolutionalType(InputType):
    """NHWC activation: (height, width, channels)."""
    height: int
    width: int
    channels: int

    def arrayElementsPerExample(self):
        return self.height * self.width * self.channels

    def shape(self):
        return (self.height, self.width, self.channels)


@dataclass(frozen=True)
class ConvolutionalFlatType(InputType):
    """Flattened image rows (e.g. raw MNIST vectors): needs a
    FeedForwardToCnnPreProcessor before any conv layer."""
    height: int
    width: int
    channels: int

    def arrayElementsPerExample(self):
        return self.height * self.width * self.channels

    def shape(self):
        return (self.height * self.width * self.channels,)


@dataclass(frozen=True)
class Convolutional3DType(InputType):
    """NDHWC activation: (depth, height, width, channels) — the TPU-native
    volumetric layout (the reference's Convolution3D is NCDHW)."""
    depth: int
    height: int
    width: int
    channels: int

    def arrayElementsPerExample(self):
        return self.depth * self.height * self.width * self.channels

    def shape(self):
        return (self.depth, self.height, self.width, self.channels)


@dataclass(frozen=True)
class RecurrentType(InputType):
    """(time, size) per example — batch-major (B, T, F) arrays."""
    size: int
    timeSeriesLength: object = None

    def arrayElementsPerExample(self):
        t = self.timeSeriesLength or 1
        return self.size * t

    def shape(self):
        return (self.timeSeriesLength, self.size)
