"""User-defined custom layers (≡ deeplearning4j-nn ::
conf.layers.samediff.SameDiffLayer / SameDiffLambdaLayer / SameDiffVertex).

The reference's escape hatch lets users define a layer by writing its
forward as a SameDiff graph; autodiff + the runtime do the rest. The
TPU-native counterpart: the user writes the forward as a PURE JAX function
(jax.numpy / lax — anything jit-traceable) and declares parameter shapes;
`jax.grad` through the whole-network jitted step differentiates it, so a
custom layer trains exactly like a built-in one, with zero framework code.

Usage:

    class TimesPlus(SameDiffLayer):
        def __init__(self, nOut=None, **kw):
            super().__init__(**kw)
            self.nOut = nOut
        def defineParameters(self):
            return {"W": (self.nIn, self.nOut), "b": (self.nOut,)}
        def defineLayer(self, params, x, mask=None):
            return jnp.tanh(x @ params["W"] + params["b"])

    net = ...list().layer(TimesPlus(nOut=8))...

Custom classes serialize through ModelSerializer: the config JSON records
the defining module, which is imported again on restore (the class must be
importable — same contract as the reference's Jackson subtype registry).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType, RecurrentType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.weights_init import init_weight


class SameDiffLayer(Layer):
    """Base class for user-defined layers (≡ samediff.SameDiffLayer).

    Subclasses implement:
      - defineParameters() -> {name: shape tuple}  (may be empty)
      - defineLayer(params, x, mask=None) -> output array
      - getOutputType(input_type) -> InputType  (optional; defaults to
        feedForward(nOut) / recurrent(nOut) shape-preserving inference)
    Optional: initializeParameters(key, name, shape) to override the
    default weightInit-based initializer for specific parameters.
    """

    def __init__(self, nIn=None, nOut=None, **kw):
        super().__init__(**kw)
        self.nIn, self.nOut = nIn, nOut

    # -- user surface ----------------------------------------------------
    def defineParameters(self):
        return {}

    def defineLayer(self, params, x, mask=None):
        raise NotImplementedError(
            f"{type(self).__name__} must implement defineLayer(params, x)")

    def initializeParameters(self, key, name, shape):
        """Default: weightInit for >=2-D params, zeros for 1-D (biases)."""
        if len(shape) >= 2:
            return init_weight(key, shape, self.weightInit, self.dist)
        return jnp.zeros(shape, jnp.float32)

    def getOutputType(self, input_type):
        n_out = self.nOut if self.nOut is not None else getattr(
            input_type, "size", None)
        if n_out is None:
            return input_type
        if isinstance(input_type, RecurrentType):
            return InputType.recurrent(n_out, input_type.timeSeriesLength)
        return InputType.feedForward(n_out)

    # -- framework bridge ------------------------------------------------
    def output_type(self, input_type):
        return self.getOutputType(input_type)

    def initialize(self, key, input_type):
        if self.nIn is None:
            self.nIn = getattr(input_type, "size", None) or getattr(
                input_type, "channels", None)
        shapes = self.defineParameters()
        params = {}
        for name in sorted(shapes):
            key, sub = jax.random.split(key)
            params[name] = self.initializeParameters(
                sub, name, tuple(int(d) for d in shapes[name]))
        return params, {}, self.output_type(input_type)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        x = self._dropout_in(x, train, rng)
        return self.defineLayer(params, x, mask=mask), state


class SameDiffLambdaLayer(SameDiffLayer):
    """Parameter-free custom layer (≡ samediff.SameDiffLambdaLayer).

    Either subclass and override defineLayer(params, x), or pass a plain
    function: SameDiffLambdaLayer(fn=lambda x: jnp.tanh(x)). A function
    passed by value cannot be serialized (same as the reference, where
    lambda layers must be registered classes to round-trip) — subclass for
    save/load support.
    """

    def __init__(self, fn=None, **kw):
        super().__init__(**kw)
        self._fn = fn

    def defineParameters(self):
        return {}

    def defineLayer(self, params, x, mask=None):
        fn = getattr(self, "_fn", None)
        if fn is None:
            raise NotImplementedError(
                f"{type(self).__name__}: override defineLayer() or pass "
                "fn=... (note fn= does not survive serialization — "
                "subclass to round-trip)")
        return fn(x)


class SameDiffOutputLayer(SameDiffLayer):
    """User-defined OUTPUT layer (≡ samediff.SameDiffOutputLayer): the
    custom-layer escape hatch for the loss head. Subclasses implement

      - defineParameters() / defineLayer(params, x)  (as SameDiffLayer)
      - defineLoss(labels, output, mask=None) -> scalar loss

    defineLayer's result is both the network's output() and what
    defineLoss scores (activation defaults to identity — apply any
    nonlinearity inside defineLayer). Trains through the same jitted
    whole-network step as built-in output layers."""

    def __init__(self, **kw):
        kw.setdefault("activation", "identity")
        super().__init__(**kw)

    def defineLoss(self, labels, output, mask=None):
        raise NotImplementedError(
            f"{type(self).__name__} must implement "
            "defineLoss(labels, output, mask=None) -> scalar")

    # -- output-layer protocol (nn.multilayer/graph loss path) -----------
    #: the network classes pass the current feature mask into
    #: pre_activation when this is set, so defineLayer keeps its
    #: mask=... contract even as the loss head
    pre_activation_takes_mask = True

    def pre_activation(self, params, x, mask=None):
        return self.defineLayer(params, x, mask=mask)

    def compute_loss(self, labels, preact, mask=None):
        return self.defineLoss(labels, preact, mask=mask)


class SameDiffVertex(GraphVertex):
    """Multi-input user-defined vertex for ComputationGraph (≡
    samediff.SameDiffVertex). Carries parameters via the graph's
    parameterized-vertex plumbing (same as AttentionVertex).

    Subclasses implement:
      - defineParameters() -> {name: shape}
      - defineVertex(params, *inputs, mask=None) -> output
      - getOutputType(*input_types) -> InputType
    """

    def __init__(self, name=None, weightInit="xavier"):
        self.name = name
        self.weightInit = weightInit
        self.updater = None

    def defineParameters(self):
        return {}

    def defineVertex(self, params, *inputs, mask=None):
        raise NotImplementedError(
            f"{type(self).__name__} must implement defineVertex")

    def getOutputType(self, *input_types):
        return input_types[0]

    def initializeParameters(self, key, name, shape):
        if len(shape) >= 2:
            return init_weight(key, shape, self.weightInit, None)
        return jnp.zeros(shape, jnp.float32)

    # framework bridge (parameterized-vertex protocol)
    def output_type(self, *ts):
        self._input_types = ts
        return self.getOutputType(*ts)

    def initialize(self, key, *ts):
        shapes = self.defineParameters()
        params = {}
        for name in sorted(shapes):
            key, sub = jax.random.split(key)
            params[name] = self.initializeParameters(
                sub, name, tuple(int(d) for d in shapes[name]))
        return params, {}

    def apply(self, *xs, params=None, mask=None):
        return self.defineVertex(params or {}, *xs, mask=mask)
