"""Graph vertices (≡ deeplearning4j-nn :: conf.graph.*: MergeVertex,
ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex, ScaleVertex,
ShiftVertex, L2NormalizeVertex, PreprocessorVertex, ReshapeVertex,
rnn.LastTimeStepVertex). Pure functions over one-or-more parent
activations."""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType,
                                               FeedForwardType, InputType,
                                               RecurrentType)


class GraphVertex:
    def output_type(self, *input_types):
        raise NotImplementedError

    def apply(self, *xs, mask=None):
        raise NotImplementedError

    def feed_forward_mask(self, *parent_masks):
        """Mask seen downstream of this vertex (≡ feedForwardMaskArray):
        default passes the first non-None parent mask through; vertices
        that drop or re-key the time axis override."""
        return next((m for m in parent_masks if m is not None), None)


class MergeVertex(GraphVertex):
    """Concat along the feature (last) axis."""

    def output_type(self, *ts):
        t0 = ts[0]
        if isinstance(t0, ConvolutionalType):
            return InputType.convolutional(t0.height, t0.width,
                                           sum(t.channels for t in ts))
        if isinstance(t0, RecurrentType):
            return InputType.recurrent(sum(t.size for t in ts),
                                       t0.timeSeriesLength)
        return InputType.feedForward(sum(t.size for t in ts))

    def apply(self, *xs, mask=None):
        return jnp.concatenate(xs, axis=-1)


class ElementWiseVertex(GraphVertex):
    Add, Subtract, Product, Average, Max, Min = (
        "add", "subtract", "product", "average", "max", "min")

    def __init__(self, op="add"):
        self.op = str(op).lower()

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        if self.op == "add":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if self.op == "subtract":
            assert len(xs) == 2
            return xs[0] - xs[1]
        if self.op == "product":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if self.op == "average":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out / len(xs)
        if self.op == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if self.op == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        raise ValueError(f"Unknown ElementWiseVertex op {self.op}")


class SubsetVertex(GraphVertex):
    def __init__(self, frm, to):
        self.frm, self.to = int(frm), int(to)  # inclusive, per reference

    def output_type(self, *ts):
        n = self.to - self.frm + 1
        t = ts[0]
        if isinstance(t, RecurrentType):
            return InputType.recurrent(n, t.timeSeriesLength)
        return InputType.feedForward(n)

    def apply(self, *xs, mask=None):
        return xs[0][..., self.frm:self.to + 1]


class StackVertex(GraphVertex):
    """Stack along batch dim (≡ StackVertex: concat examples)."""

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        return jnp.concatenate(xs, axis=0)


class UnstackVertex(GraphVertex):
    def __init__(self, frm, stackSize):
        self.frm, self.stackSize = int(frm), int(stackSize)

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        x = xs[0]
        step = x.shape[0] // self.stackSize
        return x[self.frm * step:(self.frm + 1) * step]


class ScaleVertex(GraphVertex):
    def __init__(self, scaleFactor):
        self.scale = float(scaleFactor)

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        return xs[0] * self.scale


class ShiftVertex(GraphVertex):
    def __init__(self, shiftFactor):
        self.shift = float(shiftFactor)

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        return xs[0] + self.shift


class L2NormalizeVertex(GraphVertex):
    def __init__(self, eps=1e-8):
        self.eps = float(eps)

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        x = xs[0]
        n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + self.eps)
        return x / n


class PreprocessorVertex(GraphVertex):
    def __init__(self, preprocessor):
        self.pp = preprocessor

    def output_type(self, *ts):
        return self.pp.getOutputType(ts[0])

    def apply(self, *xs, mask=None):
        return self.pp.preProcess(xs[0])


class ReshapeVertex(GraphVertex):
    def __init__(self, *shape):
        self.shape = tuple(int(s) for s in
                           (shape[0] if len(shape) == 1 and
                            isinstance(shape[0], (tuple, list)) else shape))

    def output_type(self, *ts):
        if len(self.shape) == 2:
            return InputType.feedForward(self.shape[-1])
        if len(self.shape) == 4:
            return InputType.convolutional(*self.shape[1:])
        return ts[0]

    def apply(self, *xs, mask=None):
        return xs[0].reshape(self.shape)


class LastTimeStepVertex(GraphVertex):
    """≡ rnn.LastTimeStepVertex — (B,T,F) -> (B,F), mask-aware."""

    def __init__(self, maskArrayInputName=None):
        self.maskName = maskArrayInputName

    def feed_forward_mask(self, *parent_masks):
        return None  # emits a single (feed-forward) step

    def output_type(self, *ts):
        return InputType.feedForward(ts[0].size)

    def apply(self, *xs, mask=None):
        x = xs[0]
        if mask is None:
            return x[:, -1, :]
        idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]


class SpaceToDepthVertex(GraphVertex):
    """≡ conf.layers.SpaceToDepthLayer as a vertex (YOLOv2 'reorg'
    passthrough): (B, H, W, C) → (B, H/b, W/b, C·b²)."""

    def __init__(self, blockSize=2):
        self.blockSize = int(blockSize)

    def output_type(self, *ts):
        t = ts[0]
        b = self.blockSize
        return InputType.convolutional(t.height // b, t.width // b,
                                       t.channels * b * b)

    def apply(self, *xs, mask=None):
        x = xs[0]
        n, h, w, c = x.shape
        b = self.blockSize
        x = x.reshape(n, h // b, b, w // b, b, c)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(
            n, h // b, w // b, c * b * b)


class DuplicateToTimeSeriesVertex(GraphVertex):
    """≡ rnn.DuplicateToTimeSeriesVertex — broadcast a (B, F) feed-forward
    activation across time: (B, F) + reference (B, T, F') → (B, T, F).
    The reference names the graph input whose length to copy; here the
    time-series whose T is duplicated-to is wired as the SECOND parent."""

    def __init__(self, referenceInputName=None):
        self.referenceInputName = referenceInputName

    def output_type(self, *ts):
        ff, seq = ts[0], ts[1]
        return InputType.recurrent(ff.size,
                                   getattr(seq, "timeSeriesLength", None))

    def feed_forward_mask(self, *parent_masks):
        # time axis comes from the SECOND (reference sequence) parent
        return parent_masks[1] if len(parent_masks) > 1 else None

    def apply(self, *xs, mask=None):
        ff, seq = xs[0], xs[1]
        t = seq.shape[1]
        return jnp.broadcast_to(ff[:, None, :], (ff.shape[0], t, ff.shape[1]))


class ReverseTimeSeriesVertex(GraphVertex):
    """≡ rnn.ReverseTimeSeriesVertex — reverse the time axis. Mask-aware:
    each example reverses within its own valid length L (out[t] = x[L-1-t]
    for t < L, zeros after), matching the reference's per-example
    reversal rather than a naive flip that would move padding to the
    front."""

    def __init__(self, maskArrayInputName=None):
        self.maskName = maskArrayInputName

    def output_type(self, *ts):
        return ts[0]

    def apply(self, *xs, mask=None):
        x = xs[0]
        t = x.shape[1]
        if mask is None:
            return jnp.flip(x, axis=1)
        lengths = jnp.sum(mask > 0, axis=1).astype(jnp.int32)   # (B,)
        pos = jnp.arange(t)[None, :]                            # (1, T)
        src = jnp.clip(lengths[:, None] - 1 - pos, 0, t - 1)    # (B, T)
        y = jnp.take_along_axis(x, src[:, :, None], axis=1)
        return jnp.where((pos < lengths[:, None])[:, :, None], y, 0)


class L2Vertex(GraphVertex):
    """≡ conf.graph.L2Vertex — pairwise Euclidean distance between two
    parents: (B, ...) × (B, ...) → (B, 1) (siamese-network head)."""

    def __init__(self, eps=1e-8):
        self.eps = float(eps)

    def output_type(self, *ts):
        return InputType.feedForward(1)

    def feed_forward_mask(self, *parent_masks):
        return None  # scalar distance per example, no time axis

    def apply(self, *xs, mask=None):
        a, b = xs[0], xs[1]
        d = (a - b).reshape(a.shape[0], -1)
        sq = jnp.sum(d.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        return jnp.sqrt(sq + self.eps).astype(a.dtype)


class FrozenVertex(GraphVertex):
    """≡ conf.graph.FrozenVertex — wraps any vertex and blocks gradient
    flow into its parameters (stop_gradient on the params; activations
    still differentiate through to upstream layers, matching the
    reference's frozen-during-transfer-learning semantics)."""

    def __init__(self, vertex=None):
        if vertex is None:
            raise ValueError("FrozenVertex requires a wrapped vertex")
        self.inner = vertex

    def output_type(self, *ts):
        return self.inner.output_type(*ts)

    # parameterized-vertex protocol passthrough (only when inner has params)
    def __getattr__(self, name):
        if name == "initialize" and hasattr(self.inner, "initialize"):
            return self.inner.initialize
        raise AttributeError(name)

    def apply(self, *xs, params=None, mask=None):
        import jax
        if hasattr(self.inner, "initialize"):
            frozen = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                            params or {})
            return self.inner.apply(*xs, params=frozen, mask=mask)
        return self.inner.apply(*xs, mask=mask)
