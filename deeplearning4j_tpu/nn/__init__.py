from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.builders import (BackpropType,
                                                 NeuralNetConfiguration,
                                                 MultiLayerConfiguration,
                                                 WorkspaceMode)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, Convolution1DLayer, ConvolutionLayer,
    Cropping2D, DenseLayer, DepthwiseConvolution2D, DropoutLayer,
    EmbeddingLayer, EmbeddingSequenceLayer,
    GlobalPoolingLayer, LossLayer, OutputLayer, PReLULayer,
    SeparableConvolution2D, Subsampling1DLayer, SubsamplingLayer,
    TimeDistributed, Upsampling1D, Upsampling2D, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.special_layers import (
    AutoEncoder, CenterLossOutputLayer, LocallyConnected1D,
    LocallyConnected2D, VariationalAutoencoder)
from deeplearning4j_tpu.nn.dropout import (AlphaDropout, Dropout,
                                           GaussianDropout, GaussianNoise,
                                           SpatialDropout)
from deeplearning4j_tpu.nn.constraints import (MaxNormConstraint,
                                               MinMaxNormConstraint,
                                               NonNegativeConstraint,
                                               UnitNormConstraint)
from deeplearning4j_tpu.nn.losses import (LossBinaryXENT, LossFunction,
                                          LossMCXENT, LossMSE,
                                          LossNegativeLogLikelihood)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import (AMSGrad, AdaDelta, AdaGrad,
                                            AdaMax, Adam, GradientNormalization,
                                            Nadam, Nesterovs, NoOp, RmsProp,
                                            Sgd, Updater)
from deeplearning4j_tpu.nn.weights_init import WeightInit

__all__ = [
    "Activation", "BackpropType", "NeuralNetConfiguration",
    "MultiLayerConfiguration", "WorkspaceMode", "InputType", "layers",
    "ActivationLayer", "BatchNormalization", "Convolution1DLayer",
    "ConvolutionLayer", "Cropping2D", "DenseLayer",
    "DepthwiseConvolution2D", "DropoutLayer", "EmbeddingLayer",
    "EmbeddingSequenceLayer", "GlobalPoolingLayer", "LossLayer",
    "OutputLayer", "PReLULayer", "SeparableConvolution2D",
    "Subsampling1DLayer", "SubsamplingLayer", "TimeDistributed",
    "Upsampling1D", "Upsampling2D",
    "ZeroPaddingLayer", "AutoEncoder", "CenterLossOutputLayer",
    "LocallyConnected1D",
    "LocallyConnected2D", "AlphaDropout", "Dropout", "GaussianDropout",
    "GaussianNoise", "SpatialDropout",
    "VariationalAutoencoder", "LossBinaryXENT", "LossMCXENT", "LossMSE",
    "LossNegativeLogLikelihood",
    "LossFunction", "MultiLayerNetwork", "AMSGrad",
    "AdaDelta", "AdaGrad", "AdaMax", "Adam", "GradientNormalization",
    "Nadam", "Nesterovs", "NoOp", "RmsProp", "Sgd", "Updater", "WeightInit",
    "MaxNormConstraint", "MinMaxNormConstraint", "NonNegativeConstraint",
    "UnitNormConstraint",
]
