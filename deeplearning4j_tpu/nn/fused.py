"""Conv1x1 + BatchNorm fusion pass for ComputationGraph.

The reference reaches fused conv+BN through cuDNN helper classes
(deeplearning4j-cuda :: CudnnConvolutionHelper /
CudnnBatchNormalizationHelper chosen per-layer at runtime). The TPU-native
equivalent is a graph-level rewrite: a 1x1 convolution feeding only a
BatchNormalization is executed as ONE fused Pallas op
(kernels/pointwise_conv.fused_conv1x1_bn) — the conv becomes a GEMM with a
BN-stats epilogue, and BN's closed-form backward is reconstructed inside
the conv-gradient GEMMs instead of materializing the intermediate
gradient (see kernels/pointwise_conv.py for the pass accounting).

The rewrite is *execution-only*: node names, parameter trees, state
trees, serialization, transfer learning and constraints are all
unchanged — `mark_conv1x1_bn_fusions` just annotates node pairs, and the
graph executor routes the pair through `fused_apply` at train time.

OFF by default (opt in with DL4J_TPU_FUSE_CONV_BN=1): measured on the
v5e ResNet-50 headline bench the fused step is SLOWER (179 ms vs 99 ms,
BENCH.md "negative result") — Pallas custom-calls are fusion barriers,
so the BN-apply/relu passes XLA used to merge with neighbours become
standalone, and the row-major GEMM operands force relayout copies
against XLA's batch-minor conv layouts. The kernels stay correct,
tested, and available for graphs where XLA's fusion does worse.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _eval_epilogue(xf, w, a, b, act, interpret):
    """act((xf @ w)·a + b) through the epilogue-fused Pallas kernel
    (kernels/pointwise_conv.matmul_epilogue), with a closed-form VJP:
    the kernel itself has no differentiation rule, but eval-mode
    forwards still get differentiated (input saliency, adversarial
    probes), so the backward recomputes the pre-affine GEMM and emits
    the standard affine/relu chain — grads to gamma/beta flow through
    the fold arithmetic outside this function."""
    from deeplearning4j_tpu.kernels.pointwise_conv import matmul_epilogue
    return matmul_epilogue(xf, w, a, b, act=act, interpret=interpret)


def _eval_epilogue_fwd(xf, w, a, b, act, interpret):
    z = _eval_epilogue(xf, w, a, b, act, interpret)
    return z, (xf, w, a, z)


def _eval_epilogue_bwd(act, interpret, res, dz):
    xf, w, a, z = res
    dzf = dz.astype(jnp.float32)
    if act == "relu":
        dzf = jnp.where(z > 0, dzf, 0.0)
    dy = dzf * a                                   # z = y·a + b
    wf = w.astype(jnp.float32)
    dx = (dy @ wf.T).astype(xf.dtype)
    y = jnp.dot(xf.astype(jnp.float32), wf)        # recompute, not stored
    dw = (xf.astype(jnp.float32).T @ dy).astype(w.dtype)
    da = jnp.sum(dzf * y, axis=0).astype(a.dtype)
    db = jnp.sum(dzf, axis=0).astype(a.dtype)
    return dx, dw, da, db


_eval_epilogue.defvjp(_eval_epilogue_fwd, _eval_epilogue_bwd)


def fusion_enabled():
    env = os.environ.get("DL4J_TPU_FUSE_CONV_BN")
    if env is None:
        return False
    return env.strip().lower() in ("1", "true", "on", "yes")


def _eligible_conv(layer):
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
    if type(layer) is not ConvolutionLayer:
        return False
    # explicit nonzero padding would change the output shape of a 1x1
    # conv; the GEMM path only covers pad-free geometry ("same" for k=1
    # is also pad-free)
    pad_free = (str(layer.convolutionMode).lower() == "same"
                or tuple(layer.padding) == (0, 0))
    return (tuple(layer.kernelSize) == (1, 1)
            and tuple(layer.dilation) == (1, 1)
            and layer.stride[0] == layer.stride[1]
            and pad_free
            and not layer.hasBias
            and str(layer.activation).lower() in ("identity", "linear")
            and getattr(layer, "spaceToDepth", 1) == 1
            and not getattr(layer, "frozen", False)
            and not getattr(layer, "frozen_params", False)
            and getattr(layer, "weightNoise", None) is None
            and (layer.dropOut is None or layer.dropOut >= 1.0))


def _eligible_bn(layer):
    from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
    return (type(layer) is BatchNormalization
            and str(layer.activation).lower() in ("identity", "linear",
                                                  "relu")
            and not layer.lockGammaBeta
            and not getattr(layer, "frozen", False)
            and (layer.dropOut is None or layer.dropOut >= 1.0))


def find_conv1x1_bn_fusions(conf):
    """Find eligible (conv1x1 -> batchnorm) node pairs in a built
    ComputationGraphConfiguration.

    Returns {bn_node_name: conv_node_name}. Pure query — the caller
    (ComputationGraph.init) keeps the mapping on the *network instance*,
    never on the shared conf, so two nets built from one conf can run
    fused and unfused independently."""
    nodes = conf.nodes
    consumers = conf.consumers()
    pairs = {}
    for name in conf.topo_order:
        conv = nodes[name]
        if conv.kind != "layer" or not _eligible_conv(conv.ref):
            continue
        outs = consumers.get(name, [])
        if len(outs) != 1 or name in conf.output_names:
            continue
        bn_name = outs[0]
        bn = nodes[bn_name]
        if (bn.kind != "layer" or not _eligible_bn(bn.ref)
                or bn.preprocessor is not None
                or bn_name in conf.output_names
                or len(bn.inputs) != 1):
            continue
        pairs[bn_name] = name
    return pairs


def fused_apply(conv_layer, bn_layer, p_conv, p_bn, s_bn, x, train,
                interpret=None):
    """Execute act(batchnorm(conv1x1(x))) fused. x: (B, H, W, C) NHWC.

    Returns (z, new_bn_state, y_conv) with semantics identical to running
    conv_layer.apply then bn_layer.apply in train/eval mode; y_conv is
    the intermediate conv output (already materialized by the kernel —
    the graph records it so feedForward() still reports the conv node's
    true activation)."""
    s = conv_layer.stride[0]
    if s > 1:
        # 1x1 conv with stride s touches exactly the (::s, ::s) pixels
        x = x[:, ::s, ::s, :]
    b, h, w_, cin = x.shape
    w = p_conv["W"].astype(x.dtype).reshape(cin, -1)
    n = w.shape[1]
    xf = x.reshape(b * h * w_, cin)
    if train:
        from deeplearning4j_tpu.kernels.pointwise_conv import (
            fused_conv1x1_bn, matmul_stats)
        gamma = p_bn.get("gamma")
        beta = p_bn.get("beta")
        act = str(bn_layer.activation).lower()
        act = "identity" if act in ("identity", "linear") else act
        z, mu, var = fused_conv1x1_bn(xf, w, gamma, beta, bn_layer.eps,
                                      act, interpret)
        d = bn_layer.decay
        new_state = {"mean": d * s_bn["mean"] + (1 - d) * mu,
                     "var": d * s_bn["var"] + (1 - d) * var}
        # conv activation for feedForward reporting: recompute lazily —
        # XLA DCEs this whole branch unless someone actually reads it
        y = jnp.dot(xf, w, preferred_element_type=jnp.float32).astype(
            x.dtype)
    elif isinstance(xf, jax.core.Tracer):
        # jitted inference (serving/eval executables): BN is a
        # per-channel affine of the RUNNING stats — fold it (plus the
        # relu) into the GEMM's epilogue so the conv output tile is
        # normalized while still in VMEM instead of in a standalone
        # BN-apply pass (the shape BENCH.md round 3 concluded is the
        # only fusion that wins). _eval_epilogue carries a custom VJP
        # (recompute-based closed form), so autodiff THROUGH an eval
        # forward (input saliency etc.) keeps working. The
        # reporting-only y below is DCE'd by XLA unless something
        # actually reads it.
        gamma = p_bn.get("gamma", jnp.ones_like(s_bn["mean"]))
        beta = p_bn.get("beta", jnp.zeros_like(s_bn["mean"]))
        inv = jax.lax.rsqrt(s_bn["var"] + bn_layer.eps)
        act = str(bn_layer.activation).lower()
        act = "identity" if act in ("identity", "linear") else act
        z = _eval_epilogue(xf, w, gamma * inv,
                           beta - gamma * s_bn["mean"] * inv,
                           act, interpret)
        new_state = s_bn
        y = jnp.dot(xf, w, preferred_element_type=jnp.float32).astype(
            x.dtype)
    else:
        # eager inference: nothing DCEs an unread tensor here, so a
        # separate epilogue kernel would make the conv GEMM run twice
        # (once for z, once for the reported y) — one GEMM + the
        # standalone BN apply is strictly cheaper op-by-op
        y = jnp.dot(xf, w, preferred_element_type=jnp.float32).astype(
            x.dtype)
        z, new_state = bn_layer.apply(p_bn, s_bn, y, train=False)
    return (z.reshape(b, h, w_, n), new_state,
            y.reshape(b, h, w_, n))
