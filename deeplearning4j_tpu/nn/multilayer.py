"""MultiLayerNetwork (≡ deeplearning4j-nn :: multilayer.MultiLayerNetwork).

The reference drives fit() through a Solver that executes ops one-by-one on
the CUDA executioner with cuDNN helper hand-offs; here the WHOLE training
step — forward, loss (+ L1/L2), backward, gradient normalization, updater —
traces into ONE jitted XLA executable with donated param/optimizer buffers,
which is the TPU-native equivalent of the reference's workspace reuse +
fused helper path. Inputs are cast to the configured compute dtype
(`dataType`, e.g. bfloat16 for MXU) while parameters stay float32 masters.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import profiler as _prof
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience import watchdog as _watchdog
from deeplearning4j_tpu.runtime import pipeline as _pipeline
from deeplearning4j_tpu.util.crash_reporting import \
    with_crash_dump
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn import accum as _accum
from deeplearning4j_tpu.nn.updaters import Updater, build_optimizer, same_updater
from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax, resolve_dtype


def _l1l2_penalty(layer_confs, params):
    """≡ reference score regularization: l1*sum|W| + 0.5*l2*||W||² on weight
    tensors (biases/beta/gamma excluded, matching the reference)."""
    total = 0.0
    for i, layer in enumerate(layer_confs):
        l1, l2 = layer.regularization_terms()
        if not l1 and not l2:
            continue
        p = params.get(str(i), {})
        for name, v in p.items():
            if name in ("b", "beta", "gamma", "alpha", "centers"):
                continue
            v = v.astype(jnp.float32)
            if l1:
                total += l1 * jnp.sum(jnp.abs(v))
            if l2:
                total += 0.5 * l2 * jnp.sum(v * v)
    return total


def _hook_params(layer, p, ltrain, lrng):
    """Per-layer param transforms shared by BOTH network classes' forward
    loops (MultiLayerNetwork and ComputationGraph must never diverge):
    - frozen_params (≡ FrozenLayerWithBackprop): params are constants to
      the grad; train-mode behavior and upstream gradients kept.
    - weightNoise (WeightNoise/DropConnect): weight-space noise as a pure
      function of the step rng — stays inside the jitted step. The 0x57
      fold_in tag keeps the noise stream distinct from the layer's
      dropout stream (which uses lrng directly)."""
    if getattr(layer, "frozen_params", False):
        p = jax.tree_util.tree_map(jax.lax.stop_gradient, p)
    wn = getattr(layer, "weightNoise", None)
    if wn is not None and ltrain and lrng is not None:
        p = wn.apply_to_params(p, jax.random.fold_in(lrng, 0x57))
    return p


def _apply_layer(layer, p, s, x, ltrain, lrng, mask):
    """Run one layer, honouring its `remat` flag: remat=True wraps the
    train-mode apply in jax.checkpoint so activations inside the layer are
    recomputed during backward instead of stored — the DSL-level knob for
    trading FLOPs against HBM on deep/long-sequence models (any layer
    config accepts remat=True / .remat(True); ≡ the role of the
    reference's workspace memory modes, but as a per-layer rematerialization
    policy the XLA way)."""
    if ltrain and getattr(layer, "remat", False):
        def inner(p_, s_, x_, r_, m_):
            return layer.apply(p_, s_, x_, train=True, rng=r_, mask=m_)
        return jax.checkpoint(inner)(p, s, x, lrng, mask)
    return layer.apply(p, s, x, train=ltrain, rng=lrng, mask=mask)


class MultiLayerNetwork:
    def __init__(self, conf):
        self.conf = conf
        self.layers = conf.layers
        self._params = None
        self._state = None
        self._opt_state = None
        self._tx = None
        self._listeners = []
        self._score = None
        self._iteration = 0
        self._epoch = 0
        self._compute_dtype = resolve_dtype(conf.data_type) or jnp.float32
        self._rng_key = jax.random.PRNGKey(conf.seed)

    # -- lifecycle -------------------------------------------------------
    def init(self, params=None):
        if self.conf.input_type is None:
            raise ValueError("setInputType(...) (or explicit nIn on every "
                             "layer) is required before init()")
        key = jax.random.PRNGKey(self.conf.seed)
        ps, ss = {}, {}
        cur = self.conf.input_type
        from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalFlatType
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        if isinstance(cur, ConvolutionalFlatType):
            cur = InputType.feedForward(cur.arrayElementsPerExample())
        for i, layer in enumerate(self.layers):
            in_type = self.conf.input_types[i]
            key, sub = jax.random.split(key)
            p, s, cur = layer.initialize(sub, in_type)
            if p:
                ps[str(i)] = p
            if s:
                ss[str(i)] = s
        self._params = ps
        self._state = ss
        if params is not None:
            self.setParams(params)
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        defaults = self.conf.defaults
        global_updater = defaults.get("updater")
        overrides = {str(i): l.updater for i, l in enumerate(self.layers)
                     if l.updater is not None
                     and not same_updater(l.updater, global_updater)}
        gn = defaults.get("gradientNormalization")
        gn_thr = defaults.get("gradientNormalizationThreshold", 1.0)
        wd = defaults.get("weightDecay", 0.0) or 0.0
        if not overrides:
            self._tx = build_optimizer(global_updater, gn, gn_thr, wd)
        else:
            transforms = {"__global__": build_optimizer(global_updater, gn, gn_thr, wd)}
            for k, u in overrides.items():
                transforms[k] = build_optimizer(u, gn, gn_thr, wd)
            labels = {k: (k if k in overrides else "__global__")
                      for k in self._params}
            self._tx = optax.multi_transform(transforms, labels)
        self._opt_state = self._tx.init(self._params)

    # -- parameter surface (≡ Model.params()/numParams/paramTable) ------
    def paramTable(self):
        flat = {}
        for li, p in (self._params or {}).items():
            for name, v in p.items():
                flat[f"{li}_{name}"] = NDArray(v)
        return flat

    def params(self):
        leaves = jax.tree_util.tree_leaves(
            {k: self._params[k] for k in sorted(self._params, key=int)})
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate([l.ravel() for l in leaves]))

    def numParams(self):
        return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(self._params))

    def setParams(self, flat):
        flat = as_jax(flat).ravel()
        ordered = {k: self._params[k] for k in sorted(self._params, key=int)}
        leaves, treedef = jax.tree_util.tree_flatten(ordered)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        rebuilt = jax.tree_util.tree_unflatten(treedef, out)
        self._params = {k: rebuilt[k] for k in self._params}
        return self

    def getParam(self, key):
        li, name = key.split("_", 1)
        return NDArray(self._params[li][name])

    def setParam(self, key, value):
        li, name = key.split("_", 1)
        self._params[li][name] = as_jax(value).astype(self._params[li][name].dtype)

    # -- forward ---------------------------------------------------------
    def _forward(self, params, state, x, train, rng, mask=None,
                 collect=False, stop_at=None, carries=None):
        """carries: optional {layer_idx: carry} for TBPTT / rnnTimeStep —
        recurrent layers are then driven via scan_apply so hidden state
        threads across calls (≡ the reference's rnnActivateUsingStoredState)."""
        x = x.astype(self._compute_dtype)
        acts = []
        new_state = dict(state)
        new_carries = {} if carries is not None else None
        preact = None
        n = len(self.layers) if stop_at is None else stop_at
        for i, layer in enumerate(self.layers[:n]):
            # frozen layers (transfer learning) always run inference-mode:
            # no dropout, batch-norm running stats pinned (≡ FrozenLayer)
            ltrain = train and not getattr(layer, "frozen", False)
            pp = self.conf.preprocessors.get(i)
            if pp is not None:
                x = pp.preProcess(x)
            lrng = None
            if rng is not None:
                lrng = jax.random.fold_in(rng, i)
            p = _hook_params(layer, params.get(str(i), {}), ltrain, lrng)
            s = state.get(str(i), {})
            if i == len(self.layers) - 1 and hasattr(layer, "compute_loss") \
                    and hasattr(layer, "pre_activation"):
                xd = layer._dropout_in(x, ltrain, lrng)
                if getattr(layer, "pre_activation_takes_mask", False):
                    # custom loss heads (SameDiffOutputLayer) keep the
                    # defineLayer(params, x, mask) contract
                    preact = layer.pre_activation(p, xd, mask=mask)
                else:
                    preact = layer.pre_activation(p, xd)
                from deeplearning4j_tpu.nn.activations import get_activation
                x = get_activation(layer.activation)(preact)
            elif carries is not None and getattr(layer, "is_recurrent", False):
                if not hasattr(layer, "scan_apply"):
                    raise ValueError(
                        f"rnnTimeStep/tbptt: {type(layer).__name__} (layer "
                        f"{i}) cannot run step-by-step (no carried state "
                        "protocol); use fit/output on whole sequences")
                x = layer._dropout_in(x, ltrain, lrng)
                x, carry = layer.scan_apply(p, x, carries.get(str(i)), mask)
                new_carries[str(i)] = carry
            else:
                x, ns = _apply_layer(layer, p, s, x, ltrain, lrng, mask)
                if ns:
                    new_state[str(i)] = ns
            if mask is not None:
                # layers that reshape/drop the time axis transform the mask
                # for everything downstream (≡ feedForwardMaskArray)
                mask = layer.feed_forward_mask(mask)
            if collect:
                acts.append(x)
        if carries is not None:
            return x, preact, new_state, acts, new_carries
        return x, preact, new_state, acts

    @with_crash_dump
    def output(self, x, train=False, fmask=None):
        x = as_jax(x)
        fmask = None if fmask is None else as_jax(fmask)
        y, _, _, _ = self._forward(self._params, self._state, x, train, None,
                                   mask=fmask)
        return NDArray(y)

    def getOutputLayer(self):
        """≡ MultiLayerNetwork.getOutputLayer — the last layer's conf
        object (e.g. a Yolo2OutputLayer for detection post-processing)."""
        return self.layers[-1]

    def getPredictedObjects(self, x, confThreshold=0.5, nmsThreshold=0.4):
        """Detection convenience (≡ YoloUtils.getPredictedObjects over
        this net's output): forward + decode + threshold + per-class NMS.
        Returns List[List[DetectedObject]], one inner list per example.
        Requires the output layer to be a Yolo2OutputLayer."""
        out_layer = self.layers[-1]
        if not hasattr(out_layer, "getPredictedObjects"):
            raise TypeError(
                f"output layer {type(out_layer).__name__} has no detection "
                "decode — getPredictedObjects needs a Yolo2OutputLayer head")
        y = self.output(x)
        return out_layer.getPredictedObjects(as_jax(y), confThreshold,
                                             nmsThreshold)

    def predict(self, x):
        """≡ Classifier.predict — argmax class index per example."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if isinstance(x, DataSet):
            x = x.features
        out = self.output(x).numpy()
        return np.argmax(out, axis=-1)

    def f1Score(self, data, labels=None):
        """≡ Classifier.f1Score(DataSet | (examples, labels)) —
        macro-averaged F1 (Evaluation.f1()'s default) over one forward
        pass."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        mask = None
        if isinstance(data, DataSet):
            feats, labels = data.features, data.labels
            mask = data.labelsMask
        else:
            feats = data
        ev = Evaluation()
        ev.eval(labels, self.output(feats).numpy(), mask)
        return ev.f1()

    def feedForward(self, x, train=False):
        x = as_jax(x)
        _, _, _, acts = self._forward(self._params, self._state, x, train,
                                      None, collect=True)
        return [NDArray(a) for a in acts]

    def activateSelectedLayers(self, from_idx, to_idx, x):
        """Apply layers [from_idx, to_idx] inclusive to activations `x`
        (which must already be layer from_idx's input)."""
        x = as_jax(x).astype(self._compute_dtype)
        for i in range(int(from_idx), int(to_idx) + 1):
            layer = self.layers[i]
            pp = self.conf.preprocessors.get(i)
            if pp is not None:
                x = pp.preProcess(x)
            x, _ = layer.apply(self._params.get(str(i), {}),
                               self._state.get(str(i), {}), x, train=False)
        return NDArray(x)

    # -- stateful RNN inference (≡ rnnTimeStep/rnnClearPreviousState) ----
    def rnnTimeStep(self, x):
        x = as_jax(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]  # (B, F) -> (B, 1, F)
        if not hasattr(self, "_rnn_carries") or self._rnn_carries is None:
            self._rnn_carries = {}
        y, _, _, _, self._rnn_carries = self._forward(
            self._params, self._state, x, False, None,
            carries=self._rnn_carries)
        return NDArray(y[:, -1, :] if squeeze and y.ndim == 3 else y)

    def rnnClearPreviousState(self):
        self._rnn_carries = None

    def rnnGetPreviousState(self, layer_idx):
        return (self._rnn_carries or {}).get(str(layer_idx))

    # -- loss / gradients -------------------------------------------------
    def _loss(self, params, state, x, y, fmask, lmask, rng, carries=None,
              train=True):
        out_layer = self.layers[-1]
        if not hasattr(out_layer, "compute_loss"):
            raise ValueError("Last layer must be an OutputLayer/LossLayer to fit()")
        needs_feats = getattr(out_layer, "needs_features", False)
        if needs_feats and carries is not None:
            raise ValueError(
                f"{type(out_layer).__name__} (feature-dependent loss) is "
                "not supported with truncated BPTT")
        if carries is not None:
            _, preact, new_state, _, new_carries = self._forward(
                params, state, x, train, rng, mask=fmask, carries=carries)
        else:
            _, preact, new_state, acts = self._forward(
                params, state, x, train, rng, mask=fmask,
                collect=needs_feats)
            new_carries = None
        if needs_feats and carries is None:
            feats = acts[-2] if len(acts) >= 2 else x.astype(
                self._compute_dtype)
            pp = self.conf.preprocessors.get(len(self.layers) - 1)
            if pp is not None:
                feats = pp.preProcess(feats)
            data_loss = out_layer.compute_loss_with_features(
                params.get(str(len(self.layers) - 1), {}),
                y.astype(jnp.float32), preact.astype(jnp.float32),
                feats.astype(jnp.float32), lmask)
        else:
            data_loss = out_layer.compute_loss(y.astype(jnp.float32),
                                               preact.astype(jnp.float32),
                                               lmask)
        return (data_loss + _l1l2_penalty(self.layers, params),
                (new_state, new_carries))

    def score(self, dataset=None):
        if dataset is not None:
            x, y = as_jax(dataset.features), as_jax(dataset.labels)
            fmask = None if dataset.featuresMask is None else as_jax(dataset.featuresMask)
            lmask = None if dataset.labelsMask is None else as_jax(dataset.labelsMask)
            # inference-mode forward (BN running stats, no dropout) —
            # matches the reference's score(DataSet) semantics
            loss, _ = self._loss(self._params, self._state, x, y, fmask,
                                 lmask, None, train=False)
            return float(loss)
        # lazy score: fit() leaves the DEVICE loss scalar in _score so a
        # listener-free loop never blocks; reading it here is the
        # on-demand sync point (counted via dl4j.pipeline.syncs)
        return _pipeline.materialize_score(self)

    def computeGradients(self, x, y, fmask=None, lmask=None):
        """Gradients of the full regularized loss — used by gradient-check
        tests (≡ deeplearning4j-core GradientCheckUtil)."""
        x, y = as_jax(x), as_jax(y)
        grads, _ = jax.grad(
            lambda p: self._loss(p, self._state, x, y, fmask, lmask, None),
            has_aux=True)(self._params)
        return grads

    # -- training ---------------------------------------------------------
    def _apply_constraints(self, params):
        """Post-update parameter constraints (≡ BaseConstraint application
        after the updater step) — folded into the jitted step; free when no
        layer declares constraints (static config, checked at trace)."""
        pairs = [(str(i), l) for i, l in enumerate(self.layers)]
        if not any(getattr(l, "constraints", None) for _, l in pairs):
            return params
        from deeplearning4j_tpu.nn.constraints import apply_layer_constraints
        return apply_layer_constraints(pairs, params)

    @functools.cached_property
    def _train_step(self):
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, x, y, fmask, lmask, rng):
            (loss, (new_state, _)), grads = jax.value_and_grad(
                lambda p: self._loss(p, state, x, y, fmask, lmask, rng),
                has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, new_state, loss

        return step

    @functools.cached_property
    def _train_step_guarded(self):
        """The guardian's variant of `_train_step`: the SAME update plus
        a device-side health verdict — global grad norm finite, loss
        finite, grad norm under the guardian's EMA-derived threshold —
        and the update is APPLIED ONLY WHEN HEALTHY (`jnp.where`
        select inside the same donated program), so one overflowing
        step can never write NaN into the live params. `lr_scale`
        (traced scalar — no recompile when the guardian backs off the
        LR) multiplies the updates for the reduce-LR escalation rung.
        Compiled only when a guardian is installed; the unguarded path
        is untouched."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, x, y, fmask, lmask, rng,
                 lr_scale, max_gnorm):
            (loss, (new_state, _)), grads = jax.value_and_grad(
                lambda p: self._loss(p, state, x, y, fmask, lmask, rng),
                has_aux=True)(params)
            params, opt_state, (state,), gnorm, ok = \
                _guardian.guarded_apply(
                    tx, grads, loss, params, opt_state, lr_scale,
                    max_gnorm, constraints=self._apply_constraints,
                    extra=((new_state, state),))
            return params, opt_state, state, loss, gnorm, ok

        return step

    @functools.cached_property
    def _train_scan(self):
        """K train steps in ONE dispatch: lax.scan over stacked batches.

        TPU-first replacement for the reference's per-batch fit loop
        (MultiLayerNetwork.fit → one Solver step per DataSet): on a
        tunnelled/remote chip each dispatch costs ~10 ms of host round-trip,
        which dominates sub-20 ms steps (measured, BENCH.md round 4). The
        scan body is the SAME update as _train_step, consuming one stacked
        batch slice and one pre-split rng per iteration, so k scanned steps
        are bit-identical to k sequential _train_step calls."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def scan_steps(params, opt_state, state, xs, ys, fmasks, lmasks,
                       rngs):
            def body(carry, inp):
                p, o, s = carry
                x, y, fm, lm, rng = inp
                (loss, (ns, _)), grads = jax.value_and_grad(
                    lambda pp: self._loss(pp, s, x, y, fm, lm, rng),
                    has_aux=True)(p)
                updates, o = tx.update(grads, o, p)
                p = optax.apply_updates(p, updates)
                p = self._apply_constraints(p)
                return (p, o, ns), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (xs, ys, fmasks, lmasks, rngs))
            return params, opt_state, state, losses

        return scan_steps

    def _fit_batches_scanned(self, group):
        """Flush a same-shape batch group through ONE scanned dispatch.
        Callers only send FULL groups here (sub-k remainders run singly)
        so lax.scan is traced for exactly one length per batch shape."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"multilayer@{id(self):x}")
        _ps = _prof.ACTIVE             # armed ProfileSession: the whole
        if _ps is not None:            # scanned dispatch is one "step"
            _ps.step_start()
        with _mon.span("train.stage"):
            subs = []
            for _ in group:   # identical key stream to seq _fit_batch
                self._rng_key, sub = jax.random.split(self._rng_key)
                subs.append(sub)
            xs = jnp.stack([jnp.asarray(f) for f, _, _, _ in group])
            ys = jnp.stack([jnp.asarray(l) for _, l, _, _ in group])
            lms = (None if group[0][2] is None
                   else jnp.stack([jnp.asarray(m)
                                   for _, _, m, _ in group]))
            fms = (None if group[0][3] is None
                   else jnp.stack([jnp.asarray(m)
                                   for _, _, _, m in group]))
        with _mon.span("train.scan_dispatch"):
            (self._params, self._opt_state, self._state,
             losses) = self._train_scan(self._params, self._opt_state,
                                        self._state, xs, ys, fms, lms,
                                        jnp.stack(subs))
        self._last_features = group[-1][0]
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            if self._listeners:
                # device slices, not device_get: listeners that never
                # read score() cost zero syncs; ones that do pay only
                # for the iterations they actually look at
                for i in range(len(group)):
                    self._score = losses[i]
                    self._iteration += 1
                    for listener in self._listeners:
                        listener.iterationDone(self, self._iteration,
                                               self._epoch)
            else:
                self._score = losses[len(group) - 1]
                self._iteration += len(group)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    # -- in-step gradient accumulation (ISSUE 14): G microbatches ->
    # ONE optimizer step in ONE dispatch. Unlike _train_scan (k separate
    # updates), the scan body only accumulates gradients; the single
    # update runs after the scan — so a G-microbatch step equals an
    # on-device sequential sum-then-update reference, and the effective
    # batch is G× the per-dispatch memory footprint.
    @functools.cached_property
    def _train_step_accum(self):
        """Accumulated step: `nn/accum.accum_scan` over G stacked
        microbatches (grads/loss summed on device, BN state threaded
        sequentially), then ONE updater application."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, xs, ys, fmasks, lmasks, rngs):
            grads, loss, _, state = _accum.accum_scan(
                self._accum_grad_fn, params, state,
                (xs, ys, fmasks, lmasks, rngs))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, state, loss

        return step

    def _accum_grad_fn(self, params, state, inp):
        """One microbatch's ((loss, new_state), grads) for accum_scan
        (drops the per-layer activations aux the plain step keeps)."""
        x, y, fm, lm, rng = inp
        (loss, (ns, _)), grads = jax.value_and_grad(
            lambda p: self._loss(p, state, x, y, fm, lm, rng),
            has_aux=True)(params)
        return (loss, ns), grads

    @functools.cached_property
    def _train_step_accum_guarded(self):
        """Guardian variant of `_train_step_accum`: ONE device health
        verdict gates the ACCUMULATED update (params, optimizer state
        and bn state all revert when unhealthy), while a NaN in any
        single microbatch still fails it — per-microbatch loss
        finiteness is ANDed through the scan and poisons the loss the
        verdict inspects (non-finite grads also survive the on-device
        sum into the accumulated gnorm). Unlike stepsPerDispatch (which
        the guardian forces to 1: a scan group hides k-1 verdicts),
        accumulation IS one optimizer step — one verdict is exactly the
        per-update cadence the guardian needs."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, xs, ys, fmasks, lmasks, rngs,
                 lr_scale, max_gnorm):
            grads, loss, micro_ok, new_state = _accum.accum_scan(
                self._accum_grad_fn, params, state,
                (xs, ys, fmasks, lmasks, rngs))
            vloss = jnp.where(micro_ok, loss, jnp.float32(jnp.nan))
            params, opt_state, (state,), gnorm, ok = \
                _guardian.guarded_apply(
                    tx, grads, vloss, params, opt_state, lr_scale,
                    max_gnorm, constraints=self._apply_constraints,
                    extra=((new_state, state),))
            return params, opt_state, state, loss, gnorm, ok

        return step

    def _fit_batches_accum(self, group):
        """Flush a FULL G-batch group through one accumulated optimizer
        step. One REAL update: iteration count and listeners advance
        once (the group is one step of the G×-effective batch), score
        is the mean microbatch loss (device scalar, lazy)."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"multilayer@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        with _mon.span("train.stage"):
            subs = []
            for _ in group:   # one split per microbatch, like the scan
                self._rng_key, sub = jax.random.split(self._rng_key)
                subs.append(sub)
            xs = jnp.stack([jnp.asarray(f) for f, _, _, _ in group])
            ys = jnp.stack([jnp.asarray(l) for _, l, _, _ in group])
            lms = (None if group[0][2] is None
                   else jnp.stack([jnp.asarray(m)
                                   for _, _, m, _ in group]))
            fms = (None if group[0][3] is None
                   else jnp.stack([jnp.asarray(m)
                                   for _, _, _, m in group]))
        _g = _guardian.ACTIVE
        with _mon.span("train.accum_dispatch"):
            if _g is not None:
                (self._params, self._opt_state, self._state, loss,
                 gnorm, ok) = self._train_step_accum_guarded(
                    self._params, self._opt_state, self._state, xs, ys,
                    fms, lms, jnp.stack(subs), _g.lr_scale,
                    _g.max_gnorm)
            else:
                (self._params, self._opt_state, self._state,
                 loss) = self._train_step_accum(
                    self._params, self._opt_state, self._state, xs, ys,
                    fms, lms, jnp.stack(subs))
            self._score = loss    # device scalar; score() floats it
        if _g is not None:
            _g.on_step(loss, gnorm, ok)   # one verdict per real update
        self._iteration += 1
        self._last_features = group[-1][0]
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in self._listeners:
                listener.iterationDone(self, self._iteration, self._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    @staticmethod
    def _batch_sig(ds):
        def sig(a):
            return None if a is None else tuple(np.shape(a))
        return (sig(ds.features), sig(ds.labels), sig(ds.labelsMask),
                sig(ds.featuresMask))

    @functools.cached_property
    def _train_step_tbptt(self):
        """TBPTT segment step: gradients truncate at segment boundaries,
        hidden state (carries) threads across segments
        (≡ BackpropType.TruncatedBPTT in the reference)."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, carries, x, y, fmask, lmask, rng):
            def lossf(p):
                loss, (new_state, new_carries) = self._loss(
                    p, state, x, y, fmask, lmask, rng, carries=carries)
                return loss, (new_state, new_carries)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            # stop state flowing gradients across segments
            new_carries = jax.lax.stop_gradient(new_carries)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, new_state, new_carries, loss

        return step

    @functools.cached_property
    def _train_step_tbptt_guarded(self):
        """Guardian variant of `_train_step_tbptt`: the same segment
        update plus the device-side health verdict, applied only when
        healthy — params, optimizer state, bn state AND the recurrent
        carries (a NaN forward pass must not poison the hidden state
        that threads into the next segment). Segments report
        `on_step(retryable=False)`: earlier healthy segments of the same
        batch already updated params, so the RETRY rung must never
        re-run the whole batch."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, carries, x, y, fmask, lmask,
                 rng, lr_scale, max_gnorm):
            def lossf(p):
                loss, (new_state, new_carries) = self._loss(
                    p, state, x, y, fmask, lmask, rng, carries=carries)
                return loss, (new_state, new_carries)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            # stop state flowing gradients across segments
            new_carries = jax.lax.stop_gradient(new_carries)
            params, opt_state, (state, carries), gnorm, ok = \
                _guardian.guarded_apply(
                    tx, grads, loss, params, opt_state, lr_scale,
                    max_gnorm, constraints=self._apply_constraints,
                    extra=((new_state, state), (new_carries, carries)))
            return params, opt_state, state, carries, loss, gnorm, ok

        return step

    def _zero_carries(self, batch):
        carries = {}
        for i, layer in enumerate(self.layers):
            if getattr(layer, "is_recurrent", False) and hasattr(layer, "zero_carry"):
                carries[str(i)] = layer.zero_carry(batch, self._compute_dtype)
        return carries

    def _fit_batch(self, features, labels, labels_mask=None,
                   features_mask=None):
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"multilayer@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        # "train.stage": host-side step prep (device placement of the
        # batch + rng split) — its own attribution phase so the flight
        # recorder's per-step sum tracks wall time (steps.SUM_PHASES)
        with _mon.span("train.stage"):
            x = jnp.asarray(features)
            y = jnp.asarray(labels)
            lmask = None if labels_mask is None \
                else jnp.asarray(labels_mask)
            fmask = None if features_mask is None \
                else jnp.asarray(features_mask)
            self._rng_key, sub = jax.random.split(self._rng_key)
        from deeplearning4j_tpu.nn.conf.builders import BackpropType
        if (self.conf.backprop_type == BackpropType.TruncatedBPTT
                and x.ndim == 3 and x.shape[1] > self.conf.tbptt_fwd_length):
            tlen = int(self.conf.tbptt_fwd_length)
            carries = self._zero_carries(x.shape[0])
            total = None    # loss accumulates ON DEVICE: the old
            nseg = 0        # per-segment float() blocked every segment
            _g = _guardian.ACTIVE
            with _mon.span("train.dispatch"):
                for t0 in range(0, x.shape[1], tlen):
                    xs = x[:, t0:t0 + tlen]
                    ys = y[:, t0:t0 + tlen] if y.ndim == 3 else y
                    fs = None if fmask is None else fmask[:, t0:t0 + tlen]
                    ls = None if lmask is None else lmask[:, t0:t0 + tlen]
                    if _g is not None:
                        (self._params, self._opt_state, self._state,
                         carries, loss, gnorm, ok) = \
                            self._train_step_tbptt_guarded(
                                self._params, self._opt_state, self._state,
                                carries, xs, ys, fs, ls,
                                jax.random.fold_in(sub, t0),
                                _g.lr_scale, _g.max_gnorm)
                        # retryable=False: the batch's earlier healthy
                        # segments already updated params
                        _g.on_step(loss, gnorm, ok, retryable=False)
                    else:
                        (self._params, self._opt_state, self._state,
                         carries, loss) = self._train_step_tbptt(
                            self._params, self._opt_state, self._state,
                            carries, xs, ys, fs, ls,
                            jax.random.fold_in(sub, t0))
                    total = loss if total is None else total + loss
                    nseg += 1
            self._score = None if total is None else total / nseg
        else:
            _g = _guardian.ACTIVE
            with _mon.span("train.dispatch"):
                if _g is not None:
                    (self._params, self._opt_state, self._state, loss,
                     gnorm, ok) = self._train_step_guarded(
                        self._params, self._opt_state, self._state, x, y,
                        fmask, lmask, sub, _g.lr_scale, _g.max_gnorm)
                else:
                    self._params, self._opt_state, self._state, loss = \
                        self._train_step(
                            self._params, self._opt_state, self._state,
                            x, y, fmask, lmask, sub)
                self._score = loss    # device scalar; score() floats it
            if _g is not None:
                # device scalars only — the guardian materializes them
                # in one stacked read at its check cadence
                _g.on_step(loss, gnorm, ok)
        self._iteration += 1
        # most recent training batch, for listeners that inspect
        # activations (StatsListener histograms — ≡ the reference
        # dashboard's activation charts over the last minibatch);
        # _params_version counts REAL updates (the scanned path fires k
        # listener calls per single update)
        self._last_features = x
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in self._listeners:
                listener.iterationDone(self, self._iteration, self._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    # -- layerwise unsupervised pretraining (≡ MultiLayerNetwork.pretrain
    # / pretrainLayer: VAE ELBO, historically RBM contrastive divergence) -
    def pretrainLayer(self, layer_idx, data, epochs=1):
        """Unsupervised-train one layer (must define pretrain_loss) on the
        activations feeding it; one jitted step over that layer's params."""
        layer = self.layers[int(layer_idx)]
        if not hasattr(layer, "pretrain_loss"):
            return self  # ≡ reference: non-pretrainable layers are skipped
        key = str(layer_idx)
        tx = build_optimizer(
            layer.updater or self.conf.defaults.get("updater"),
            self.conf.defaults.get("gradientNormalization"),
            self.conf.defaults.get("gradientNormalizationThreshold", 1.0),
            self.conf.defaults.get("weightDecay", 0.0) or 0.0)
        opt_state = tx.init(self._params[key])

        @jax.jit
        def step(p, opt, x, rng):
            loss, grads = jax.value_and_grad(layer.pretrain_loss)(p, x, rng)
            updates, opt = tx.update(grads, opt, p)
            return optax.apply_updates(p, updates), opt, loss

        def batches():
            if hasattr(data, "reset"):
                data.reset()
                for ds in data:
                    yield as_jax(ds.features)
            else:
                yield as_jax(data.features if isinstance(data, DataSet)
                             else data)

        p = self._params[key]
        for _ in range(int(epochs)):
            for feats in batches():
                if layer_idx > 0:
                    feats = self.activateSelectedLayers(
                        0, layer_idx - 1, feats).jax()
                pp = self.conf.preprocessors.get(int(layer_idx))
                if pp is not None:
                    feats = pp.preProcess(feats)
                self._rng_key, sub = jax.random.split(self._rng_key)
                p, opt_state, loss = step(p, opt_state, feats, sub)
                self._score = loss    # lazy; score() floats on demand
        self._params[key] = p
        self._build_optimizer()  # opt state shapes unchanged but refresh
        return self

    def pretrain(self, data, epochs=1):
        """≡ reference pretrain(iterator): layerwise over all layers that
        support unsupervised pretraining."""
        for i in range(len(self.layers)):
            self.pretrainLayer(i, data, epochs)
        return self

    @with_crash_dump
    def fit(self, data, labels=None, epochs=None, stepsPerDispatch=1,
            prefetch=None):
        """stepsPerDispatch > 1 (iterator form only): group consecutive
        same-shape batches and run each group as ONE lax.scan dispatch —
        numerically identical to the sequential loop (tested), but pays
        the host→device round-trip once per group instead of per batch.
        Groups flush early on a shape change, so ragged tails stay exact.
        TBPTT configs ignore it (the segment loop owns the dispatch).

        `.gradientAccumulation(G)` on the conf (iterator form): every G
        consecutive same-shape batches become ONE accumulated optimizer
        step in one dispatch (scan sums grads, single update) — the
        G×-effective-batch path; takes precedence over stepsPerDispatch
        and composes with an installed guardian (one verdict per real
        update). Sub-G remainders run as ordinary per-batch steps.

        prefetch (iterator form, async-supporting iterators): staging
        queue depth for the background device-staging prefetcher — batch
        N+1 is pulled, preprocessed, and copied into XLA-owned device
        buffers while step N computes. Default
        runtime.pipeline.DEFAULT_PREFETCH (2); 0 disables. Combined with
        the lazy score (no per-step float(loss)) a listener-free fit
        performs ZERO host-blocking syncs — see README 'Host pipeline &
        async dispatch'."""
        if self._params is None:
            self.init()
        if labels is not None:  # fit(features, labels)
            try:
                with _mon.span("fit"):
                    self._fit_batch(as_jax(data), as_jax(labels))
            finally:           # retire even on a raise: a FAILED fit is
                #                not a wedged one (see iterator path)
                if _watchdog.ACTIVE is not None:
                    _watchdog.ACTIVE.retire(f"multilayer@{id(self):x}")
            return self
        if isinstance(data, DataSet):
            try:
                with _mon.span("fit"):
                    self._fit_batch(data.features, data.labels,
                                    data.labelsMask, data.featuresMask)
            finally:
                if _watchdog.ACTIVE is not None:
                    _watchdog.ACTIVE.retire(f"multilayer@{id(self):x}")
            return self
        # iterator
        from deeplearning4j_tpu.nn.conf.builders import BackpropType
        accum = int(self.conf.defaults.get("gradientAccumulation", 1)
                    or 1)
        k = max(1, int(stepsPerDispatch))
        if self.conf.backprop_type == BackpropType.TruncatedBPTT:
            k, accum = 1, 1   # the segment loop owns the dispatch
        if accum > 1:
            # accumulation groups G batches into ONE optimizer step —
            # it owns the grouping; stepsPerDispatch (k separate
            # updates per dispatch) does not compose with it
            k = accum
        elif _guardian.ACTIVE is not None:
            k = 1    # guardian needs per-step health verdicts; a scan
            #          group would hide k-1 of them inside one dispatch
            #          (an ACCUMULATED group is one update with one
            #          verdict, so accum > 1 stays on)
        n_epochs = int(epochs) if epochs is not None else 1

        def flush(group):
            if len(group) == k and accum > 1:
                self._fit_batches_accum(group)
            elif len(group) == k:
                self._fit_batches_scanned(group)
            else:        # sub-k remainder: avoid a fresh per-length trace
                for f, l, lm, fm in group:
                    self._fit_batch(f, l, lm, fm)

        it, _pf = _pipeline.maybe_prefetch(data, prefetch)
        try:
            for _ in range(n_epochs):
                with _mon.span("fit.epoch"):
                    if hasattr(it, "reset"):
                        it.reset()
                    group, group_sig = [], None
                    for ds in _mon.traced_iter(it):
                        if _faults.ACTIVE is not None:
                            _faults.ACTIVE.fire(_faults.DATA_NEXT)
                        if k == 1:
                            self._fit_batch(ds.features, ds.labels,
                                            ds.labelsMask, ds.featuresMask)
                            continue
                        sig = self._batch_sig(ds)
                        if group and (sig != group_sig or len(group) >= k):
                            flush(group)
                            group = []
                        group_sig = sig
                        group.append((ds.features, ds.labels,
                                      ds.labelsMask, ds.featuresMask))
                    if group:
                        flush(group)
                    self._epoch += 1
                    with _mon.span("fit.epoch_listeners"):
                        for listener in self._listeners:
                            if hasattr(listener, "onEpochEnd"):
                                listener.onEpochEnd(self)
        finally:
            # the fit ended (or raised): this trainer's heartbeat is no
            # longer stall evidence — an armed watchdog must not age it
            # into a false trip while other trainers keep running
            if _watchdog.ACTIVE is not None:
                _watchdog.ACTIVE.retire(f"multilayer@{id(self):x}")
            if _pf is not None:
                _pf.close()
        return self

    # -- evaluation -------------------------------------------------------
    def evaluate(self, iterator, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation()
        self._eval_loop(iterator, e, prefetch=prefetch)
        return e

    def evaluateROC(self, iterator, threshold_steps=0, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import ROC
        roc = ROC(threshold_steps)
        self._eval_loop(iterator, roc, prefetch=prefetch)
        return roc

    def evaluateRegression(self, iterator, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import RegressionEvaluation
        e = RegressionEvaluation()
        self._eval_loop(iterator, e, prefetch=prefetch)
        return e

    def evaluateROCMultiClass(self, iterator, threshold_steps=0,
                              prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import ROCMultiClass
        roc = ROCMultiClass(threshold_steps)
        self._eval_loop(iterator, roc, prefetch=prefetch)
        return roc

    def evaluateCalibration(self, iterator, reliabilityDiagNumBins=10,
                            histogramNumBins=10, prefetch=None):
        """≡ MultiLayerNetwork.evaluateCalibration → EvaluationCalibration."""
        from deeplearning4j_tpu.eval.evaluation import EvaluationCalibration
        e = EvaluationCalibration(reliabilityDiagNumBins, histogramNumBins)
        self._eval_loop(iterator, e, prefetch=prefetch)
        return e

    def _eval_loop(self, iterator, evaluator, prefetch=None):
        # eval overlaps too: a background stage pulls + device-stages
        # batch N+1's features while batch N's forward pass runs
        # (labels stay host-side — the evaluator reads them there);
        # prefetch=0 forces fully synchronous eval (mirrors fit())
        it, _pf = _pipeline.maybe_prefetch(
            iterator, prefetch, stage=_pipeline.stage_for_eval)
        try:
            if hasattr(it, "reset"):
                it.reset()
            for ds in _mon.traced_iter(it, "eval.data_next"):
                if _faults.ACTIVE is not None:
                    _faults.ACTIVE.fire(_faults.EVAL_FORWARD)
                with _mon.span("eval.batch"):
                    out = self.output(ds.features, fmask=ds.featuresMask)
                    evaluator.eval(ds.labels, out.numpy(),
                                   mask=ds.labelsMask)
        finally:
            if _pf is not None:
                _pf.close()

    # -- listeners --------------------------------------------------------
    def setListeners(self, *listeners):
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = listeners[0]
        self._listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self._listeners.extend(listeners)
        return self

    def getListeners(self):
        return list(self._listeners)

    # -- misc parity ------------------------------------------------------
    def getnLayers(self):
        return len(self.layers)

    def getLayer(self, idx):
        return self.layers[idx]

    def getEpochCount(self):
        return self._epoch

    def getIterationCount(self):
        return self._iteration

    def summary(self):
        lines = ["=" * 72,
                 f"{'Idx':<4}{'Layer':<28}{'Out':<22}{'nParams':>10}", "-" * 72]
        total = 0
        for i, l in enumerate(self.layers):
            p = self._params.get(str(i), {}) if self._params else {}
            n = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(p))
            total += n
            out = self.conf.input_types[i]
            out_str = str(l.output_type(out).shape()) if out is not None else "?"
            lines.append(f"{i:<4}{type(l).__name__:<28}{out_str:<22}{n:>10,}")
        lines += ["-" * 72, f"Total params: {total:,}", "=" * 72]
        return "\n".join(lines)

    def clone(self):
        import copy
        m = MultiLayerNetwork(self.conf)
        if self._params is not None:
            # materialize real copies: the live net's jitted train step
            # DONATES its param buffers, which would delete shared arrays
            m._params = jax.tree_util.tree_map(jnp.copy, self._params)
            m._state = jax.tree_util.tree_map(jnp.copy, self._state)
            m._build_optimizer()
        return m

    def save(self, path, saveUpdater=True):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        ModelSerializer.writeModel(self, path, saveUpdater)

    @staticmethod
    def load(path, loadUpdater=True):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restoreMultiLayerNetwork(path, loadUpdater)
